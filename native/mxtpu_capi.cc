// mxtpu C ABI — predict API + error convention.
//
// Reference parity: include/mxnet/c_predict_api.h (MXPredCreate / MXPredSetInput /
// MXPredForward / MXPredGetOutputShape / MXPredGetOutput / MXPredFree, 250 LoC) and
// the API_BEGIN/API_END -> MXGetLastError error convention of src/c_api/
// c_api_common.h:38-47 + c_api_error.cc:28.
//
// TPU-native design: the compute path is JAX, so the stable C boundary embeds (or,
// when the host process already runs Python, attaches to) the CPython interpreter
// and drives mxtpu/capi_impl.py. The C side is pure marshalling: every entry point
// takes flat buffers, grabs the GIL, calls one Python method, and copies results
// out. Any language with a C FFI (the reference's Scala/R/C++/Perl binding role,
// SURVEY §2.6) can load this library and run inference from a symbol-JSON +
// params checkpoint without knowing Python exists.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 $(python3-config --includes) \
//   mxtpu_capi.cc -o libmxtpu_capi.so -L$LIBDIR -lpython3.X
// (mxtpu/capi.py does this on demand, like mxtpu/native.py does for the IO lib.)

#include <Python.h>

#include <cstdarg>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_CAPI_ABI_VERSION 1

extern "C" {
typedef void* PredictorHandle;

const char* MXGetLastError();
int MXCAPIGetVersion(int* out);
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredGetNumOutputs(PredictorHandle handle, uint32_t* out);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim);
int MXPredSetInput(PredictorHandle handle, const char* key, const float* data,
                   uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);
}

namespace {

// ---- error convention (c_api_common.h API_BEGIN/API_END parity) -------------
thread_local std::string g_last_error;

void set_error_from_python() {
  // must hold the GIL
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// ---- interpreter bootstrap ---------------------------------------------------
// Two modes: (a) host process already runs Python (ctypes in-process binding) —
// attach via PyGILState; (b) pure C/C++ host (the bindings story) — initialize
// the interpreter once, then release the GIL so every entry point can use the
// same PyGILState discipline regardless of mode.
std::once_flag g_init_once;
PyObject* g_impl_module = nullptr;  // mxtpu.capi_impl, owned forever
bool g_init_ok = false;
std::string g_bootstrap_error;  // shared across threads (set once, read-only after)

void bootstrap() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);          // no signal handlers: we are a library
    PyEval_SaveThread();         // drop the GIL acquired by initialization
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* mod = PyImport_ImportModule("mxtpu.capi_impl");
  if (mod == nullptr) {
    set_error_from_python();
    g_bootstrap_error =
        "cannot import mxtpu.capi_impl (is the repo on PYTHONPATH?): "
        + g_last_error;
  } else {
    g_impl_module = mod;  // keep the reference for the process lifetime
    g_init_ok = true;
  }
  PyGILState_Release(gil);
}

bool ensure_ready() {
  std::call_once(g_init_once, bootstrap);
  if (!g_init_ok)
    g_last_error = g_bootstrap_error;  // every failing caller's thread sees it
  return g_init_ok;
}

struct Pred {
  PyObject* obj;  // mxtpu.capi_impl.Predictor instance (owned)
  // backing store for MXPredGetOutputShape pointers (valid until next call on
  // the same handle / MXPredFree, same lifetime contract as the reference)
  std::vector<std::vector<uint32_t>> shapes;
};

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXCAPIGetVersion(int* out) {
  if (out == nullptr) {
    g_last_error = "MXCAPIGetVersion: null argument";
    return -1;
  }
  *out = MXTPU_CAPI_ABI_VERSION;
  return 0;
}

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out) {
  if (out == nullptr || symbol_json_str == nullptr) {
    g_last_error = "MXPredCreate: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* names = nullptr;
  PyObject* shapes = nullptr;
  PyObject* params = nullptr;
  PyObject* pobj = nullptr;
  do {
    names = PyList_New(num_input_nodes);
    shapes = PyList_New(num_input_nodes);
    if (names == nullptr || shapes == nullptr) break;
    bool fail = false;
    for (uint32_t i = 0; i < num_input_nodes; ++i) {
      PyObject* key = PyUnicode_FromString(input_keys[i]);
      if (key == nullptr) { fail = true; break; }
      PyList_SET_ITEM(names, i, key);
      uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
      PyObject* shp = PyTuple_New(hi - lo);
      if (shp == nullptr) { fail = true; break; }
      for (uint32_t j = lo; j < hi; ++j)
        PyTuple_SET_ITEM(shp, j - lo,
                         PyLong_FromUnsignedLong(input_shape_data[j]));
      PyList_SET_ITEM(shapes, i, shp);
    }
    if (fail) break;
    params = PyBytes_FromStringAndSize(
        static_cast<const char*>(param_bytes), param_size);
    if (params == nullptr) break;
    pobj = PyObject_CallMethod(g_impl_module, "create_predictor", "sOOOii",
                               symbol_json_str, params, names, shapes,
                               dev_type, dev_id);
    if (pobj == nullptr) {
      set_error_from_python();
      break;
    }
    Pred* p = new Pred{pobj, {}};
    pobj = nullptr;  // ownership moved into the handle
    *out = p;
    rc = 0;
  } while (false);
  if (rc != 0 && !PyErr_Occurred() && g_last_error.empty())
    g_last_error = "MXPredCreate: allocation failure";
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(names);
  Py_XDECREF(shapes);
  Py_XDECREF(params);
  Py_XDECREF(pobj);
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetNumOutputs(PredictorHandle handle, uint32_t* out) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || out == nullptr) {
    g_last_error = "MXPredGetNumOutputs: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* n = PyObject_GetAttrString(p->obj, "num_outputs");
  if (n == nullptr) {
    set_error_from_python();
  } else {
    *out = static_cast<uint32_t>(PyLong_AsUnsignedLong(n));
    Py_DECREF(n);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || shape_data == nullptr || shape_ndim == nullptr) {
    g_last_error = "MXPredGetOutputShape: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* shp = PyObject_CallMethod(p->obj, "output_shape", "I", index);
  if (shp == nullptr) {
    set_error_from_python();
  } else {
    Py_ssize_t nd = PyTuple_Size(shp);
    std::vector<uint32_t> dims(static_cast<size_t>(nd));
    for (Py_ssize_t i = 0; i < nd; ++i)
      dims[i] = static_cast<uint32_t>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i)));
    Py_DECREF(shp);
    if (p->shapes.size() <= index) p->shapes.resize(index + 1);
    p->shapes[index] = std::move(dims);
    *shape_data = p->shapes[index].data();
    *shape_ndim = static_cast<uint32_t>(p->shapes[index].size());
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(PredictorHandle handle, const char* key, const float* data,
                   uint32_t size) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || key == nullptr || data == nullptr) {
    g_last_error = "MXPredSetInput: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  if (buf != nullptr) {
    PyObject* r = PyObject_CallMethod(p->obj, "set_input", "sO", key, buf);
    Py_DECREF(buf);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      Py_DECREF(r);
      rc = 0;
    }
  } else {
    set_error_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(PredictorHandle handle) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr) {
    g_last_error = "MXPredForward: null handle";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "forward", nullptr);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                    uint32_t size) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || data == nullptr) {
    g_last_error = "MXPredGetOutput: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "get_output", "I", index);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    char* raw = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(r, &raw, &len) == 0) {
      Py_ssize_t want = static_cast<Py_ssize_t>(size) * sizeof(float);
      if (len != want) {
        g_last_error = "MXPredGetOutput: size mismatch (have " +
                       std::to_string(len / sizeof(float)) + " floats, caller asked " +
                       std::to_string(size) + ")";
      } else {
        std::memcpy(data, raw, static_cast<size_t>(len));
        rc = 0;
      }
    } else {
      set_error_from_python();
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredFree(PredictorHandle handle) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// training ABI — the imperative slice of the reference's c_api.h:
// MXNDArrayCreateEx (:119), MXImperativeInvokeEx (c_api_ndarray.cc:81),
// MXAutogradMarkVariables / MXAutogradBackwardEx (c_api_ndarray.cc:319-396),
// MXListAllOpNames. An NDArrayHandle IS the owned PyObject* of the framework
// NDArray; ops are addressed BY NAME (the registry replaces the reference's
// AtomicSymbolCreator handles — declared deviation, same capability). With
// the fused optimizer ops (sgd_update et al.) in the registry, a pure C
// client can run a full train loop: create/copy arrays, mark variables,
// record, invoke ops, backward, read grads, apply updates.
// ---------------------------------------------------------------------------

typedef void* NDArrayHandle;

namespace {

// shared result plumbing: call an impl-module function, return the PyObject*
PyObject* call_impl(const char* fn, const char* fmt, ...) {
  // caller must hold the GIL and have run ensure_ready()
  PyObject* callable = PyObject_GetAttrString(g_impl_module, fn);
  if (callable == nullptr) return nullptr;
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (args == nullptr) {
    Py_DECREF(callable);
    return nullptr;
  }
  if (!PyTuple_Check(args)) {  // single-arg format strings build a bare value
    PyObject* t = PyTuple_Pack(1, args);
    Py_DECREF(args);
    args = t;
    if (args == nullptr) {
      Py_DECREF(callable);
      return nullptr;
    }
  }
  PyObject* out = PyObject_CallObject(callable, args);
  Py_DECREF(args);
  Py_DECREF(callable);
  return out;
}

// MXListAllOpNames backing store (stable for the process lifetime, like the
// reference's per-process registries)
std::vector<std::string> g_op_names;
std::vector<const char*> g_op_name_ptrs;
std::mutex g_op_names_mu;

}  // namespace

extern "C" {

int MXNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dev_type,
                    int dev_id, int delay_alloc, int dtype,
                    NDArrayHandle* out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;  // placement is XLA's
  if (out == nullptr || (ndim > 0 && shape == nullptr)) {
    g_last_error = "MXNDArrayCreate: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* shp = PyTuple_New(ndim);
  if (shp != nullptr) {
    for (uint32_t i = 0; i < ndim; ++i)
      PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
    PyObject* arr = call_impl("nd_create", "(Oi)", shp, dtype);
    Py_DECREF(shp);
    if (arr == nullptr) {
      set_error_from_python();
    } else {
      *out = arr;  // ownership transfers to the handle
      rc = 0;
    }
  } else {
    set_error_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_DECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, uint32_t* out_ndim,
                      uint32_t* out_shape, uint32_t max_ndim) {
  if (handle == nullptr || out_ndim == nullptr) {
    g_last_error = "MXNDArrayGetShape: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* shp = call_impl("nd_shape", "(O)",
                            static_cast<PyObject*>(handle));
  if (shp == nullptr) {
    set_error_from_python();
  } else {
    Py_ssize_t nd = PyTuple_Size(shp);
    *out_ndim = static_cast<uint32_t>(nd);
    if (out_shape == nullptr) {
      rc = 0;                              // ndim-only query
    } else if (static_cast<uint32_t>(nd) > max_ndim) {
      g_last_error = "MXNDArrayGetShape: shape buffer too small (array has " +
                     std::to_string(nd) + " dims, caller provided " +
                     std::to_string(max_ndim) + ")";
    } else {
      for (Py_ssize_t i = 0; i < nd; ++i)
        out_shape[i] = static_cast<uint32_t>(
            PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i)));
      rc = 0;
    }
    Py_DECREF(shp);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out) {
  if (handle == nullptr || out == nullptr) {
    g_last_error = "MXNDArrayGetDType: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("nd_dtype_code", "(O)",
                          static_cast<PyObject*>(handle));
  if (r == nullptr) {
    set_error_from_python();
  } else {
    *out = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size_bytes) {
  if (handle == nullptr || data == nullptr) {
    g_last_error = "MXNDArraySyncCopyFromCPU: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(size_bytes));
  if (buf != nullptr) {
    PyObject* r = call_impl("nd_copy_from", "(OO)",
                            static_cast<PyObject*>(handle), buf);
    Py_DECREF(buf);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      Py_DECREF(r);
      rc = 0;
    }
  } else {
    set_error_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                           size_t size_bytes) {
  if (handle == nullptr || data == nullptr) {
    g_last_error = "MXNDArraySyncCopyToCPU: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("nd_copy_to", "(O)",
                          static_cast<PyObject*>(handle));
  if (r == nullptr) {
    set_error_from_python();
  } else {
    char* raw = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(r, &raw, &len) == 0) {
      if (static_cast<size_t>(len) != size_bytes) {
        g_last_error = "MXNDArraySyncCopyToCPU: size mismatch (array has " +
                       std::to_string(len) + " bytes, caller asked " +
                       std::to_string(size_bytes) + ")";
      } else {
        std::memcpy(data, raw, static_cast<size_t>(len));
        rc = 0;
      }
    } else {
      set_error_from_python();
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXImperativeInvokeByName(const char* op_name, int num_inputs,
                             NDArrayHandle* inputs, int* num_outputs,
                             NDArrayHandle* outputs, int max_outputs,
                             int num_params, const char** param_keys,
                             const char** param_vals) {
  if (op_name == nullptr || num_outputs == nullptr ||
      (num_inputs > 0 && inputs == nullptr) ||
      (num_params > 0 && (param_keys == nullptr || param_vals == nullptr))) {
    g_last_error = "MXImperativeInvokeByName: null argument";
    return -1;
  }
  if (outputs == nullptr) {
    // count-only queries would run the op and destroy its results (double
    // compute for the two-call pattern) — single-call convention here: pass
    // a buffer sized by the op's num_outputs (MXListAllOpNames +
    // ops.registry.describe expose it; few ops exceed 4)
    g_last_error = "MXImperativeInvokeByName: outputs buffer required "
                   "(single-call convention; size it from the op's "
                   "num_outputs)";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* ins = PyList_New(num_inputs);
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  if (ins != nullptr && keys != nullptr && vals != nullptr) {
    bool fail = false;
    for (int i = 0; i < num_inputs && !fail; ++i) {
      PyObject* o = static_cast<PyObject*>(inputs[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(ins, i, o);
    }
    for (int i = 0; i < num_params && !fail; ++i) {
      PyObject* k = PyUnicode_FromString(param_keys[i]);
      PyObject* v = PyUnicode_FromString(param_vals[i]);
      if (k == nullptr || v == nullptr) { Py_XDECREF(k); Py_XDECREF(v);
        fail = true; break; }
      PyList_SET_ITEM(keys, i, k);
      PyList_SET_ITEM(vals, i, v);
    }
    if (!fail) {
      PyObject* outs = call_impl("invoke_op", "(sOOO)", op_name, ins, keys,
                                 vals);
      if (outs == nullptr) {
        set_error_from_python();
      } else {
        Py_ssize_t n = PyList_Size(outs);
        *num_outputs = static_cast<int>(n);
        if (n <= max_outputs) {
          for (Py_ssize_t i = 0; i < n; ++i) {
            PyObject* o = PyList_GET_ITEM(outs, i);
            Py_INCREF(o);          // handle ownership for the caller
            outputs[i] = o;
          }
          rc = 0;
        } else {
          g_last_error = "MXImperativeInvokeByName: output buffer too small";
        }
        Py_DECREF(outs);
      }
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(ins);
  Py_XDECREF(keys);
  Py_XDECREF(vals);
  PyGILState_Release(gil);
  return rc;
}

int MXListAllOpNames(uint32_t* out_size, const char*** out_array) {
  if (out_size == nullptr || out_array == nullptr) {
    g_last_error = "MXListAllOpNames: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  std::lock_guard<std::mutex> lock(g_op_names_mu);
  if (g_op_names.empty()) {
    PyGILState_STATE gil = PyGILState_Ensure();
    PyObject* names = call_impl("list_op_names", "()");
    if (names == nullptr) {
      set_error_from_python();
      PyGILState_Release(gil);
      return -1;
    }
    Py_ssize_t n = PyList_Size(names);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char* s = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
      if (s != nullptr) g_op_names.emplace_back(s);
    }
    Py_DECREF(names);
    PyGILState_Release(gil);
    g_op_name_ptrs.reserve(g_op_names.size());
    for (const auto& s : g_op_names) g_op_name_ptrs.push_back(s.c_str());
  }
  *out_size = static_cast<uint32_t>(g_op_name_ptrs.size());
  *out_array = g_op_name_ptrs.data();
  return 0;
}

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("autograd_set_recording", "(i)", is_recording);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("autograd_set_training", "(i)", is_training);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXAutogradMarkVariables(uint32_t num_var, NDArrayHandle* var_handles,
                            uint32_t* reqs_array) {
  if (num_var > 0 && (var_handles == nullptr || reqs_array == nullptr)) {
    g_last_error = "MXAutogradMarkVariables: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* vars = PyList_New(num_var);
  PyObject* reqs = PyList_New(num_var);
  if (vars != nullptr && reqs != nullptr) {
    for (uint32_t i = 0; i < num_var; ++i) {
      PyObject* o = static_cast<PyObject*>(var_handles[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(vars, i, o);
      PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(reqs_array[i]));
    }
    PyObject* r = call_impl("autograd_mark_variables", "(OO)", vars, reqs);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      Py_DECREF(r);
      rc = 0;
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(vars);
  Py_XDECREF(reqs);
  PyGILState_Release(gil);
  return rc;
}

int MXAutogradBackward(uint32_t num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* head_grad_handles, int retain_graph) {
  if (num_output > 0 && output_handles == nullptr) {
    g_last_error = "MXAutogradBackward: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* heads = PyList_New(num_output);
  PyObject* hgrads = head_grad_handles == nullptr
      ? PyList_New(0) : PyList_New(num_output);
  if (heads != nullptr && hgrads != nullptr) {
    for (uint32_t i = 0; i < num_output; ++i) {
      PyObject* o = static_cast<PyObject*>(output_handles[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(heads, i, o);
      if (head_grad_handles != nullptr) {
        PyObject* g = static_cast<PyObject*>(head_grad_handles[i]);
        Py_INCREF(g);
        PyList_SET_ITEM(hgrads, i, g);
      }
    }
    PyObject* r = call_impl("autograd_backward", "(OOi)", heads, hgrads,
                            retain_graph);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      Py_DECREF(r);
      rc = 0;
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(heads);
  Py_XDECREF(hgrads);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  if (handle == nullptr || out == nullptr) {
    g_last_error = "MXNDArrayGetGrad: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* g = call_impl("nd_get_grad", "(O)",
                          static_cast<PyObject*>(handle));
  if (g == nullptr) {
    set_error_from_python();
  } else {
    *out = g;  // ownership to caller
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

// ---------------------------------------------------------------------------
// KVStore surface — c_api.h MXKVStoreCreate (:1359) / Init / PushEx / PullEx /
// GetRank / GetGroupSize / Barrier / Free. A KVStoreHandle is the owned
// PyObject* of the framework KVStore; values are the SAME NDArray handles as
// the training ABI. MXKVStoreSetUpdater's C-callback is replaced by
// MXKVStoreSetOptimizer taking the restricted JSON spec
// {"name": ..., "kwargs": {...}} — the same format the dist_async parameter
// server accepts on its wire, so one spec drives local and server roles.
// ---------------------------------------------------------------------------

typedef void* KVStoreHandle;

namespace {

// shared helper: run impl fn(kv, [keys], [handles]) for init/push/pull
int kv_keys_vals(const char* fn, KVStoreHandle handle, uint32_t num,
                 const char** keys, NDArrayHandle* vals) {
  if (handle == nullptr || (num > 0 && (keys == nullptr || vals == nullptr))) {
    g_last_error = std::string(fn) + ": null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* ks = PyList_New(num);
  PyObject* vs = PyList_New(num);
  if (ks != nullptr && vs != nullptr) {
    bool fail = false;
    for (uint32_t i = 0; i < num && !fail; ++i) {
      PyObject* k = PyUnicode_FromString(keys[i]);
      if (k == nullptr) { fail = true; break; }
      PyList_SET_ITEM(ks, i, k);
      PyObject* v = static_cast<PyObject*>(vals[i]);
      Py_INCREF(v);
      PyList_SET_ITEM(vs, i, v);
    }
    if (!fail) {
      PyObject* r = call_impl(fn, "(OOO)",
                              static_cast<PyObject*>(handle), ks, vs);
      if (r == nullptr) {
        set_error_from_python();
      } else {
        Py_DECREF(r);
        rc = 0;
      }
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(ks);
  Py_XDECREF(vs);
  PyGILState_Release(gil);
  return rc;
}

int kv_get_int(const char* fn, KVStoreHandle handle, int* out) {
  if (handle == nullptr || out == nullptr) {
    g_last_error = std::string(fn) + ": null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl(fn, "(O)", static_cast<PyObject*>(handle));
  if (r == nullptr) {
    set_error_from_python();
  } else {
    long v = PyLong_AsLong(r);
    Py_DECREF(r);
    if (PyErr_Occurred()) {          // non-int return: report, don't leak
      set_error_from_python();
    } else {
      *out = static_cast<int>(v);
      rc = 0;
    }
  }
  PyGILState_Release(gil);
  return rc;
}

}  // namespace

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  if (type == nullptr || out == nullptr) {
    g_last_error = "MXKVStoreCreate: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* kv = call_impl("kv_create", "(s)", type);
  if (kv == nullptr) {
    set_error_from_python();
  } else {
    *out = kv;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXKVStoreFree(KVStoreHandle handle) {
  if (handle == nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_DECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXKVStoreInitEx(KVStoreHandle handle, uint32_t num, const char** keys,
                    NDArrayHandle* vals) {
  return kv_keys_vals("kv_init", handle, num, keys, vals);
}

int MXKVStorePushEx(KVStoreHandle handle, uint32_t num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  (void)priority;  // XLA owns scheduling
  return kv_keys_vals("kv_push", handle, num, keys, vals);
}

int MXKVStorePullEx(KVStoreHandle handle, uint32_t num, const char** keys,
                    NDArrayHandle* outs, int priority) {
  (void)priority;
  return kv_keys_vals("kv_pull", handle, num, keys, outs);
}

int MXKVStoreGetRank(KVStoreHandle handle, int* out) {
  return kv_get_int("kv_rank", handle, out);
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int* out) {
  return kv_get_int("kv_size", handle, out);
}

int MXKVStoreBarrier(KVStoreHandle handle) {
  if (handle == nullptr) {
    g_last_error = "MXKVStoreBarrier: null handle";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("kv_barrier", "(O)",
                          static_cast<PyObject*>(handle));
  if (r == nullptr) {
    set_error_from_python();
  } else {
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXKVStoreSetOptimizer(KVStoreHandle handle, const char* spec_json) {
  if (handle == nullptr || spec_json == nullptr) {
    g_last_error = "MXKVStoreSetOptimizer: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("kv_set_optimizer", "(Os)",
                          static_cast<PyObject*>(handle), spec_json);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Symbol ABI — the graph-composition slice of the reference's c_api.h
// (src/c_api/c_api_symbolic.cc: MXSymbolCreateAtomicSymbol :134,
// MXSymbolCreateVariable :161, MXSymbolCreateFromJSON, MXSymbolCompose :342,
// MXSymbolSaveToJSON, MXSymbolListArguments/Outputs/AuxiliaryStates,
// MXSymbolInferShape :466). A SymbolHandle is a capi_impl.SymbolBox PyObject:
// atomic descriptor after CreateAtomicSymbolByName, a real framework Symbol
// after Compose (in-place, reference protocol). Ops are addressed by NAME
// (same declared deviation as MXImperativeInvokeByName). String/shape return
// buffers are thread-local, valid until the next Symbol call on the thread —
// the reference's per-thread ret-store lifetime contract
// (c_api_common.h MXAPIThreadLocalEntry).
// ---------------------------------------------------------------------------

namespace {

thread_local std::string g_sym_json_ret;
thread_local std::vector<std::string> g_sym_strs;
thread_local std::vector<const char*> g_sym_str_ptrs;

// MXSymbolInferShape backing store
struct ShapeRet {
  std::vector<std::vector<uint32_t>> dims;   // flattened per-tensor shapes
  std::vector<uint32_t> ndims;
  std::vector<const uint32_t*> ptrs;
};
thread_local ShapeRet g_shape_ret[3];        // arg / out / aux

int fill_shape_group(PyObject* seq, ShapeRet* slot, uint32_t* size,
                     const uint32_t** ndim_out, const uint32_t*** data_out) {
  Py_ssize_t n = PyList_Size(seq);
  slot->dims.assign(static_cast<size_t>(n), {});
  slot->ndims.assign(static_cast<size_t>(n), 0);
  slot->ptrs.assign(static_cast<size_t>(n), nullptr);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* t = PyList_GET_ITEM(seq, i);
    Py_ssize_t nd = PyTuple_Size(t);
    slot->ndims[i] = static_cast<uint32_t>(nd);
    slot->dims[i].resize(static_cast<size_t>(nd));
    for (Py_ssize_t d = 0; d < nd; ++d)
      slot->dims[i][d] = static_cast<uint32_t>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(t, d)));
    slot->ptrs[i] = slot->dims[i].data();
  }
  *size = static_cast<uint32_t>(n);
  *ndim_out = slot->ndims.data();
  *data_out = slot->ptrs.data();
  return 0;
}

int sym_string_list(const char* fn, void* handle, uint32_t* out_size,
                    const char*** out_array) {
  if (handle == nullptr || out_size == nullptr || out_array == nullptr) {
    g_last_error = std::string(fn) + ": null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl(fn, "(O)", static_cast<PyObject*>(handle));
  if (r == nullptr) {
    set_error_from_python();
  } else {
    Py_ssize_t n = PyList_Size(r);
    g_sym_strs.clear();
    g_sym_str_ptrs.clear();
    bool ok = true;
    for (Py_ssize_t i = 0; i < n && ok; ++i) {
      const char* c = PyUnicode_AsUTF8(PyList_GET_ITEM(r, i));
      if (c == nullptr) {
        set_error_from_python();
        ok = false;
      } else {
        g_sym_strs.emplace_back(c);
      }
    }
    if (ok) {
      for (auto& s : g_sym_strs) g_sym_str_ptrs.push_back(s.c_str());
      *out_size = static_cast<uint32_t>(n);
      *out_array = g_sym_str_ptrs.data();
      rc = 0;
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

}  // namespace

extern "C" {

typedef void* SymbolHandle;

int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  if (name == nullptr || out == nullptr) {
    g_last_error = "MXSymbolCreateVariable: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("sym_create_variable", "(s)", name);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  if (json == nullptr || out == nullptr) {
    g_last_error = "MXSymbolCreateFromJSON: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("sym_create_from_json", "(s)", json);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCreateAtomicSymbolByName(const char* op_name, uint32_t num_param,
                                     const char** keys, const char** vals,
                                     SymbolHandle* out) {
  if (op_name == nullptr || out == nullptr ||
      (num_param > 0 && (keys == nullptr || vals == nullptr))) {
    g_last_error = "MXSymbolCreateAtomicSymbolByName: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* ks = PyList_New(num_param);
  PyObject* vs = PyList_New(num_param);
  if (ks != nullptr && vs != nullptr) {
    for (uint32_t i = 0; i < num_param; ++i) {
      PyList_SET_ITEM(ks, i, PyUnicode_FromString(keys[i]));
      PyList_SET_ITEM(vs, i, PyUnicode_FromString(vals[i]));
    }
    PyObject* r = call_impl("sym_create_atomic", "(sOO)", op_name, ks, vs);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      *out = r;
      rc = 0;
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(ks);
  Py_XDECREF(vs);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCompose(SymbolHandle sym, const char* name, uint32_t num_args,
                    const char** keys, SymbolHandle* args) {
  if (sym == nullptr || (num_args > 0 && args == nullptr)) {
    g_last_error = "MXSymbolCompose: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* ks = PyList_New(num_args);
  PyObject* ins = PyList_New(num_args);
  if (ks != nullptr && ins != nullptr) {
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SET_ITEM(ks, i, PyUnicode_FromString(
          keys != nullptr && keys[i] != nullptr ? keys[i] : ""));
      PyObject* o = static_cast<PyObject*>(args[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(ins, i, o);
    }
    PyObject* r = call_impl("sym_compose", "(OsOO)",
                            static_cast<PyObject*>(sym),
                            name != nullptr ? name : "", ks, ins);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      Py_DECREF(r);
      rc = 0;
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(ks);
  Py_XDECREF(ins);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  if (sym == nullptr || out_json == nullptr) {
    g_last_error = "MXSymbolSaveToJSON: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = call_impl("sym_tojson", "(O)", static_cast<PyObject*>(sym));
  if (r == nullptr) {
    set_error_from_python();
  } else {
    const char* c = PyUnicode_AsUTF8(r);
    if (c != nullptr) {
      g_sym_json_ret = c;
      *out_json = g_sym_json_ret.c_str();
      rc = 0;
    } else {
      set_error_from_python();
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolListArguments(SymbolHandle sym, uint32_t* out_size,
                          const char*** out_array) {
  return sym_string_list("sym_list_arguments", sym, out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle sym, uint32_t* out_size,
                        const char*** out_array) {
  return sym_string_list("sym_list_outputs", sym, out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle sym, uint32_t* out_size,
                                const char*** out_array) {
  return sym_string_list("sym_list_aux", sym, out_size, out_array);
}

int MXSymbolInferShape(SymbolHandle sym, uint32_t num_args, const char** keys,
                       const uint32_t* arg_ind_ptr,
                       const uint32_t* arg_shape_data,
                       uint32_t* in_shape_size, const uint32_t** in_shape_ndim,
                       const uint32_t*** in_shape_data,
                       uint32_t* out_shape_size,
                       const uint32_t** out_shape_ndim,
                       const uint32_t*** out_shape_data,
                       uint32_t* aux_shape_size,
                       const uint32_t** aux_shape_ndim,
                       const uint32_t*** aux_shape_data, int* complete) {
  if (sym == nullptr || complete == nullptr ||
      (num_args > 0 && (keys == nullptr || arg_ind_ptr == nullptr ||
                        arg_shape_data == nullptr))) {
    g_last_error = "MXSymbolInferShape: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* ks = PyList_New(num_args);
  PyObject* shps = PyList_New(num_args);
  if (ks != nullptr && shps != nullptr) {
    for (uint32_t i = 0; i < num_args; ++i) {
      PyList_SET_ITEM(ks, i, PyUnicode_FromString(keys[i]));
      uint32_t lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
      PyObject* t = PyTuple_New(hi - lo);
      for (uint32_t d = lo; d < hi; ++d)
        PyTuple_SET_ITEM(t, d - lo,
                         PyLong_FromUnsignedLong(arg_shape_data[d]));
      PyList_SET_ITEM(shps, i, t);
    }
    PyObject* r = call_impl("sym_infer_shape", "(OOO)",
                            static_cast<PyObject*>(sym), ks, shps);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      PyObject* arg_s = PyTuple_GET_ITEM(r, 0);
      PyObject* out_s = PyTuple_GET_ITEM(r, 1);
      PyObject* aux_s = PyTuple_GET_ITEM(r, 2);
      *complete = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 3)));
      fill_shape_group(arg_s, &g_shape_ret[0], in_shape_size, in_shape_ndim,
                       in_shape_data);
      fill_shape_group(out_s, &g_shape_ret[1], out_shape_size, out_shape_ndim,
                       out_shape_data);
      fill_shape_group(aux_s, &g_shape_ret[2], aux_shape_size, aux_shape_ndim,
                       aux_shape_data);
      Py_DECREF(r);
      rc = 0;
    }
  }
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(ks);
  Py_XDECREF(shps);
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolFree(SymbolHandle sym) {
  if (sym == nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_DECREF(static_cast<PyObject*>(sym));
  PyGILState_Release(gil);
  return 0;
}

}  // extern "C"
