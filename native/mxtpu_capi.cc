// mxtpu C ABI — predict API + error convention.
//
// Reference parity: include/mxnet/c_predict_api.h (MXPredCreate / MXPredSetInput /
// MXPredForward / MXPredGetOutputShape / MXPredGetOutput / MXPredFree, 250 LoC) and
// the API_BEGIN/API_END -> MXGetLastError error convention of src/c_api/
// c_api_common.h:38-47 + c_api_error.cc:28.
//
// TPU-native design: the compute path is JAX, so the stable C boundary embeds (or,
// when the host process already runs Python, attaches to) the CPython interpreter
// and drives mxtpu/capi_impl.py. The C side is pure marshalling: every entry point
// takes flat buffers, grabs the GIL, calls one Python method, and copies results
// out. Any language with a C FFI (the reference's Scala/R/C++/Perl binding role,
// SURVEY §2.6) can load this library and run inference from a symbol-JSON +
// params checkpoint without knowing Python exists.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 $(python3-config --includes) \
//   mxtpu_capi.cc -o libmxtpu_capi.so -L$LIBDIR -lpython3.X
// (mxtpu/capi.py does this on demand, like mxtpu/native.py does for the IO lib.)

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_CAPI_ABI_VERSION 1

extern "C" {
typedef void* PredictorHandle;

const char* MXGetLastError();
int MXCAPIGetVersion(int* out);
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredGetNumOutputs(PredictorHandle handle, uint32_t* out);
int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim);
int MXPredSetInput(PredictorHandle handle, const char* key, const float* data,
                   uint32_t size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle handle);
}

namespace {

// ---- error convention (c_api_common.h API_BEGIN/API_END parity) -------------
thread_local std::string g_last_error;

void set_error_from_python() {
  // must hold the GIL
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// ---- interpreter bootstrap ---------------------------------------------------
// Two modes: (a) host process already runs Python (ctypes in-process binding) —
// attach via PyGILState; (b) pure C/C++ host (the bindings story) — initialize
// the interpreter once, then release the GIL so every entry point can use the
// same PyGILState discipline regardless of mode.
std::once_flag g_init_once;
PyObject* g_impl_module = nullptr;  // mxtpu.capi_impl, owned forever
bool g_init_ok = false;
std::string g_bootstrap_error;  // shared across threads (set once, read-only after)

void bootstrap() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);          // no signal handlers: we are a library
    PyEval_SaveThread();         // drop the GIL acquired by initialization
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* mod = PyImport_ImportModule("mxtpu.capi_impl");
  if (mod == nullptr) {
    set_error_from_python();
    g_bootstrap_error =
        "cannot import mxtpu.capi_impl (is the repo on PYTHONPATH?): "
        + g_last_error;
  } else {
    g_impl_module = mod;  // keep the reference for the process lifetime
    g_init_ok = true;
  }
  PyGILState_Release(gil);
}

bool ensure_ready() {
  std::call_once(g_init_once, bootstrap);
  if (!g_init_ok)
    g_last_error = g_bootstrap_error;  // every failing caller's thread sees it
  return g_init_ok;
}

struct Pred {
  PyObject* obj;  // mxtpu.capi_impl.Predictor instance (owned)
  // backing store for MXPredGetOutputShape pointers (valid until next call on
  // the same handle / MXPredFree, same lifetime contract as the reference)
  std::vector<std::vector<uint32_t>> shapes;
};

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXCAPIGetVersion(int* out) {
  if (out == nullptr) {
    g_last_error = "MXCAPIGetVersion: null argument";
    return -1;
  }
  *out = MXTPU_CAPI_ABI_VERSION;
  return 0;
}

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out) {
  if (out == nullptr || symbol_json_str == nullptr) {
    g_last_error = "MXPredCreate: null argument";
    return -1;
  }
  if (!ensure_ready()) return -1;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* names = nullptr;
  PyObject* shapes = nullptr;
  PyObject* params = nullptr;
  PyObject* pobj = nullptr;
  do {
    names = PyList_New(num_input_nodes);
    shapes = PyList_New(num_input_nodes);
    if (names == nullptr || shapes == nullptr) break;
    bool fail = false;
    for (uint32_t i = 0; i < num_input_nodes; ++i) {
      PyObject* key = PyUnicode_FromString(input_keys[i]);
      if (key == nullptr) { fail = true; break; }
      PyList_SET_ITEM(names, i, key);
      uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
      PyObject* shp = PyTuple_New(hi - lo);
      if (shp == nullptr) { fail = true; break; }
      for (uint32_t j = lo; j < hi; ++j)
        PyTuple_SET_ITEM(shp, j - lo,
                         PyLong_FromUnsignedLong(input_shape_data[j]));
      PyList_SET_ITEM(shapes, i, shp);
    }
    if (fail) break;
    params = PyBytes_FromStringAndSize(
        static_cast<const char*>(param_bytes), param_size);
    if (params == nullptr) break;
    pobj = PyObject_CallMethod(g_impl_module, "create_predictor", "sOOOii",
                               symbol_json_str, params, names, shapes,
                               dev_type, dev_id);
    if (pobj == nullptr) {
      set_error_from_python();
      break;
    }
    Pred* p = new Pred{pobj, {}};
    pobj = nullptr;  // ownership moved into the handle
    *out = p;
    rc = 0;
  } while (false);
  if (rc != 0 && !PyErr_Occurred() && g_last_error.empty())
    g_last_error = "MXPredCreate: allocation failure";
  if (PyErr_Occurred()) set_error_from_python();
  Py_XDECREF(names);
  Py_XDECREF(shapes);
  Py_XDECREF(params);
  Py_XDECREF(pobj);
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetNumOutputs(PredictorHandle handle, uint32_t* out) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || out == nullptr) {
    g_last_error = "MXPredGetNumOutputs: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* n = PyObject_GetAttrString(p->obj, "num_outputs");
  if (n == nullptr) {
    set_error_from_python();
  } else {
    *out = static_cast<uint32_t>(PyLong_AsUnsignedLong(n));
    Py_DECREF(n);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(PredictorHandle handle, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || shape_data == nullptr || shape_ndim == nullptr) {
    g_last_error = "MXPredGetOutputShape: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* shp = PyObject_CallMethod(p->obj, "output_shape", "I", index);
  if (shp == nullptr) {
    set_error_from_python();
  } else {
    Py_ssize_t nd = PyTuple_Size(shp);
    std::vector<uint32_t> dims(static_cast<size_t>(nd));
    for (Py_ssize_t i = 0; i < nd; ++i)
      dims[i] = static_cast<uint32_t>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shp, i)));
    Py_DECREF(shp);
    if (p->shapes.size() <= index) p->shapes.resize(index + 1);
    p->shapes[index] = std::move(dims);
    *shape_data = p->shapes[index].data();
    *shape_ndim = static_cast<uint32_t>(p->shapes[index].size());
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(PredictorHandle handle, const char* key, const float* data,
                   uint32_t size) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || key == nullptr || data == nullptr) {
    g_last_error = "MXPredSetInput: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  if (buf != nullptr) {
    PyObject* r = PyObject_CallMethod(p->obj, "set_input", "sO", key, buf);
    Py_DECREF(buf);
    if (r == nullptr) {
      set_error_from_python();
    } else {
      Py_DECREF(r);
      rc = 0;
    }
  } else {
    set_error_from_python();
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(PredictorHandle handle) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr) {
    g_last_error = "MXPredForward: null handle";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "forward", nullptr);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutput(PredictorHandle handle, uint32_t index, float* data,
                    uint32_t size) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr || data == nullptr) {
    g_last_error = "MXPredGetOutput: null argument";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "get_output", "I", index);
  if (r == nullptr) {
    set_error_from_python();
  } else {
    char* raw = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(r, &raw, &len) == 0) {
      Py_ssize_t want = static_cast<Py_ssize_t>(size) * sizeof(float);
      if (len != want) {
        g_last_error = "MXPredGetOutput: size mismatch (have " +
                       std::to_string(len / sizeof(float)) + " floats, caller asked " +
                       std::to_string(size) + ")";
      } else {
        std::memcpy(data, raw, static_cast<size_t>(len));
        rc = 0;
      }
    } else {
      set_error_from_python();
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredFree(PredictorHandle handle) {
  Pred* p = static_cast<Pred*>(handle);
  if (p == nullptr) return 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"
