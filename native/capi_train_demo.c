/* Pure-C TRAINING client of the mxtpu C ABI (libmxtpu_capi.so).
 *
 * The reference's c_api.h training surface (MXNDArrayCreateEx,
 * MXImperativeInvokeEx, MXAutogradMarkVariables, MXAutogradBackwardEx) lets
 * any C FFI host run a training loop; this program proves the same
 * capability here: it fits w for y = x·wᵀ by gradient descent using ONLY the
 * C ABI — create arrays, mark the weight, record, FullyConnected forward,
 * LinearRegressionOutput loss head, backward, read the grad, sgd_update.
 *
 * Prints one JSON line: {"ok":1,"loss_first":...,"loss_last":...}
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* NDArrayHandle;
extern const char* MXGetLastError(void);
extern int MXNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dev_type,
                           int dev_id, int delay_alloc, int dtype,
                           NDArrayHandle* out);
extern int MXNDArrayFree(NDArrayHandle h);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void* data,
                                    size_t size_bytes);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle h, void* data,
                                  size_t size_bytes);
extern int MXNDArrayGetShape(NDArrayHandle h, uint32_t* out_ndim,
                             uint32_t* out_shape, uint32_t max_ndim);
extern int MXNDArrayGetGrad(NDArrayHandle h, NDArrayHandle* out);
extern int MXImperativeInvokeByName(const char* op, int num_in,
                                    NDArrayHandle* in, int* num_out,
                                    NDArrayHandle* out, int max_out,
                                    int num_params, const char** keys,
                                    const char** vals);
extern int MXAutogradSetIsRecording(int flag, int* prev);
extern int MXAutogradSetIsTraining(int flag, int* prev);
extern int MXAutogradMarkVariables(uint32_t n, NDArrayHandle* vars,
                                   uint32_t* reqs);
extern int MXAutogradBackward(uint32_t n, NDArrayHandle* heads,
                              NDArrayHandle* head_grads, int retain);
extern int MXListAllOpNames(uint32_t* out_size, const char*** out_names);

#define CHECK(expr)                                                    \
  do {                                                                 \
    if ((expr) != 0) {                                                 \
      fprintf(stderr, "FAIL %s: %s\n", #expr, MXGetLastError());       \
      return 1;                                                        \
    }                                                                  \
  } while (0)

#define N 16
#define D 4
#define H 3

int main(void) {
  /* synthetic data: y = x * true_wᵀ */
  float x_host[N * D], w_true[H * D], y_host[N * H], w_host[H * D];
  for (int i = 0; i < N * D; ++i) x_host[i] = 0.05f * (float)((i * 7) % 40) - 1.0f;
  for (int i = 0; i < H * D; ++i) w_true[i] = 0.1f * (float)((i * 3) % 11) - 0.5f;
  for (int n = 0; n < N; ++n)
    for (int h = 0; h < H; ++h) {
      float acc = 0.f;
      for (int d = 0; d < D; ++d) acc += x_host[n * D + d] * w_true[h * D + d];
      y_host[n * H + h] = acc;
    }
  for (int i = 0; i < H * D; ++i) w_host[i] = 0.f;

  uint32_t xs[2] = {N, D}, ws[2] = {H, D}, ys_[2] = {N, H};
  NDArrayHandle x, w, y;
  CHECK(MXNDArrayCreate(xs, 2, 1, 0, 0, 0, &x));
  CHECK(MXNDArrayCreate(ws, 2, 1, 0, 0, 0, &w));
  CHECK(MXNDArrayCreate(ys_, 2, 1, 0, 0, 0, &y));
  CHECK(MXNDArraySyncCopyFromCPU(x, x_host, sizeof(x_host)));
  CHECK(MXNDArraySyncCopyFromCPU(w, w_host, sizeof(w_host)));
  CHECK(MXNDArraySyncCopyFromCPU(y, y_host, sizeof(y_host)));

  /* registry sanity: the fused optimizer op we rely on must be listed */
  uint32_t n_ops = 0;
  const char** op_names = NULL;
  CHECK(MXListAllOpNames(&n_ops, &op_names));
  int have_sgd = 0;
  for (uint32_t i = 0; i < n_ops; ++i)
    if (strcmp(op_names[i], "sgd_update") == 0) have_sgd = 1;
  if (!have_sgd) {
    fprintf(stderr, "sgd_update missing from op registry\n");
    return 1;
  }

  uint32_t req = 1; /* write */
  CHECK(MXAutogradMarkVariables(1, &w, &req));

  const char* fc_keys[2] = {"num_hidden", "no_bias"};
  const char* fc_vals[2] = {"3", "True"};
  const char* sgd_keys[1] = {"lr"};
  const char* sgd_vals[1] = {"0.2"};

  float loss_first = -1.f, loss_last = -1.f;
  for (int it = 0; it < 30; ++it) {
    int prev;
    CHECK(MXAutogradSetIsRecording(1, &prev));
    CHECK(MXAutogradSetIsTraining(1, &prev));

    NDArrayHandle fc_in[2] = {x, w};
    NDArrayHandle fc_out[1];
    int n_out = 0;
    CHECK(MXImperativeInvokeByName("FullyConnected", 2, fc_in, &n_out,
                                   fc_out, 1, 2, fc_keys, fc_vals));
    NDArrayHandle reg_in[2] = {fc_out[0], y};
    NDArrayHandle reg_out[1];
    CHECK(MXImperativeInvokeByName("LinearRegressionOutput", 2, reg_in,
                                   &n_out, reg_out, 1, 0, NULL, NULL));
    CHECK(MXAutogradBackward(1, reg_out, NULL, 0));
    CHECK(MXAutogradSetIsRecording(0, &prev));

    /* mean squared error of the prediction, on the host */
    float pred[N * H];
    CHECK(MXNDArraySyncCopyToCPU(fc_out[0], pred, sizeof(pred)));
    float mse = 0.f;
    for (int i = 0; i < N * H; ++i) {
      float d = pred[i] - y_host[i];
      mse += d * d;
    }
    mse /= (float)(N * H);
    if (it == 0) loss_first = mse;
    loss_last = mse;

    NDArrayHandle g;
    CHECK(MXNDArrayGetGrad(w, &g));
    NDArrayHandle upd_in[2] = {w, g};
    NDArrayHandle upd_out[1];
    CHECK(MXImperativeInvokeByName("sgd_update", 2, upd_in, &n_out, upd_out,
                                   1, 1, sgd_keys, sgd_vals));
    /* write the updated weight back into w's buffer via host copy (the C
     * surface is functional: ops return new arrays) */
    float w_new[H * D];
    CHECK(MXNDArraySyncCopyToCPU(upd_out[0], w_new, sizeof(w_new)));
    CHECK(MXNDArraySyncCopyFromCPU(w, w_new, sizeof(w_new)));
    MXNDArrayFree(upd_out[0]);
    MXNDArrayFree(g);
    MXNDArrayFree(fc_out[0]);
    MXNDArrayFree(reg_out[0]);
  }

  MXNDArrayFree(x);
  MXNDArrayFree(w);
  MXNDArrayFree(y);

  int ok = loss_last < 0.05f * loss_first;
  printf("{\"ok\":%d,\"loss_first\":%.6f,\"loss_last\":%.6f}\n", ok,
         loss_first, loss_last);
  return ok ? 0 : 1;
}
