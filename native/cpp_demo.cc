// C++ client of the mxtpu-cpp header binding (native/mxtpu_cpp.hpp) —
// cpp-package usage-pattern parity: RAII predictor, exceptions, std::vector IO.
// Usage: cpp_demo <symbol.json> <file.params> <input_name> <d0,d1,...>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <sstream>

#include "mxtpu_cpp.hpp"

static std::string slurp(const char* path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char** argv) {
  if (argc != 5) {
    std::fprintf(stderr, "usage: %s symbol.json file.params input d0,d1,...\n",
                 argv[0]);
    return 2;
  }
  std::vector<uint32_t> shape;
  uint32_t numel = 1;
  for (char* tok = std::strtok(argv[4], ","); tok;
       tok = std::strtok(nullptr, ",")) {
    shape.push_back(static_cast<uint32_t>(std::atoi(tok)));
    numel *= shape.back();
  }
  try {
    mxtpu::Predictor pred(slurp(argv[1]), slurp(argv[2]),
                          {{argv[3], shape}});
    std::vector<float> in(numel);
    for (uint32_t i = 0; i < numel; ++i)
      in[i] = 0.01f * static_cast<float>(i % 100) - 0.5f;
    pred.set_input(argv[3], in);
    pred.forward();
    auto oshape = pred.output_shape(0);
    auto out = pred.get_output(0);
    double checksum = 0.0;
    for (float v : out) checksum += v;
    std::printf("{\"ok\":1,\"num_outputs\":%u,\"shape\":[", pred.num_outputs());
    for (size_t i = 0; i < oshape.size(); ++i)
      std::printf("%s%u", i ? "," : "", oshape[i]);
    std::printf("],\"checksum\":%.6f}\n", checksum);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
  return 0;
}
