// mxtpu-cpp — header-only C++ binding over the mxtpu C ABI.
//
// Reference parity: cpp-package/include/mxnet-cpp (27 headers wrapping the C
// API in RAII classes; SURVEY §2.6). The TPU-native framework's stable ABI is
// predict-scoped (native/mxtpu_capi.cc), so this binding wraps that surface:
// a `mxtpu::Predictor` that loads a symbol-JSON + params checkpoint and runs
// inference with exception-based error handling and std::vector buffers.
// It demonstrates the bindings capability — any further language (JVM/R/...)
// binds the same flat C functions.
//
// Usage:
//   mxtpu::Predictor pred(symbol_json_string, param_blob,
//                         {{"data", {8, 3, 224, 224}}});
//   pred.set_input("data", my_floats);
//   pred.forward();
//   std::vector<float> probs = pred.get_output(0);
//
// Link against libmxtpu_capi.so; the library bootstraps the embedded CPython
// interpreter on first use (set PYTHONPATH to the mxtpu repo).

#ifndef MXTPU_CPP_HPP_
#define MXTPU_CPP_HPP_

#include <cstdint>
#include <numeric>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

extern "C" {
typedef void* PredictorHandle;
const char* MXGetLastError();
int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id, uint32_t num_input,
                 const char** input_keys, const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, PredictorHandle* out);
int MXPredGetNumOutputs(PredictorHandle h, uint32_t* out);
int MXPredGetOutputShape(PredictorHandle h, uint32_t index,
                         uint32_t** shape_data, uint32_t* shape_ndim);
int MXPredSetInput(PredictorHandle h, const char* key, const float* data,
                   uint32_t size);
int MXPredForward(PredictorHandle h);
int MXPredGetOutput(PredictorHandle h, uint32_t index, float* data,
                    uint32_t size);
int MXPredFree(PredictorHandle h);
}

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& where)
      : std::runtime_error(where + ": " + MXGetLastError()) {}
};

class Predictor {
 public:
  using NamedShape = std::pair<std::string, std::vector<uint32_t>>;

  // dev_type: 1 = cpu, 2 = accelerator (TPU), matching the C ABI enum.
  Predictor(const std::string& symbol_json, const std::string& param_bytes,
            const std::vector<NamedShape>& inputs, int dev_type = 1,
            int dev_id = 0) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0};
    std::vector<uint32_t> dims;
    for (const auto& kv : inputs) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    if (MXPredCreate(symbol_json.c_str(), param_bytes.data(),
                     static_cast<int>(param_bytes.size()), dev_type, dev_id,
                     static_cast<uint32_t>(keys.size()), keys.data(),
                     indptr.data(), dims.empty() ? nullptr : dims.data(),
                     &handle_) != 0)
      throw Error("MXPredCreate");
  }

  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }

  ~Predictor() {
    if (handle_ != nullptr) MXPredFree(handle_);
  }

  void set_input(const std::string& key, const std::vector<float>& data) {
    if (MXPredSetInput(handle_, key.c_str(), data.data(),
                       static_cast<uint32_t>(data.size())) != 0)
      throw Error("MXPredSetInput");
  }

  void forward() {
    if (MXPredForward(handle_) != 0) throw Error("MXPredForward");
  }

  uint32_t num_outputs() const {
    uint32_t n = 0;
    if (MXPredGetNumOutputs(handle_, &n) != 0)
      throw Error("MXPredGetNumOutputs");
    return n;
  }

  std::vector<uint32_t> output_shape(uint32_t index) const {
    uint32_t* data = nullptr;
    uint32_t ndim = 0;
    if (MXPredGetOutputShape(handle_, index, &data, &ndim) != 0)
      throw Error("MXPredGetOutputShape");
    return std::vector<uint32_t>(data, data + ndim);
  }

  std::vector<float> get_output(uint32_t index) const {
    auto shape = output_shape(index);
    uint32_t size = std::accumulate(shape.begin(), shape.end(), 1u,
                                    [](uint32_t a, uint32_t b) { return a * b; });
    std::vector<float> out(size);
    if (MXPredGetOutput(handle_, index, out.data(), size) != 0)
      throw Error("MXPredGetOutput");
    return out;
  }

 private:
  PredictorHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_HPP_
