/* Pure-C SYMBOL client of the mxtpu C ABI (libmxtpu_capi.so).
 *
 * The round-4 verdict's missing slice: a C host that COMPOSES a graph —
 * FC(8) -> relu -> FC(3) -> SoftmaxOutput — with MXSymbolCreateAtomicSymbolByName
 * + MXSymbolCompose (no Python-authored JSON anywhere), discovers its
 * auto-created parameters with MXSymbolListArguments, runs MXSymbolInferShape,
 * serializes with MXSymbolSaveToJSON, binds the JSON through MXPredCreate with
 * an EMPTY params payload (every argument arrives via MXPredSetInput), and
 * checks the prediction against a softmax MLP computed right here in C.
 *
 * Reference parity target: src/c_api/c_api_symbolic.cc + c_predict_api.cc.
 * Prints one JSON line: {"ok":1,"args":N,"complete":1,"maxdiff":...}
 */
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* SymbolHandle;
typedef void* PredictorHandle;

extern const char* MXGetLastError(void);
extern int MXSymbolCreateVariable(const char* name, SymbolHandle* out);
extern int MXSymbolCreateAtomicSymbolByName(const char* op, uint32_t num_param,
                                            const char** keys,
                                            const char** vals,
                                            SymbolHandle* out);
extern int MXSymbolCompose(SymbolHandle sym, const char* name,
                           uint32_t num_args, const char** keys,
                           SymbolHandle* args);
extern int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
extern int MXSymbolListArguments(SymbolHandle sym, uint32_t* size,
                                 const char*** names);
extern int MXSymbolInferShape(
    SymbolHandle sym, uint32_t num_args, const char** keys,
    const uint32_t* arg_ind_ptr, const uint32_t* arg_shape_data,
    uint32_t* in_size, const uint32_t** in_ndim, const uint32_t*** in_data,
    uint32_t* out_size, const uint32_t** out_ndim, const uint32_t*** out_data,
    uint32_t* aux_size, const uint32_t** aux_ndim, const uint32_t*** aux_data,
    int* complete);
extern int MXSymbolFree(SymbolHandle sym);
extern int MXPredCreate(const char* symbol_json, const void* param_bytes,
                        int param_size, int dev_type, int dev_id,
                        uint32_t num_input, const char** input_keys,
                        const uint32_t* input_shape_indptr,
                        const uint32_t* input_shape_data,
                        PredictorHandle* out);
extern int MXPredSetInput(PredictorHandle h, const char* key,
                          const float* data, uint32_t size);
extern int MXPredForward(PredictorHandle h);
extern int MXPredGetOutput(PredictorHandle h, uint32_t index, float* data,
                           uint32_t size);
extern int MXPredFree(PredictorHandle h);

#define CHECK(call)                                                     \
  do {                                                                  \
    if ((call) != 0) {                                                  \
      fprintf(stderr, "FAIL %s: %s\n", #call, MXGetLastError());        \
      return 1;                                                         \
    }                                                                   \
  } while (0)

#define B 2
#define IN 4
#define H 8
#define C 3

/* deterministic parameter patterns (mirrored by the expected-value math) */
static float w1v(int i, int j) { return 0.05f * (float)(i - 3) + 0.02f * (float)j; }
static float b1v(int i) { return 0.01f * (float)i; }
static float w2v(int i, int j) { return 0.03f * (float)(j - 4) - 0.02f * (float)i; }
static float b2v(int i) { return 0.05f - 0.01f * (float)i; }
static float xv(int n, int j) { return 0.3f * (float)n + 0.1f * (float)j - 0.2f; }

int main(void) {
  /* ---- compose the graph, pure C ---------------------------------------- */
  SymbolHandle data, fc1, relu, fc2, net;
  CHECK(MXSymbolCreateVariable("data", &data));

  const char* fc1_keys[] = {"num_hidden"};
  const char* fc1_vals[] = {"8"};
  CHECK(MXSymbolCreateAtomicSymbolByName("FullyConnected", 1, fc1_keys,
                                         fc1_vals, &fc1));
  const char* dkey[] = {"data"};
  SymbolHandle dargs[] = {data};
  CHECK(MXSymbolCompose(fc1, "fc1", 1, dkey, dargs));

  const char* act_keys[] = {"act_type"};
  const char* act_vals[] = {"relu"};
  CHECK(MXSymbolCreateAtomicSymbolByName("Activation", 1, act_keys, act_vals,
                                         &relu));
  SymbolHandle rargs[] = {fc1};
  CHECK(MXSymbolCompose(relu, "relu1", 1, NULL, rargs));

  const char* fc2_keys[] = {"num_hidden"};
  const char* fc2_vals[] = {"3"};
  CHECK(MXSymbolCreateAtomicSymbolByName("FullyConnected", 1, fc2_keys,
                                         fc2_vals, &fc2));
  SymbolHandle f2args[] = {relu};
  CHECK(MXSymbolCompose(fc2, "fc2", 1, dkey, f2args));

  CHECK(MXSymbolCreateAtomicSymbolByName("SoftmaxOutput", 0, NULL, NULL,
                                         &net));
  SymbolHandle nargs[] = {fc2};
  CHECK(MXSymbolCompose(net, "softmax", 1, NULL, nargs));

  /* ---- discover the auto-created parameters ------------------------------ */
  uint32_t n_args = 0;
  const char** arg_names = NULL;
  CHECK(MXSymbolListArguments(net, &n_args, &arg_names));
  /* expected: data + 2x(weight,bias) + label = 6 */
  if (n_args != 6) {
    fprintf(stderr, "FAIL: expected 6 arguments, got %u\n", n_args);
    return 1;
  }
  /* copy the names: the backing store is reused by later Symbol calls */
  char names_buf[6][128];
  const char* names[6];
  for (uint32_t i = 0; i < n_args; ++i) {
    strncpy(names_buf[i], arg_names[i], 127);
    names_buf[i][127] = 0;
    names[i] = names_buf[i];
  }

  /* ---- infer shapes from the data shape ---------------------------------- */
  const char* ikeys[] = {"data"};
  const uint32_t indptr[] = {0, 2};
  const uint32_t ishape[] = {B, IN};
  uint32_t in_size, out_size, aux_size;
  const uint32_t *in_ndim, *out_ndim, *aux_ndim;
  const uint32_t **in_data, **out_data, **aux_data;
  int complete = 0;
  CHECK(MXSymbolInferShape(net, 1, ikeys, indptr, ishape, &in_size, &in_ndim,
                           &in_data, &out_size, &out_ndim, &out_data,
                           &aux_size, &aux_ndim, &aux_data, &complete));
  if (!complete || in_size != n_args) {
    fprintf(stderr, "FAIL: infer_shape incomplete (%d) or size %u\n",
            complete, in_size);
    return 1;
  }
  /* stash the inferred arg shapes before the store is reused */
  uint32_t shapes[6][4];
  uint32_t ndims[6];
  uint32_t total_dims = 0;
  for (uint32_t i = 0; i < in_size; ++i) {
    ndims[i] = in_ndim[i];
    for (uint32_t d = 0; d < in_ndim[i]; ++d) shapes[i][d] = in_data[i][d];
    total_dims += in_ndim[i];
  }

  /* ---- serialize, bind via the predict ABI (empty params) ---------------- */
  const char* json = NULL;
  CHECK(MXSymbolSaveToJSON(net, &json));
  char* json_copy = strdup(json);

  uint32_t bind_indptr[7];
  uint32_t bind_dims[24];
  uint32_t pos = 0;
  bind_indptr[0] = 0;
  for (uint32_t i = 0; i < n_args; ++i) {
    for (uint32_t d = 0; d < ndims[i]; ++d) bind_dims[pos++] = shapes[i][d];
    bind_indptr[i + 1] = pos;
  }
  PredictorHandle pred = NULL;
  CHECK(MXPredCreate(json_copy, NULL, 0, 1, 0, n_args, names, bind_indptr,
                     bind_dims, &pred));

  /* ---- feed every argument from C --------------------------------------- */
  float x[B * IN], w1[H * IN], b1[H], w2[C * H], b2[C];
  for (int n = 0; n < B; ++n)
    for (int j = 0; j < IN; ++j) x[n * IN + j] = xv(n, j);
  for (int i = 0; i < H; ++i)
    for (int j = 0; j < IN; ++j) w1[i * IN + j] = w1v(i, j);
  for (int i = 0; i < H; ++i) b1[i] = b1v(i);
  for (int i = 0; i < C; ++i)
    for (int j = 0; j < H; ++j) w2[i * H + j] = w2v(i, j);
  for (int i = 0; i < C; ++i) b2[i] = b2v(i);
  float label[B] = {0.0f, 0.0f};

  for (uint32_t i = 0; i < n_args; ++i) {
    const char* nm = names[i];
    uint32_t sz = 1;
    for (uint32_t d = 0; d < ndims[i]; ++d) sz *= shapes[i][d];
    const float* src = NULL;
    if (strcmp(nm, "data") == 0) src = x;
    else if (strstr(nm, "fc1_weight")) src = w1;
    else if (strstr(nm, "fc1_bias")) src = b1;
    else if (strstr(nm, "fc2_weight")) src = w2;
    else if (strstr(nm, "fc2_bias")) src = b2;
    else if (strstr(nm, "label")) src = label;
    if (src == NULL) {
      fprintf(stderr, "FAIL: unexpected argument %s\n", nm);
      return 1;
    }
    CHECK(MXPredSetInput(pred, nm, src, sz));
  }

  /* ---- forward + verify against the same MLP computed here --------------- */
  CHECK(MXPredForward(pred));
  float out[B * C];
  CHECK(MXPredGetOutput(pred, 0, out, B * C));

  float maxdiff = 0.0f;
  for (int n = 0; n < B; ++n) {
    float h[H], logits[C], prob[C];
    for (int i = 0; i < H; ++i) {
      float acc = b1[i];
      for (int j = 0; j < IN; ++j) acc += w1[i * IN + j] * x[n * IN + j];
      h[i] = acc > 0.0f ? acc : 0.0f;
    }
    float m = -1e30f;
    for (int i = 0; i < C; ++i) {
      float acc = b2[i];
      for (int j = 0; j < H; ++j) acc += w2[i * H + j] * h[j];
      logits[i] = acc;
      if (acc > m) m = acc;
    }
    float z = 0.0f;
    for (int i = 0; i < C; ++i) {
      prob[i] = expf(logits[i] - m);
      z += prob[i];
    }
    for (int i = 0; i < C; ++i) {
      float d = fabsf(out[n * C + i] - prob[i] / z);
      if (d > maxdiff) maxdiff = d;
    }
  }
  if (maxdiff > 1e-4f) {
    fprintf(stderr, "FAIL: prediction mismatch, maxdiff=%g\n", (double)maxdiff);
    return 1;
  }

  printf("{\"ok\":1,\"args\":%u,\"complete\":%d,\"maxdiff\":%g}\n", n_args,
         complete, (double)maxdiff);
  free(json_copy);
  MXPredFree(pred);
  MXSymbolFree(net);
  MXSymbolFree(fc2);
  MXSymbolFree(relu);
  MXSymbolFree(fc1);
  MXSymbolFree(data);
  return 0;
}
