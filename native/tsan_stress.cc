// ThreadSanitizer stress harness for the native IO library.
//
// The reference ships no sanitizer integration (SURVEY §5: correctness is "by
// construction" plus threaded_engine_test.cc); this framework does better by
// compiling its host-side C++ hot loops WITH -fsanitize=thread and hammering
// them from concurrent callers — the way the Python layer actually uses them
// (ImageIter's decode pool calls jpeg_decode/nhwc_u8_to_nchw_f32 from many
// threads while a prefetch thread runs rio_read_batch).
//
// Built by tests/test_native_io.py as
//   g++ -fsanitize=thread -O1 -g tsan_stress.cc mxtpu_io.cc \
//       -DMXTPU_HAVE_JPEG -ljpeg -o tsan_stress
// and run as a subprocess; any data race makes TSAN print "WARNING:
// ThreadSanitizer" and exit(66) via the halt_on_error runtime flag the test
// sets. Exit 0 == race-free under this workload.
//
// Usage: tsan_stress <file.rec>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
int64_t rio_index(const char* path, int64_t* offsets, int64_t* sizes,
                  int64_t max_records);
int rio_read_batch(const char* path, const int64_t* offsets,
                   const int64_t* sizes, const int64_t* out_offsets,
                   int64_t n, char* out, int num_threads);
void nhwc_u8_to_nchw_f32(const uint8_t* in, float* out, const float* mean,
                         const float* std_, int64_t n, int64_t h, int64_t w,
                         int64_t c, int scale255, int num_threads);
#ifdef MXTPU_HAVE_JPEG
int jpeg_dims(const uint8_t* buf, int64_t size, int64_t* h, int64_t* w,
              int64_t* c);
int jpeg_decode(const uint8_t* buf, int64_t size, uint8_t* out,
                int64_t out_size);
#endif
}

// recordio.pack layout: 24-byte IRHeader ("IfQQ") then the image bytes
constexpr int64_t kIRHeaderSize = 24;

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s file.rec\n", argv[0]);
    return 2;
  }
  const char* path = argv[1];

  std::vector<int64_t> offsets(4096), sizes(4096);
  int64_t n = rio_index(path, offsets.data(), sizes.data(), 4096);
  if (n <= 0) {
    std::fprintf(stderr, "rio_index failed: %lld\n",
                 static_cast<long long>(n));
    return 2;
  }

  std::vector<int64_t> out_offsets(n);
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    out_offsets[i] = total;
    total += sizes[i];
  }

  // Concurrent callers, each also asking for an internal thread pool — the
  // worst nesting the Python layer produces.
  constexpr int kCallers = 4;
  constexpr int kIters = 8;
  std::vector<std::thread> callers;
  for (int t = 0; t < kCallers; ++t) {
    callers.emplace_back([&, t] {
      std::vector<char> buf(total);
      const int64_t N = 8, H = 24, W = 24, C = 3;
      std::vector<uint8_t> img(N * H * W * C);
      std::vector<float> outf(N * C * H * W);
      float mean[3] = {123.f, 116.f, 103.f};
      float stdv[3] = {58.f, 57.f, 57.f};
      for (int it = 0; it < kIters; ++it) {
        if (rio_read_batch(path, offsets.data(), sizes.data(),
                           out_offsets.data(), n, buf.data(), 3) != 0) {
          std::fprintf(stderr, "caller %d: rio_read_batch failed\n", t);
          std::exit(2);
        }
#ifdef MXTPU_HAVE_JPEG
        // the likeliest race site: concurrent libjpeg decodes of the record
        // payloads (ImageIter's decode pool does exactly this)
        std::vector<uint8_t> pix;
        for (int64_t i = 0; i < n; ++i) {
          const uint8_t* payload = reinterpret_cast<const uint8_t*>(
              buf.data() + out_offsets[i]);
          const uint8_t* jpg = payload + kIRHeaderSize;
          int64_t jlen = sizes[i] - kIRHeaderSize;
          int64_t jh = 0, jw = 0, jc = 0;
          if (jpeg_dims(jpg, jlen, &jh, &jw, &jc) != 0) {
            std::fprintf(stderr, "caller %d: jpeg_dims failed on rec %lld\n",
                         t, static_cast<long long>(i));
            std::exit(2);
          }
          pix.resize(jh * jw * 3);
          if (jpeg_decode(jpg, jlen, pix.data(), pix.size()) != 0) {
            std::fprintf(stderr, "caller %d: jpeg_decode failed on rec %lld\n",
                         t, static_cast<long long>(i));
            std::exit(2);
          }
        }
#endif
        for (size_t i = 0; i < img.size(); ++i)
          img[i] = static_cast<uint8_t>((i * 31 + it + t) & 0xff);
        nhwc_u8_to_nchw_f32(img.data(), outf.data(), mean, stdv, N, H, W, C,
                            /*scale255=*/0, /*num_threads=*/3);
      }
    });
  }
  for (auto& th : callers) th.join();
  std::printf("tsan_stress: ok (%lld records, %d callers x %d iters)\n",
              static_cast<long long>(n), kCallers, kIters);
  return 0;
}
