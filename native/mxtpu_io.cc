// mxtpu native IO — C++ data-pipeline kernels (reference parity: src/io/, the
// reference's RecordIO parse + batch assembly are C++ with OMP decode threads,
// iter_image_recordio_2.cc). The Python layer binds these via ctypes; everything
// here is host-side (the device path is XLA's).
//
// Exposed C ABI:
//   rio_index      — scan a RecordIO file, return record offsets/sizes
//   rio_read_batch — positioned parallel reads of many records into one buffer
//   nhwc_u8_to_nchw_f32 — fused uint8→float32 normalize + HWC→CHW transpose,
//                         threaded over the batch (the host-side hot loop that
//                         feeds device_put)
//   f32_batch_stack — parallel memcpy gather of sample pointers into a batch
//   jpeg_dims / jpeg_decode — libjpeg RGB decode (the reference's OMP decode
//                             hot loop, iter_image_recordio_2.cc:138-149);
//                             callers parallelize across a thread pool (the
//                             ctypes call releases the GIL)

#include <atomic>
#include <csetjmp>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#ifdef MXTPU_HAVE_JPEG
#include <jpeglib.h>
#endif

namespace {

constexpr uint32_t kMagic = 0xCED7230A;
constexpr uint32_t kLenMask = (1u << 29) - 1;

int hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n ? static_cast<int>(n) : 4;
}

// simple static partition parallel-for over [0, n)
template <typename F>
void parallel_for(int64_t n, F&& fn, int max_threads = 0) {
  int nt = max_threads > 0 ? max_threads : hw_threads();
  if (nt > n) nt = static_cast<int>(n);
  if (nt <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(nt);
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back([lo, hi, &fn]() {
      for (int64_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

// Scan a RecordIO file; fills offsets[i] (payload start) and sizes[i] for up to
// max_records records. Returns the number of records found, or -1 on IO error,
// -2 on a corrupt magic.
int64_t rio_index(const char* path, int64_t* offsets, int64_t* sizes,
                  int64_t max_records) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t count = 0;
  uint32_t head[2];
  int64_t pos = 0;
  while (count < max_records && std::fread(head, 4, 2, f) == 2) {
    if (head[0] != kMagic) {
      std::fclose(f);
      return -2;
    }
    int64_t len = head[1] & kLenMask;
    offsets[count] = pos + 8;
    sizes[count] = len;
    ++count;
    int64_t pad = (4 - (len % 4)) % 4;
    pos += 8 + len + pad;
    if (std::fseek(f, static_cast<long>(pos), SEEK_SET) != 0) break;
  }
  std::fclose(f);
  return count;
}

// Parallel positioned reads: record i is read from offsets[i] (sizes[i] bytes)
// into out + out_offsets[i]. Each worker opens its own FILE* (pread semantics).
// Returns 0 on success, -1 if any read failed.
int rio_read_batch(const char* path, const int64_t* offsets, const int64_t* sizes,
                   const int64_t* out_offsets, int64_t n, char* out,
                   int num_threads) {
  std::atomic<int> failed{0};
  int nt = num_threads > 0 ? num_threads : hw_threads();
  if (nt > n) nt = static_cast<int>(n);
  if (nt < 1) nt = 1;
  std::vector<std::thread> workers;
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back([&, lo, hi]() {
      FILE* f = std::fopen(path, "rb");
      if (!f) {
        failed.store(1);
        return;
      }
      for (int64_t i = lo; i < hi; ++i) {
        if (std::fseek(f, static_cast<long>(offsets[i]), SEEK_SET) != 0 ||
            std::fread(out + out_offsets[i], 1, static_cast<size_t>(sizes[i]),
                       f) != static_cast<size_t>(sizes[i])) {
          failed.store(1);
          break;
        }
      }
      std::fclose(f);
    });
  }
  for (auto& w : workers) w.join();
  return failed.load() ? -1 : 0;
}

// Fused normalize + layout transform for image batches:
//   in:  N × H × W × C uint8
//   out: N × C × H × W float32, out = (in/255 − mean[c]) / std[c]  (scale255=1)
//        or (in − mean[c]) / std[c]                                  (scale255=0)
// Threaded over N (the reference does this with OMP preprocess_threads).
void nhwc_u8_to_nchw_f32(const uint8_t* in, float* out, const float* mean,
                         const float* stddev, int64_t n, int64_t h, int64_t w,
                         int64_t c, int scale255, int num_threads) {
  const int64_t hw = h * w;
  const int64_t img_in = hw * c;
  const int64_t img_out = c * hw;
  const float inv255 = 1.0f / 255.0f;
  parallel_for(
      n,
      [&](int64_t i) {
        const uint8_t* src = in + i * img_in;
        float* dst = out + i * img_out;
        for (int64_t ch = 0; ch < c; ++ch) {
          const float m = mean ? mean[ch] : 0.0f;
          const float inv_s = stddev ? 1.0f / stddev[ch] : 1.0f;
          float* d = dst + ch * hw;
          const uint8_t* s = src + ch;
          if (scale255) {
            for (int64_t p = 0; p < hw; ++p)
              d[p] = (static_cast<float>(s[p * c]) * inv255 - m) * inv_s;
          } else {
            for (int64_t p = 0; p < hw; ++p)
              d[p] = (static_cast<float>(s[p * c]) - m) * inv_s;
          }
        }
      },
      num_threads);
}

// Gather n sample pointers (each `bytes` long) into a contiguous batch buffer.
void f32_batch_stack(const float** samples, float* out, int64_t n, int64_t bytes,
                     int num_threads) {
  parallel_for(
      n,
      [&](int64_t i) {
        std::memcpy(reinterpret_cast<char*>(out) + i * bytes,
                    samples[i], static_cast<size_t>(bytes));
      },
      num_threads);
}

#ifdef MXTPU_HAVE_JPEG
namespace {

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

}  // namespace

// Parse the JPEG header only: fills h/w/c. Returns 0 on success.
int jpeg_dims(const uint8_t* buf, int64_t size, int64_t* h, int64_t* w,
              int64_t* c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  *c = 3;  // decode always emits RGB (grayscale upconverts)
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Full RGB decode into a caller-allocated h*w*3 buffer. Returns 0 on success.
int jpeg_decode(const uint8_t* buf, int64_t size, uint8_t* out,
                int64_t capacity) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(size));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int64_t stride = static_cast<int64_t>(cinfo.output_width) * 3;
  if (stride * cinfo.output_height > capacity) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<int64_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

#else  // !MXTPU_HAVE_JPEG — keep the ABI, report failure (callers fall back)

int jpeg_dims(const uint8_t*, int64_t, int64_t*, int64_t*, int64_t*) {
  return -1;
}
int jpeg_decode(const uint8_t*, int64_t, uint8_t*, int64_t) { return -1; }

#endif  // MXTPU_HAVE_JPEG

namespace {

// splitmix64: per-image deterministic stream from (seed, index)
inline uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

}  // namespace

// The whole per-record pipeline of the reference's iter_image_recordio_2.cc
// ParseChunk loop (:50-149) as ONE threaded C pass writing straight into the
// preallocated batch slab: JPEG decode -> (center|random) crop -> optional
// mirror -> [(x - mean)/std ->] NCHW, float32 (out_dtype=0) or uint8
// (out_dtype=1, mean/std must be null — the feed-to-device layout where
// normalize runs on-chip). Removes every per-record Python hop and per-image
// array allocation of the fallback path. Returns 0, -1 on a decode failure,
// -2 when a decoded image is smaller than the HxW target, -3 on bad args.
int decode_augment_batch(const uint8_t* blob, const int64_t* offsets,
                         const int64_t* sizes, int64_t n, int64_t H, int64_t W,
                         const float* mean, const float* stddev, int rand_crop,
                         int rand_mirror, uint64_t seed, int out_dtype,
                         void* out, int num_threads) {
  if (blob == nullptr || offsets == nullptr || sizes == nullptr ||
      out == nullptr || H <= 0 || W <= 0 ||
      (out_dtype == 1 && (mean != nullptr || stddev != nullptr)))
    return -3;
  std::atomic<int> failed{0};
  const int64_t img_out = 3 * H * W;
  parallel_for(
      n,
      [&](int64_t i) {
        if (failed.load(std::memory_order_relaxed)) return;
        const uint8_t* buf = blob + offsets[i];
        int64_t h = 0, w = 0, c = 0;
        if (jpeg_dims(buf, sizes[i], &h, &w, &c) != 0) {
          failed.store(-1);
          return;
        }
        if (h < H || w < W) {
          failed.store(-2);
          return;
        }
        std::vector<uint8_t> scratch(static_cast<size_t>(h * w * 3));
        if (jpeg_decode(buf, sizes[i], scratch.data(),
                        static_cast<int64_t>(scratch.size())) != 0) {
          failed.store(-1);
          return;
        }
        uint64_t r = mix64(seed ^ static_cast<uint64_t>(i));
        const int64_t x0 = rand_crop ? static_cast<int64_t>(r % (w - W + 1))
                                     : (w - W) / 2;
        r = mix64(r);
        const int64_t y0 = rand_crop ? static_cast<int64_t>(r % (h - H + 1))
                                     : (h - H) / 2;
        r = mix64(r);
        const bool mirror = rand_mirror && (r & 1);
        for (int64_t ch = 0; ch < 3; ++ch) {
          const float m = mean ? mean[ch] : 0.0f;
          const float inv_s = stddev ? 1.0f / stddev[ch] : 1.0f;
          for (int64_t y = 0; y < H; ++y) {
            const uint8_t* srow = scratch.data() + ((y0 + y) * w + x0) * 3 + ch;
            const int64_t base = i * img_out + (ch * H + y) * W;
            if (out_dtype == 1) {
              uint8_t* d = static_cast<uint8_t*>(out) + base;
              if (mirror) {
                for (int64_t x = 0; x < W; ++x) d[x] = srow[(W - 1 - x) * 3];
              } else {
                for (int64_t x = 0; x < W; ++x) d[x] = srow[x * 3];
              }
            } else {
              float* d = static_cast<float*>(out) + base;
              if (mirror) {
                for (int64_t x = 0; x < W; ++x)
                  d[x] = (static_cast<float>(srow[(W - 1 - x) * 3]) - m) * inv_s;
              } else {
                for (int64_t x = 0; x < W; ++x)
                  d[x] = (static_cast<float>(srow[x * 3]) - m) * inv_s;
              }
            }
          }
        }
      },
      num_threads);
  return failed.load();
}

int mxtpu_io_abi_version() { return 3; }

}  // extern "C"
