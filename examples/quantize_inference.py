#!/usr/bin/env python
"""Post-training INT8 quantization demo — train a small net fp32, quantize
with entropy calibration, compare accuracy and agreement (the reference's
``example/quantization`` flow re-based on gluon + the int8 MXU path)."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--calib-mode", default="entropy",
                   choices=["none", "naive", "entropy"])
    p.add_argument("--quantized-dtype", default="auto",
                   choices=["int8", "uint8", "auto"])
    args = p.parse_args()

    import numpy as np

    from mxtpu import autograd, gluon, nd
    from mxtpu.contrib import quantization as qz
    from mxtpu.gluon import nn

    rs = np.random.RandomState(0)
    x = rs.randn(512, 32).astype(np.float32)
    w_true = rs.randn(32, 4).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    xa, ya = nd.array(x), nd.array(y.astype(np.float32))
    for _ in range(80):
        with autograd.record():
            L = lossfn(net(xa), ya).mean()
        L.backward()
        trainer.step(1)

    with autograd.predict_mode():
        fp32_pred = np.argmax(net(xa).asnumpy(), axis=1)
    calib = [nd.array(x[i * 128:(i + 1) * 128]) for i in range(4)]
    qnet = qz.quantize_net(net, quantized_dtype=args.quantized_dtype,
                           calib_mode=args.calib_mode,
                           calib_data=calib if args.calib_mode != "none"
                           else None)
    with autograd.predict_mode():
        q_pred = np.argmax(qnet(xa).asnumpy(), axis=1)
    print(f"fp32 acc:  {(fp32_pred == y).mean():.4f}")
    print(f"quant acc: {(q_pred == y).mean():.4f}  "
          f"(calib={args.calib_mode}, dtype={args.quantized_dtype})")
    print(f"agreement: {(q_pred == fp32_pred).mean():.4f}")


if __name__ == "__main__":
    main()
