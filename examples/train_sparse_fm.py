#!/usr/bin/env python
"""Factorization machine on LibSVM data with dist_sync KVStore — the
reference's ``example/sparse/factorization_machine`` flow (BASELINE config 5).

The full sparse pipeline composes end-to-end:

  LibSVM file → ``LibSVMIter`` CSR batches → sparse forward
  (``sparse.dot(csr, dense)``) → **row-sparse gradients** via the transposed
  sparse dot (the DotCsrTransDnsRsp rule the reference registers for its
  sparse linear ops) → ``kvstore dist_sync`` sparse push + ``row_sparse_pull``
  → lazy SGD that touches only the rows present in the batch.

FM model (Rendle 2010): s(x) = w0 + x·w + ½ Σ_f [(x·V)_f² − (x²·V²)_f],
logistic loss. Gradients are the classic closed forms — expressed with the
framework's sparse ops so every grad is row-sparse:
  ∂L/∂w = Xᵀδ,   ∂L/∂V = Xᵀ(δ ⊙ XV) − (X²)ᵀ(δ·1) ⊙ V-rows
with δ = σ(s) − y.

Synthetic task: planted sparse logistic model over a large vocabulary; only
O(nnz) rows of w/V are ever touched per step — the capability the reference's
row-sparse parameter-server protocol exists for.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_libsvm(path, rs, n_rows, n_feat, nnz, w_true):
    """Synthetic planted-model LibSVM file: label = 1[σ(x·w_true) > 0.5]."""
    import numpy as np
    with open(path, "w") as f:
        for _ in range(n_rows):
            idx = np.sort(rs.choice(n_feat, nnz, replace=False))
            val = rs.rand(nnz).astype(np.float32) + 0.5
            score = float((val * w_true[idx]).sum())
            label = 1 if score > 0 else 0
            cols = " ".join(f"{i}:{v:.4f}" for i, v in zip(idx, val))
            f.write(f"{label} {cols}\n")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-features", type=int, default=10000)
    p.add_argument("--rank", type=int, default=8)
    p.add_argument("--rows", type=int, default=2000)
    p.add_argument("--nnz", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import tempfile

    import numpy as np

    import mxtpu as mx
    from mxtpu import kvstore, nd
    from mxtpu.io import LibSVMIter
    from mxtpu.ndarray import sparse

    mx.rng.seed(0)
    rs = np.random.RandomState(0)
    D, F = args.num_features, args.rank

    w_true = np.zeros(D, np.float32)
    active = rs.choice(D, D // 10, replace=False)
    w_true[active] = rs.randn(len(active)).astype(np.float32) * 2.0

    path = os.path.join(tempfile.mkdtemp(), "fm.libsvm")
    write_libsvm(path, rs, args.rows, D, args.nnz, w_true)

    # dist_sync semantics: named params live in the store; workers push
    # row-sparse grads and pull back only the rows they need
    kv = kvstore.create("dist_sync")
    w = nd.zeros((D, 1))
    V = nd.array(rs.randn(D, F).astype(np.float32) * 0.01)
    kv.init("w", w)
    kv.init("V", V)
    lr = args.lr

    def lazy_sgd(key, grad, stored):
        """Row-sparse updater: touch only pushed rows (lazy SGD parity)."""
        if getattr(grad, "stype", "default") == "row_sparse":
            rows = grad.indices.asnumpy().astype(int)
            dense = stored.data.at[rows].add(-lr * grad.data.data)
            stored._set_data(dense)
        else:
            stored._set_data(stored.data - lr * grad.data)

    kv._set_updater(lazy_sgd)

    def forward(X, w_rows, V_rows):
        """FM score + δ-ready pieces. X csr (B, D)."""
        xw = sparse.dot(X, w_rows)                     # (B, 1)
        xv = sparse.dot(X, V_rows)                     # (B, F)
        x2 = sparse.csr_matrix(
            (X.data.asnumpy() ** 2, X.indices.asnumpy(), X.indptr.asnumpy()),
            shape=X.shape)
        v2 = nd.array(np.asarray(V_rows.data) ** 2)
        x2v2 = sparse.dot(x2, v2)                      # (B, F)
        score = xw.data[:, 0] + 0.5 * (
            np.asarray(xv.data) ** 2 - np.asarray(x2v2.data)).sum(axis=1)
        return np.asarray(score), xv, x2

    hits = total = 0
    for epoch in range(args.epochs):
        it = LibSVMIter(data_libsvm=path, data_shape=(D,),
                        batch_size=args.batch_size)
        correct = seen = 0
        for batch in it:
            X = batch.data[0]                           # CSRNDArray
            y = batch.label[0].asnumpy().reshape(-1)
            n = X.shape[0] - batch.pad
            score, xv, x2 = forward(X, w, V)
            prob = 1.0 / (1.0 + np.exp(-score))
            correct += int(((prob > 0.5) == (y > 0.5))[:n].sum())
            seen += n
            delta = ((prob - y) / max(n, 1)).astype(np.float32)
            if batch.pad:
                delta[n:] = 0.0
            dnd = nd.array(delta[:, None])
            grad_w = sparse.dot(X, dnd, transpose_a=True)          # rsp (D,1)
            grad_v1 = sparse.dot(
                X, nd.array(delta[:, None] * np.asarray(xv.data)),
                transpose_a=True)                                  # rsp (D,F)
            g2 = sparse.dot(x2, dnd, transpose_a=True)             # rsp (D,1)
            rows = g2.indices.asnumpy().astype(int)
            grad_v = sparse.row_sparse_array(
                (np.asarray(grad_v1.data.data)
                 - np.asarray(g2.data.data) * np.asarray(V.data)[rows],
                 grad_v1.indices.asnumpy()), shape=(D, F))
            kv.push("w", grad_w)
            kv.push("V", grad_v)
            # true sparse pull: only the touched rows come back
            w_rows = sparse.row_sparse_array(
                (np.zeros((len(rows), 1), np.float32), rows), shape=(D, 1))
            kv.row_sparse_pull("w", out=w_rows, row_ids=nd.array(rows))
            kv.pull("w", out=w)
            kv.pull("V", out=V)
        acc = correct / max(seen, 1)
        print(f"epoch {epoch}: train_acc={acc:.3f} "
              f"(rank {kv.rank}/{kv.num_workers})")
        hits, total = correct, seen
    return hits / max(total, 1)


if __name__ == "__main__":
    acc = main()
    print(f"final accuracy: {acc:.3f}")
