#!/usr/bin/env python
"""Sharded SPMD training with the gluon + DataParallelTrainer path: one
compiled step over a dp x tp mesh (tensor-parallel Dense shardings), the
TPU-native equivalent of the reference's multi-GPU ``kv=device`` training.
Runs on however many devices are visible (use
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu to
simulate a pod on CPU)."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()

    import numpy as np
    import jax
    from jax.sharding import PartitionSpec as P

    import mxtpu as mx
    from mxtpu import gluon, nd, optimizer, parallel
    from mxtpu.gluon import nn

    n = len(jax.devices())
    tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = max(1, n // tp)
    mesh = parallel.make_mesh((dp, tp), ("dp", "tp"))
    print(f"devices={n} mesh=dp{dp} x tp{tp}")

    net = nn.HybridSequential()
    net.add(nn.Dense(256, activation="relu", in_units=64),
            nn.Dense(10, in_units=256))
    net.initialize(init=mx.initializer.Xavier())
    shardings = {"dense0_weight": P("tp", None), "dense0_bias": P("tp"),
                 "dense1_weight": P(None, "tp")}
    dpt = parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer.SGD(learning_rate=0.1, momentum=0.9), mesh,
        param_shardings=shardings)

    rs = np.random.RandomState(0)
    w_true = rs.randn(64, 10).astype(np.float32)
    for step in range(args.steps):
        x = rs.randn(args.batch_size, 64).astype(np.float32)
        y = np.argmax(x @ w_true, axis=1).astype(np.float32)
        loss = dpt.step(nd.array(x), nd.array(y))
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {loss:.4f}")


if __name__ == "__main__":
    main()
