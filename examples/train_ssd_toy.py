#!/usr/bin/env python
"""End-to-end SSD-style detector training — the reference's ``example/ssd``
flow on a toy synthetic task: images containing one axis-aligned bright box
whose class is its color channel; a small conv backbone with multibox heads
trains against ``contrib.MultiBoxTarget`` and decodes with
``contrib.MultiBoxDetection``.

Demonstrates the full detection stack composing for TRAINING (prior
generation → target matching with hard-negative mining → cls + smooth-L1
losses → decode + NMS), not just per-op correctness.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_batch(rs, n, size=64):
    """Images with one colored rectangle; labels (n, 1, 5) [cls,x1,y1,x2,y2]."""
    import numpy as np
    x = np.zeros((n, 3, size, size), np.float32)
    labels = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        w = rs.randint(size // 4, size // 2)
        h = rs.randint(size // 4, size // 2)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - h)
        cls = rs.randint(0, 3)
        x[i, cls, y0:y0 + h, x0:x0 + w] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + h) / size]
    return x, labels


def build_net(num_classes=3, num_anchors=3):
    from mxtpu.gluon import nn

    class ToySSD(nn.HybridSequential):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.backbone = nn.HybridSequential()
                for ch in (16, 32, 64):
                    self.backbone.add(
                        nn.Conv2D(ch, 3, strides=2, padding=1,
                                  activation="relu"))
                self.cls_head = nn.Conv2D(num_anchors * (num_classes + 1), 3,
                                          padding=1)
                self.loc_head = nn.Conv2D(num_anchors * 4, 3, padding=1)

        def forward(self, x):
            feat = self.backbone(x)
            return feat, self.cls_head(feat), self.loc_head(feat)

    return ToySSD()


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--batch-size", type=int, default=16)
    # note the step is doubly normalized: the loss divides by num_pos*B and
    # trainer.step(batch_size) divides by B again — lr is calibrated for that
    p.add_argument("--lr", type=float, default=0.4)
    p.add_argument("--eval-iou", type=float, default=0.4)
    args = p.parse_args()

    import numpy as np

    import mxtpu as mx
    from mxtpu import autograd, gluon, nd

    mx.rng.seed(0)  # deterministic init regardless of ambient rng state

    num_classes = 3
    sizes, ratios = (0.35, 0.6), (1.0, 2.0)
    num_anchors = len(sizes) + len(ratios) - 1
    net = build_net(num_classes, num_anchors)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    loc_loss = gluon.loss.HuberLoss()
    rs = np.random.RandomState(0)

    def heads(xb):
        feat, cls_raw, loc_raw = net(xb)
        B = cls_raw.shape[0]
        anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios)
        # priors enumerate position-major then anchor ((i*W+j)*A + a), so both
        # heads go NCHW -> NHWC -> (pos, anchor) before flattening
        cp = cls_raw.transpose((0, 2, 3, 1))            # (B, h, w, A*(C+1))
        cp = cp.reshape((B, -1, num_classes + 1))       # (B, hw*A, C+1)
        cls_preds = cp.transpose((0, 2, 1))             # (B, C+1, hw*A)
        loc_preds = loc_raw.transpose((0, 2, 3, 1)).reshape((B, -1))
        return anchors, cls_preds, loc_preds

    first = last = None
    for step in range(args.steps):
        xb_np, lb_np = make_batch(rs, args.batch_size)
        xb, lb = nd.array(xb_np), nd.array(lb_np)
        with autograd.record():
            anchors, cls_preds, loc_preds = heads(xb)
            loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
                anchors, lb, cls_preds, negative_mining_ratio=3.0)
            # cls: (B, C+1, A) -> per-anchor CE; mined-out anchors carry the
            # -1 ignore label and must be masked (sample_weight), exactly like
            # the reference's SoftmaxOutput ignore_label usage
            valid = cls_t >= 0
            lc = cls_loss(cls_preds.transpose((0, 2, 1)), nd.relu(cls_t),
                          sample_weight=valid)
            ll = loc_loss(loc_preds * loc_m, loc_t * loc_m)
            # normalize by matched-anchor count (standard SSD normalization):
            # per-sample means dilute the few contributing anchors otherwise
            A = cls_t.shape[1]
            num_pos = nd.sum(loc_m) / 4.0 + 1.0
            loss = (nd.sum(lc) + nd.sum(ll)) * A / (num_pos * cls_t.shape[0])
        loss.backward()
        trainer.step(args.batch_size)
        v = float(loss.asscalar())
        first = v if first is None else first
        last = v
        if step % 25 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {v:.4f}")

    # evaluate: decode detections on a fresh batch, report mean IoU@top-1
    xe_np, le_np = make_batch(rs, 32)
    with autograd.predict_mode():
        anchors, cls_preds, loc_preds = heads(nd.array(xe_np))
        probs = nd.softmax(cls_preds, axis=1)
        det = nd.contrib.MultiBoxDetection(probs, loc_preds, anchors,
                                           nms_threshold=0.45)
    d = det.asnumpy()
    ious, hits = [], 0
    for i in range(32):
        rows = d[i][d[i][:, 0] >= 0]
        if not len(rows):
            ious.append(0.0)
            continue
        best = rows[0]
        gt = le_np[i, 0]
        x1, y1, x2, y2 = np.maximum(best[2], gt[1]), np.maximum(best[3], gt[2]), \
            np.minimum(best[4], gt[3]), np.minimum(best[5], gt[4])
        inter = max(0, x2 - x1) * max(0, y2 - y1)
        a1 = (best[4] - best[2]) * (best[5] - best[3])
        a2 = (gt[3] - gt[1]) * (gt[4] - gt[2])
        iou = inter / max(a1 + a2 - inter, 1e-9)
        ious.append(iou)
        hits += int(best[0] == gt[0] and iou > args.eval_iou)
    print(f"loss {first:.3f} -> {last:.3f}; mean IoU {np.mean(ious):.3f}; "
          f"cls+IoU>{args.eval_iou} hits {hits}/32")
    return first, last, float(np.mean(ious)), hits


if __name__ == "__main__":
    main()
