#!/usr/bin/env python
"""MNIST MLP/LeNet through the Module API — the reference's canonical
``example/image-classification/train_mnist.py`` flow. Uses the synthetic
MNIST source when no dataset is present (zero-egress environment)."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--kv-store", default="local")
    args = p.parse_args()

    import mxtpu as mx
    from mxtpu import gluon, io
    from mxtpu.gluon import nn
    from mxtpu.module import Module

    flat = args.network == "mlp"
    train = io.MNISTIter(batch_size=args.batch_size, flat=flat)
    val = io.MNISTIter(batch_size=args.batch_size, flat=flat, seed=7)  # held out

    if args.network == "mlp":
        net = nn.HybridSequential()
        net.add(nn.Dense(128, activation="relu"),
                nn.Dense(64, activation="relu"), nn.Dense(10))
    else:
        from mxtpu.gluon.model_zoo import vision
        net = vision.lenet(classes=10)

    mod = Module(net)
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            kvstore=args.kv_store, num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    acc = dict(mod.score(val, "acc"))["accuracy"]
    print(f"final validation accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
