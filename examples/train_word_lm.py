#!/usr/bin/env python
"""Word-level language model — the reference's ``example/gluon/
word_language_model`` flow (Embedding → multi-layer LSTM → tied-weight
decoder, truncated BPTT with carried hidden state) on a synthetic corpus.

Zero-egress stand-in for WikiText: a deterministic order-2 Markov chain over
the vocabulary, so the data has real (and known) structure — an LM that learns
it reaches perplexity ≈ the chain's branching factor, far below the uniform
baseline of vocab_size. The training loop is the reference's: batchify to
(N_batch, T) streams, slide BPTT windows, detach state between windows,
clip gradients, decay LR.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_corpus(vocab: int, length: int, branch: int = 4, seed: int = 17):
    """First-order Markov chain: every token has ``branch`` fixed successors,
    drawn uniformly — per-token entropy log(branch), so a model that learns the
    transitions reaches perplexity ≈ branch."""
    import numpy as np
    rs = np.random.RandomState(seed)
    successors = rs.randint(vocab, size=(vocab, branch))
    data = np.empty(length, np.int64)
    data[0] = rs.randint(vocab)
    draws = rs.randint(branch, size=length)
    for t in range(1, length):
        data[t] = successors[data[t - 1], draws[t]]
    return data


def batchify(data, batch_size: int):
    """(len,) token stream → (batch, T) parallel streams (reference batchify)."""
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n)


class RNNModel:
    """Embedding → LSTM → decoder (optionally tied to the embedding)."""

    def __init__(self, vocab, embed, hidden, layers, dropout, tied):
        from mxtpu import gluon
        from mxtpu.gluon import nn, rnn

        self.tied = tied
        net = nn.HybridSequential()
        self.embedding = nn.Embedding(vocab, embed)
        self.lstm = rnn.LSTM(hidden, num_layers=layers, dropout=dropout,
                             layout="TNC", input_size=embed)
        self.drop = nn.Dropout(dropout)
        if tied:
            if embed != hidden:
                raise ValueError("--tied requires embed == hidden")
            self.decoder = None  # reuse embedding weight
        else:
            self.decoder = nn.Dense(vocab, in_units=hidden, flatten=False)
        self.blocks = [b for b in (self.embedding, self.lstm, self.drop,
                                   self.decoder) if b is not None]

    def initialize(self, init):
        for b in self.blocks:
            b.initialize(init=init)

    def collect_params(self):
        params = {}
        for b in self.blocks:
            params.update(b.collect_params()._params)
        return params

    def __call__(self, x, states):
        """x: (T, N) int tokens → logits (T, N, vocab), new states."""
        from mxtpu import nd
        emb = self.drop(self.embedding(x))
        out, states = self.lstm(emb, states)
        out = self.drop(out)
        if self.tied:
            w = self.embedding.weight.data()       # (vocab, embed)
            logits = nd.dot(out, w, transpose_b=True)
        else:
            logits = self.decoder(out)
        return logits, states


def detach(states):
    return [s.detach() for s in states]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--corpus-len", type=int, default=40000)
    p.add_argument("--branch", type=int, default=4)
    p.add_argument("--embed", type=int, default=64)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--tied", action="store_true")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--bptt", type=int, default=32)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=2.0)
    p.add_argument("--clip", type=float, default=0.25)
    p.add_argument("--cpu", action="store_true", help="force the CPU backend")
    args = p.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxtpu as mx
    from mxtpu import autograd, gluon, nd

    mx.rng.seed(0)
    corpus = make_corpus(args.vocab, args.corpus_len, args.branch)
    split = int(0.9 * len(corpus))
    train_data = batchify(corpus[:split], args.batch_size)
    valid_data = batchify(corpus[split:], args.batch_size)

    model = RNNModel(args.vocab, args.embed, args.hidden, args.layers,
                     args.dropout, args.tied)
    model.initialize(mx.initializer.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    params = model.collect_params()
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": args.lr})

    # one compiled BPTT window: hybridize-equivalent — the whole
    # embed→lstm→decode→loss graph runs as a single XLA program, with state
    # carried out (CachedOp re-traces once per train/predict mode)
    def window_loss(x, y, h, c):
        logits, (h2, c2) = model(x, [h, c])
        loss = loss_fn(logits.reshape((-1, args.vocab)), y.reshape((-1,)))
        return nd.mean(loss), h2, c2

    step = mx.jit.CachedOp(window_loss,
                           params=[p.data() for p in params.values()])

    def run_epoch(data, train: bool):
        total_loss, windows = 0.0, 0
        h, c = model.lstm.begin_state(args.batch_size)
        for start in range(0, data.shape[1] - 1 - args.bptt, args.bptt):
            x = nd.array(data[:, start:start + args.bptt].T.astype(np.int32))
            y = nd.array(
                data[:, start + 1:start + 1 + args.bptt].T.astype(np.int32))
            h, c = h.detach(), c.detach()
            if train:
                with autograd.record():
                    loss, h, c = step(x, y, h, c)
                loss.backward()
                gluon.utils.clip_global_norm(
                    [p.grad() for p in params.values()], args.clip)
                trainer.step(1)
            else:
                with autograd.predict_mode():
                    loss, h, c = step(x, y, h, c)
            total_loss += float(loss.asscalar())
            windows += 1
        return float(np.exp(total_loss / max(windows, 1)))

    uniform_ppl = args.vocab
    best = float("inf")
    for epoch in range(args.epochs):
        t0 = time.time()
        train_ppl = run_epoch(train_data, train=True)
        valid_ppl = run_epoch(valid_data, train=False)
        if valid_ppl >= best:          # reference: anneal LR when stuck
            trainer.set_learning_rate(trainer.learning_rate / 4.0)
        best = min(best, valid_ppl)
        print(f"epoch {epoch}: train_ppl={train_ppl:.2f} "
              f"valid_ppl={valid_ppl:.2f} (uniform={uniform_ppl}, "
              f"chain={args.branch}) lr={trainer.learning_rate:g} "
              f"[{time.time() - t0:.1f}s]")
    return best


if __name__ == "__main__":
    ppl = main()
    print(f"final valid perplexity: {ppl:.2f}")
