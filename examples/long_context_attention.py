#!/usr/bin/env python
"""Long-context attention demo — both sequence-parallel modes over one mesh.

The reference's longest-sequence tools were bucketing and fused RNNs; here a
single (B, H, T, D) attention call scales T across chips two ways:

* ring attention (``parallel.ring_attention``): K/V rotate around the ICI
  ring; per-device memory stays O(T/n) — the mode for sequences that don't
  fit even one head per device.
* all-to-all / Ulysses (``parallel.ulysses``): one collective reshuffles
  sequence-sharding into head-sharding, full attention runs per head group,
  one collective restores — two collectives total, the mode when heads >= n.

Both produce identical math; this demo runs a causal long-context pass with
each, checks they agree with the single-device oracle, and reports the
per-device memory footprint each mode holds.

Run on the virtual pod: JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/long_context_attention.py --seq-len 4096
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=1)
    args = p.parse_args(argv)

    import numpy as np

    import jax

    from mxtpu import nd, parallel
    from mxtpu.ops.attention import flash_chunk

    n = len(jax.devices())
    mesh = parallel.make_mesh((n,), ("sp",))
    B, H, T, D = args.batch, args.heads, args.seq_len, args.head_dim
    assert T % n == 0 and H % n == 0, "seq-len and heads must divide devices"

    rs = np.random.RandomState(0)
    q = rs.randn(B, H, T, D).astype(np.float32) * 0.5
    k = rs.randn(B, H, T, D).astype(np.float32) * 0.5
    v = rs.randn(B, H, T, D).astype(np.float32) * 0.5

    oracle = np.asarray(flash_chunk(q, k, v, True, 1.0 / D ** 0.5)[0])

    ring = parallel.ring_self_attention(nd.array(q), nd.array(k), nd.array(v),
                                        mesh=mesh, causal=True)
    uly = parallel.ulysses_self_attention(nd.array(q), nd.array(k),
                                          nd.array(v), mesh=mesh, causal=True)
    err_r = float(np.abs(ring.asnumpy() - oracle).max())
    err_u = float(np.abs(uly.asnumpy() - oracle).max())
    assert err_r < 2e-4 and err_u < 2e-4, (err_r, err_u)

    fp32 = 4
    per_dev_ring = 3 * B * H * (T // n) * D * fp32          # q,k,v chunks
    per_dev_uly = 3 * B * (H // n) * T * D * fp32           # full T, H/n heads
    print(f"devices={n} T={T} H={H} D={D}")
    print(f"ring:    max|err|={err_r:.2e}  resident qkv/device="
          f"{per_dev_ring / 1e6:.2f} MB (O(T/n))")
    print(f"ulysses: max|err|={err_u:.2e}  resident qkv/device="
          f"{per_dev_uly / 1e6:.2f} MB (full T, H/n heads)")
    print("LONG_CONTEXT_OK")


if __name__ == "__main__":
    main()
