#!/usr/bin/env python
"""Decoder-transformer language model — the flagship training workload
(``gluon.model_zoo.transformer_lm``: pre-LN blocks over the Pallas flash
attention kernel, tied softmax head) trained through ``DataParallelTrainer``.

Zero-egress stand-in for a text corpus: the same planted first-order Markov
chain as ``train_word_lm.py`` — per-token entropy log(branch), so a model
that learns the transitions reaches perplexity ≈ branch, far below the
uniform baseline of vocab_size. One fwd+bwd+Adam step is ONE compiled SPMD
program; sequences are non-overlapping windows of the token stream.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.train_word_lm import make_corpus  # noqa: E402  (same corpus)


def main(argv=None) -> float:
    import numpy as np

    import mxtpu as mx
    from mxtpu import nd, optimizer
    from mxtpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxtpu.gluon.model_zoo import transformer_lm
    from mxtpu.parallel import DataParallelTrainer
    from mxtpu.parallel.mesh import data_parallel_mesh

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--vocab", type=int, default=60)
    p.add_argument("--corpus-len", type=int, default=20000)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--units", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--micro-batches", type=int, default=1)
    args = p.parse_args(argv)

    mx.rng.seed(0)
    data = make_corpus(args.vocab, args.corpus_len)
    T = args.seq_len
    n_seq = (len(data) - 1) // T
    x_all = data[:n_seq * T].reshape(n_seq, T).astype(np.int32)
    y_all = data[1:n_seq * T + 1].reshape(n_seq, T).astype(np.float32)
    n_val = max(1, n_seq // 10)
    x_tr, y_tr = x_all[:-n_val], y_all[:-n_val]
    x_va, y_va = x_all[-n_val:], y_all[-n_val:]

    net = transformer_lm("tiny", vocab_size=args.vocab, units=args.units,
                         num_layers=args.layers, num_heads=args.heads,
                         max_len=max(256, T))
    net.initialize()

    class SeqLoss:
        def __call__(self, logits, y):
            b, t, v = logits.shape
            return SoftmaxCrossEntropyLoss()(
                logits.reshape((b * t, v)), y.reshape((b * t,)))

    dpt = DataParallelTrainer(net, SeqLoss(),
                              optimizer.Adam(learning_rate=args.lr),
                              data_parallel_mesh(),
                              micro_batches=args.micro_batches)

    B = args.batch_size
    n_batches = len(x_tr) // B
    for epoch in range(args.epochs):
        tic = time.time()
        perm = np.random.RandomState(epoch).permutation(len(x_tr))
        total = 0.0
        for i in range(n_batches):
            idx = perm[i * B:(i + 1) * B]
            total += dpt.step(nd.array(x_tr[idx]), nd.array(y_tr[idx]))
        print(f"epoch {epoch}: train loss {total / n_batches:.3f} "
              f"({time.time() - tic:.1f}s)")

    # validation perplexity, batched through the same block
    from mxtpu import autograd
    losses = []
    loss_fn = SeqLoss()
    for i in range(0, len(x_va), B):
        xb, yb = x_va[i:i + B], y_va[i:i + B]
        with autograd.predict_mode():
            logits = net(nd.array(xb))
            losses.append(float(
                nd.mean(loss_fn(logits, nd.array(yb))).asscalar())
                * len(xb))
    val_loss = sum(losses) / len(x_va)
    ppl = float(np.exp(val_loss))
    print(f"valid ppl {ppl:.2f} (uniform baseline {args.vocab})")
    return ppl


if __name__ == "__main__":
    main()
