#!/usr/bin/env python
"""Two-stage (Faster-RCNN-style) detector training through the SYMBOLIC
executor — the reference's ``example/rcnn`` flow on a toy task.

The full pipeline composes in one Symbol graph, exactly the reference's
architecture (rcnn/symbol/symbol_vgg.py analog):

  backbone convs → RPN head (objectness SoftmaxOutput w/ ignore labels +
  smooth_l1 bbox regression via make_loss) → ``contrib.Proposal`` (NMS'd
  region proposals from the live RPN outputs) → ``ROIPooling`` on the shared
  feature map → FC classifier head whose labels are assigned IN-GRAPH by a
  proposal-target subgraph (box_iou → pick/take/where) — the role of the
  reference's proposal_target operator.

RPN anchor targets are computed host-side per batch (the reference does the
same in its AnchorLoader, rcnn/core/loader.py). Training drives the raw
``simple_bind`` executor — forward / backward / SGD on the arg arrays — i.e.
the Module-API internals, on the GraphExecutor-equivalent.

Toy task: images contain one bright axis-aligned rectangle; its color channel
is its class (like examples/train_ssd_toy.py).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZE = 64          # input image H=W
STRIDE = 8         # backbone downsampling
FEAT = SIZE // STRIDE
SCALES = (2.0, 4.0)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
POST_NMS = 8       # proposals kept per image


def make_batch(rs, n):
    """One colored rectangle per image; returns images, gt corner boxes
    (pixels), gt classes."""
    import numpy as np
    x = np.zeros((n, 3, SIZE, SIZE), np.float32)
    boxes = np.zeros((n, 4), np.float32)
    cls = np.zeros((n,), np.float32)
    for i in range(n):
        w = rs.randint(SIZE // 4, SIZE // 2)
        h = rs.randint(SIZE // 4, SIZE // 2)
        x0 = rs.randint(0, SIZE - w)
        y0 = rs.randint(0, SIZE - h)
        c = rs.randint(0, 3)
        x[i, c, y0:y0 + h, x0:x0 + w] = 1.0
        boxes[i] = [x0, y0, x0 + w - 1, y0 + h - 1]
        cls[i] = c
    return x, boxes, cls


def anchors_hw_a():
    """The Proposal op's anchor grid, in its (h, w, A) layout. The reference's
    rcnn example ships the same generate_anchors math the op uses
    (rcnn/processing/generate_anchor.py mirroring proposal.cc)."""
    import numpy as np

    from mxtpu.ops.detection import _rpn_anchors
    return np.asarray(_rpn_anchors(FEAT, FEAT, STRIDE, SCALES, RATIOS))


def rpn_targets(anchors, gt_boxes):
    """Host-side anchor targets (AnchorLoader parity): objectness labels in
    {1 pos, 0 neg, -1 ignore} + bbox regression targets/weights, laid out to
    match the (2A|4A, h, w) conv heads."""
    import numpy as np

    n = gt_boxes.shape[0]
    K = anchors.shape[0]                       # FEAT*FEAT*A, (h, w, A) order
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + 0.5 * (aw - 1)
    ay = anchors[:, 1] + 0.5 * (ah - 1)

    labels = np.full((n, K), -1.0, np.float32)
    targets = np.zeros((n, K, 4), np.float32)
    weights = np.zeros((n, K, 4), np.float32)
    for i in range(n):
        g = gt_boxes[i]
        ix1 = np.maximum(anchors[:, 0], g[0])
        iy1 = np.maximum(anchors[:, 1], g[1])
        ix2 = np.minimum(anchors[:, 2], g[2])
        iy2 = np.minimum(anchors[:, 3], g[3])
        inter = np.clip(ix2 - ix1 + 1, 0, None) * np.clip(iy2 - iy1 + 1, 0, None)
        area_a = aw * ah
        area_g = (g[2] - g[0] + 1) * (g[3] - g[1] + 1)
        iou = inter / (area_a + area_g - inter)
        neg = iou < 0.3
        pos = iou >= 0.5
        pos[np.argmax(iou)] = True             # best anchor is always positive
        # subsample negatives to ~3x positives (AnchorLoader fg_fraction
        # parity) so the objectness head is not swamped by background
        neg_idx = np.flatnonzero(neg & ~pos)
        keep = min(len(neg_idx), 3 * int(pos.sum()) + 4)
        neg_keep = np.random.RandomState(i + 1).choice(neg_idx, keep,
                                                       replace=False)
        labels[i, neg_keep] = 0.0
        labels[i, pos] = 1.0
        gw = g[2] - g[0] + 1.0
        gh = g[3] - g[1] + 1.0
        gx = g[0] + 0.5 * (gw - 1)
        gy = g[1] + 0.5 * (gh - 1)
        targets[i, :, 0] = (gx - ax) / aw
        targets[i, :, 1] = (gy - ay) / ah
        targets[i, :, 2] = np.log(gw / aw)
        targets[i, :, 3] = np.log(gh / ah)
        weights[i, pos] = 1.0

    # (h, w, A) → the conv heads' channel-major layouts
    lab = labels.reshape(n, FEAT, FEAT, A).transpose(0, 3, 1, 2).reshape(n, -1)
    tgt = targets.reshape(n, FEAT, FEAT, A * 4).transpose(0, 3, 1, 2)
    wgt = weights.reshape(n, FEAT, FEAT, A * 4).transpose(0, 3, 1, 2)
    return lab, tgt, wgt


def build_symbol(batch, num_classes=3):
    """The full two-stage graph (symbol_vgg.py get_vgg_train analog)."""
    from mxtpu import symbol as sym

    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    rpn_label = sym.Variable("rpn_label")
    bbox_target = sym.Variable("bbox_target")
    bbox_weight = sym.Variable("bbox_weight")
    gt_boxes = sym.Variable("gt_boxes")
    gt_cls = sym.Variable("gt_cls")

    x = data
    for i, ch in enumerate((16, 32, 64)):
        x = sym.Convolution(x, num_filter=ch, kernel=(3, 3), stride=(2, 2),
                            pad=(1, 1), name=f"conv{i}")
        x = sym.Activation(x, act_type="relu")
    feat = x                                               # (N, 64, 8, 8)

    rpn = sym.Activation(
        sym.Convolution(feat, num_filter=32, kernel=(3, 3), pad=(1, 1),
                        name="rpn_conv"), act_type="relu")
    score = sym.Convolution(rpn, num_filter=2 * A, kernel=(1, 1),
                            name="rpn_cls_score")          # (N, 2A, h, w)
    bbox = sym.Convolution(rpn, num_filter=4 * A, kernel=(1, 1),
                           name="rpn_bbox_pred")           # (N, 4A, h, w)

    # RPN losses
    score_rs = sym.reshape(score, shape=(batch, 2, A * FEAT * FEAT))
    rpn_cls_loss = sym.SoftmaxOutput(score_rs, rpn_label, multi_output=True,
                                     use_ignore=True, ignore_label=-1,
                                     normalization="valid",
                                     name="rpn_cls_loss")
    rpn_bbox_loss = sym.make_loss(
        sym.sum(sym.smooth_l1((bbox - bbox_target) * bbox_weight, scalar=3.0)),
        grad_scale=1.0 / batch, name="rpn_bbox_loss")

    # proposals from the LIVE rpn outputs (gradients blocked, like the
    # reference where Proposal is non-differentiable)
    prob = sym.softmax(score_rs, axis=1)
    prob4 = sym.reshape(prob, shape=(batch, 2 * A, FEAT, FEAT))
    rois = sym.contrib.Proposal(
        cls_prob=sym.BlockGrad(prob4), bbox_pred=sym.BlockGrad(bbox),
        im_info=im_info, feature_stride=STRIDE, scales=SCALES, ratios=RATIOS,
        rpn_pre_nms_top_n=32, rpn_post_nms_top_n=POST_NMS, threshold=0.7,
        rpn_min_size=4, name="proposal")                   # (N*POST_NMS, 5)

    # proposal-target subgraph (in-graph role of proposal_target.py):
    # label each roi by IoU with its own image's gt box
    roi_boxes = sym.slice_axis(rois, axis=1, begin=1, end=5)
    roi_img = sym.reshape(sym.slice_axis(rois, axis=1, begin=0, end=1),
                          shape=(batch * POST_NMS,))
    iou = sym.contrib.box_iou(roi_boxes, gt_boxes, format="corner")
    own_iou = sym.pick(iou, roi_img)                       # (R,)
    roi_gt = sym.take(gt_cls, roi_img)                     # (R,)
    roi_label = sym.where(own_iou > 0.5, roi_gt + 1.0, sym.zeros_like(roi_gt))

    # stage-2 head on pooled features — trained on FROZEN shared features
    # (BlockGrad on feat): the in-graph rendering of the reference's
    # alternating-training schedule. Joint training at any useful ROI loss
    # scale lets the background-dominated stage-2 gradient swamp the shared
    # convs and collapse the RPN score map to the positive base rate; with
    # the feature path blocked, the head trains at full scale while the
    # RPN alone owns the backbone.
    pooled = sym.ROIPooling(sym.BlockGrad(feat), rois, pooled_size=(4, 4),
                            spatial_scale=1.0 / STRIDE)    # (R, 64, 4, 4)
    h1 = sym.Activation(sym.FullyConnected(sym.Flatten(pooled), num_hidden=64,
                                           name="fc6"), act_type="relu")
    cls_score = sym.FullyConnected(h1, num_hidden=num_classes + 1, name="cls")
    roi_cls_loss = sym.SoftmaxOutput(cls_score, sym.BlockGrad(roi_label),
                                     grad_scale=1.0, normalization="batch",
                                     name="roi_cls_loss")

    from mxtpu.symbol import Group
    return Group([rpn_cls_loss, rpn_bbox_loss, roi_cls_loss,
                  sym.BlockGrad(rois), sym.BlockGrad(roi_label)])


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxtpu as mx
    from mxtpu import nd

    mx.rng.seed(0)
    rs = np.random.RandomState(0)
    N = args.batch_size
    out = build_symbol(N)
    anchors = anchors_hw_a()

    input_shapes = {
        "data": (N, 3, SIZE, SIZE), "im_info": (N, 3),
        "rpn_label": (N, A * FEAT * FEAT),
        "bbox_target": (N, 4 * A, FEAT, FEAT),
        "bbox_weight": (N, 4 * A, FEAT, FEAT),
        "gt_boxes": (N, 4), "gt_cls": (N,),
    }
    grad_req = {n: ("null" if n in input_shapes else "write")
                for n in out.list_arguments()}
    ex = out.simple_bind(ctx=mx.current_context(), grad_req=grad_req,
                         **input_shapes)
    # Xavier init for weights, zeros for biases
    init = mx.initializer.Xavier(magnitude=2.0)
    for name, arr in ex.arg_dict.items():
        if name in input_shapes:
            continue
        if name.endswith("_bias"):
            arr._set_data(arr.data * 0)
        else:
            init(name, arr)

    im_info = np.tile([SIZE, SIZE, 1.0], (N, 1)).astype(np.float32)
    weight_names = [n for n in out.list_arguments() if n not in input_shapes]

    last = {}
    for step in range(args.steps):
        imgs, gtb, gtc = make_batch(rs, N)
        lab, tgt, wgt = rpn_targets(anchors, gtb)
        ex.forward(is_train=True, data=nd.array(imgs), im_info=nd.array(im_info),
                   rpn_label=nd.array(lab), bbox_target=nd.array(tgt),
                   bbox_weight=nd.array(wgt), gt_boxes=nd.array(gtb),
                   gt_cls=nd.array(gtc))
        ex.backward()
        for n in weight_names:                  # plain SGD on the executor
            ex.arg_dict[n]._set_data(
                ex.arg_dict[n].data - args.lr * ex.grad_dict[n].data)

        rpn_prob, _, roi_prob, rois, roi_label = [o.asnumpy() for o in ex.outputs]
        # metrics: RPN objectness accuracy on labeled anchors, ROI head accuracy
        fg_prob = rpn_prob[:, 1, :]
        labeled = lab >= 0
        rpn_acc = float((((fg_prob > 0.5) == (lab > 0.5)) & labeled).sum()
                        / max(labeled.sum(), 1))
        roi_acc = float((roi_prob.argmax(axis=1) == roi_label).mean())
        pos_frac = float((roi_label > 0).mean())
        last = {"rpn_acc": rpn_acc, "roi_acc": roi_acc, "pos_frac": pos_frac}
        if step % args.log_every == 0 or step == args.steps - 1:
            print(f"step {step:3d}: rpn_acc={rpn_acc:.3f} "
                  f"roi_acc={roi_acc:.3f} roi_pos_frac={pos_frac:.2f}")
    return last


if __name__ == "__main__":
    stats = main()
    print(f"final: {stats}")
