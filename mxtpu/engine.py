"""``mx.engine`` — bulk-execution control (python/mxnet/engine.py parity).

The reference's engine batches consecutive async ops into one engine op to cut
per-op dispatch overhead (op bulking, threaded_engine.h:404 BulkAppend/
BulkFlush, env ``MXNET_ENGINE_BULK_SIZE``). On TPU that concern is owned by
XLA: everything inside a ``jit``/``hybridize`` trace compiles into ONE fused
program, which is bulking taken to its limit — so these context managers keep
the reference API shape while documenting where the behavior went. They still
carry real information: the bulk size is recorded and queryable, and
``bulk(0)``/``set_bulk_size(0)`` is honored by running eagerly (no-op here,
since eager dispatch is already per-op).
"""

from __future__ import annotations

from contextlib import contextmanager

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 0


def set_bulk_size(size: int) -> int:
    """Set the bulk-execution budget; returns the previous value
    (engine.py set_bulk_size parity). Informational on TPU: fusion happens at
    jit boundaries, not dispatch time."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextmanager
def bulk(size: int):
    """``with mx.engine.bulk(n):`` scope (engine.py bulk parity). Under XLA the
    equivalent lever is hybridizing the enclosing block so the scope becomes
    one compiled program."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
