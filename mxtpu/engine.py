"""``mx.engine`` — bulk-execution control (python/mxnet/engine.py parity).

The reference's engine batches consecutive async ops into one engine op to cut
per-op dispatch overhead (op bulking, threaded_engine.h:404 BulkAppend/
BulkFlush, env ``MXNET_ENGINE_BULK_SIZE``, default 15). On TPU that concern
is owned by XLA — everything inside a jit trace compiles into ONE fused
program — and the framework-level equivalent of "bulk the whole step" is the
fused training-step executor (``mxtpu.step_cache.StepExecutor``), which
``Module.forward_backward`` uses by default.

So unlike earlier revisions, this knob is now a REAL lever:

* ``bulk_size() > 0`` (the default, from ``MXNET_ENGINE_BULK_SIZE`` or 15):
  training front-ends may compile forward+backward+update into one cached,
  donated XLA program.
* ``bulk(0)`` / ``set_bulk_size(0)``: forces the eager per-op dispatch path —
  the debugging mode where Monitor hooks fire, ``autograd`` records a real
  tape, and every op is a separate dispatch (exactly the reference's
  bulking opt-out).
"""

from __future__ import annotations

import os
from contextlib import contextmanager

__all__ = ["bulk", "set_bulk_size", "bulk_size", "DEFAULT_BULK_SIZE"]

# reference default: MXNET_ENGINE_BULK_SIZE=15 (docs/faq/env_var.md)
DEFAULT_BULK_SIZE = int(os.environ.get("MXNET_ENGINE_BULK_SIZE", "15"))

_bulk_size = DEFAULT_BULK_SIZE


def set_bulk_size(size: int) -> int:
    """Set the bulk-execution budget; returns the previous value
    (engine.py set_bulk_size parity). ``0`` disables step fusion — training
    front-ends fall back to eager per-op dispatch."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


def bulk_size() -> int:
    """Current bulk budget. ``0`` means eager per-op execution; any positive
    value lets the step executor fuse whole training steps."""
    return _bulk_size


@contextmanager
def bulk(size: int):
    """``with mx.engine.bulk(n):`` scope (engine.py bulk parity).
    ``bulk(0)`` scopes the eager opt-out; any positive size re-enables step
    fusion inside the scope."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
