"""Optimizers — parity with ``python/mxnet/optimizer.py`` (SGD family, Adam family,
Ada*/RMSProp/Ftrl/FTML/Signum/SGLD/DCASGD, SURVEY.md §2.5) and with the reference's
*fused update ops* (src/operator/optimizer_op-inl.h): each optimizer's math is one
jitted XLA kernel with donated buffers, so the weight update is a single fused
HBM-bandwidth-bound pass — the TPU equivalent of the hand-fused CUDA update kernels.

Design: ``create_state(index, weight)`` returns a tuple of raw jax arrays;
``update(index, weight, grad, state)`` mutates the NDArray handle in place and returns
the new state. ``multi_precision`` keeps an fp32 master copy for fp16/bf16 weights
(optimizer.py SGD multi-precision parity).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Registry, capture_init_spec
from .lr_scheduler import LRScheduler
from .ndarray.ndarray import NDArray

registry = Registry("optimizer")
register = registry.register


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    return registry.get(name)(**kwargs)


class Optimizer:
    # ZeRO eligibility (parallel/zero.py): True when the update math is
    # purely elementwise, so concatenating params into flat buckets and
    # updating each device's shard is exact — this also licenses stage 2/3
    # (parallel/fsdp.py), where the same kernel runs on reduce-scattered
    # grad shards and fsdp-sharded params/slots. Norm-coupled (LBSGD) or
    # noise-injecting (SGLD) optimizers must opt out.
    elementwise = True

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        capture_init_spec(cls)

    def __init__(self, learning_rate: float = 0.01, wd: float = 0.0,
                 rescale_grad: float = 1.0, clip_gradient: Optional[float] = None,
                 lr_scheduler: Optional[LRScheduler] = None,
                 multi_precision: bool = False, param_dict: Optional[dict] = None,
                 begin_num_update: int = 0, **kwargs):
        self.lr = learning_rate
        self.wd = wd
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self._index_update_count: Dict[Any, int] = {}
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}
        self.param_dict = param_dict or {}
        self._jitted: Optional[Callable] = None

    # -- reference API ----------------------------------------------------
    def set_learning_rate(self, lr: float):
        self.lr = lr
        if self.lr_scheduler is not None:
            self.lr_scheduler.base_lr = lr

    @property
    def learning_rate(self) -> float:
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult: dict):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: dict):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        self._index_update_count[index] = self._index_update_count.get(index, 0) + 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _get_lr(self, index) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        p = self.param_dict.get(index)
        if p is not None and getattr(p, "lr_mult", None) is not None:
            lr *= p.lr_mult
        return lr * self.lr_mult.get(index, 1.0)

    def _get_wd(self, index) -> float:
        wd = self.wd
        p = self.param_dict.get(index)
        if p is not None and getattr(p, "wd_mult", None) is not None:
            wd *= p.wd_mult
        return wd * self.wd_mult.get(index, 1.0)

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight: NDArray) -> Tuple:
        return ()

    def create_state_multi_precision(self, index, weight: NDArray) -> Tuple:
        if self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16):
            master = weight.data.astype(jnp.float32)
            return (master,) + self.create_state(index, NDArray(master))
        return self.create_state(index, weight)

    # -- update -----------------------------------------------------------
    def _kernel(self, weight, grad, lr, wd, t, *state):
        """Pure update math: returns (new_weight, *new_state). Override."""
        raise NotImplementedError

    def _preprocess_grad(self, grad, rescale, clip):
        g = grad * rescale
        if clip is not None:
            g = jnp.clip(g, -clip, clip)
        return g

    def _get_jitted(self, clipped: bool):
        # rescale/clip are traced arguments (Trainer mutates rescale_grad per step —
        # a value frozen at trace time would silently mis-scale partial batches);
        # only clip's presence is a static variant.
        if self._jitted is None:
            self._jitted = {}
        if clipped not in self._jitted:
            def stepfn(w, g, lr, wd, rescale, clip, t, *st):
                g = self._preprocess_grad(g.astype(w.dtype), rescale,
                                          clip if clipped else None)
                return self._kernel(w, g, lr, wd, t, *st)
            self._jitted[clipped] = jax.jit(stepfn, donate_argnums=(0,))
        return self._jitted[clipped]

    # -- lazy (row-sparse) update -----------------------------------------
    # Reference parity: optimizer.py:445 SGD lazy_update / sparse adam — only rows
    # present in the row_sparse gradient are touched, including their optimizer
    # state. On TPU this is one fused gather → kernel-on-rows → scatter program; the
    # dense kernel is reused on the row slab, so every optimizer gets a lazy variant
    # for free.
    def _get_sparse_jitted(self, clipped: bool):
        key = ("sparse", clipped)
        if self._jitted is None:
            self._jitted = {}
        if key not in self._jitted:
            def stepfn(w, rows, vals, lr, wd, rescale, clip, t, *st):
                g = self._preprocess_grad(vals.astype(w.dtype), rescale,
                                          clip if clipped else None)
                w_rows = w[rows]
                row_like = [getattr(s, "shape", None) == w.shape for s in st]
                st_rows = [s[rows] if rl else s for s, rl in zip(st, row_like)]
                out = self._kernel(w_rows, g, lr, wd, t, *st_rows)
                new_rows, *new_st_rows = out if isinstance(out, tuple) else (out,)
                new_w = w.at[rows].set(new_rows)
                new_st = [s.at[rows].set(ns) if rl else ns
                          for s, ns, rl in zip(st, new_st_rows, row_like)]
                return (new_w, *new_st)
            self._jitted[key] = jax.jit(stepfn, donate_argnums=(0,))
        return self._jitted[key]

    def _update_rowsparse(self, index, weight: NDArray, grad, state: Tuple) -> Tuple:
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clipped = self.clip_gradient is not None
        clip = self.clip_gradient if clipped else 0.0
        jitted = self._get_sparse_jitted(clipped)
        dt = weight.data.dtype
        out = jitted(weight.data, grad.indices.data, grad.data.data,
                     jnp.asarray(lr, dt), jnp.asarray(wd, dt),
                     jnp.asarray(self.rescale_grad, dt), jnp.asarray(clip, dt),
                     t, *state)
        new_w, *new_state = out if isinstance(out, tuple) else (out,)
        weight._set_data(new_w)
        return tuple(new_state)

    def update(self, index, weight: NDArray, grad: NDArray, state: Tuple) -> Tuple:
        if getattr(grad, "stype", "default") == "row_sparse":
            return self._update_rowsparse(index, weight, grad, state)
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        clipped = self.clip_gradient is not None
        jitted = self._get_jitted(clipped)
        clip = self.clip_gradient if clipped else 0.0

        use_master = (self.multi_precision and state
                      and isinstance(state, tuple) and len(state) > 0
                      and weight.dtype in (jnp.float16, jnp.bfloat16))
        if use_master:
            master, *rest = state
            out = jitted(master, grad.data.astype(jnp.float32),
                         jnp.float32(lr), jnp.float32(wd),
                         jnp.float32(self.rescale_grad), jnp.float32(clip), t, *rest)
            new_master, *new_state = out if isinstance(out, tuple) else (out,)
            weight._set_data(new_master.astype(weight.dtype))
            return (new_master, *new_state)
        dt = weight.data.dtype
        out = jitted(weight.data, grad.data, jnp.asarray(lr, dt),
                     jnp.asarray(wd, dt), jnp.asarray(self.rescale_grad, dt),
                     jnp.asarray(clip, dt), t, *state)
        if isinstance(out, tuple):
            new_w, *new_state = out
        else:
            new_w, new_state = out, []
        weight._set_data(new_w)
        return tuple(new_state)

    def update_multi_precision(self, index, weight, grad, state):
        return self.update(index, weight, grad, state)


# subclasses WITHOUT their own __init__ (SGLD, NAG, Test, …) reach the base
# ctor directly — wrap it too so their spec is still captured
capture_init_spec(Optimizer)


@register(name="sgd")
class SGD(Optimizer):
    """SGD w/ momentum + weight decay (optimizer.py:444; fused sgd_mom_update parity)."""

    def __init__(self, momentum: float = 0.0, lazy_update: bool = True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return (jnp.zeros(weight.shape, weight.data.dtype),)
        return ()

    def _kernel(self, w, g, lr, wd, t, *state):
        g = g + wd * w
        if self.momentum == 0.0:
            return w - lr * g
        (mom,) = state
        mom = self.momentum * mom - lr * g
        return w + mom, mom


@register(name="nag")
class NAG(SGD):
    """Nesterov accelerated SGD (optimizer.py NAG)."""

    def _kernel(self, w, g, lr, wd, t, *state):
        g = g + wd * w
        if self.momentum == 0.0:
            return w - lr * g
        (mom,) = state
        mom = self.momentum * mom + g
        return w - lr * (g + self.momentum * mom), mom


@register(name="signum")
class Signum(Optimizer):
    """Sign-based SGD w/ momentum (optimizer.py Signum; signsgd_update parity)."""

    def __init__(self, learning_rate: float = 0.01, momentum: float = 0.9,
                 wd_lh: float = 0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return (jnp.zeros(weight.shape, weight.data.dtype),)
        return ()

    def _kernel(self, w, g, lr, wd, t, *state):
        if self.momentum == 0.0:
            return w - lr * (jnp.sign(g + wd * w))
        (mom,) = state
        mom = self.momentum * mom - (1 - self.momentum) * (g + wd * w)
        return (1 - lr * self.wd_lh) * w + lr * jnp.sign(mom), mom


@register(name="sgld")
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (optimizer.py SGLD)."""

    elementwise = False          # injects fresh noise per param (custom update)

    def update(self, index, weight, grad, state):
        from . import rng
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess_grad(grad.data, self.rescale_grad,
                                  self.clip_gradient) + wd * weight.data
        noise = jnp.sqrt(lr) * jax.random.normal(rng.next_key(), weight.shape,
                                                 weight.data.dtype)
        weight._set_data(weight.data - lr / 2 * g + noise)
        return state


@register(name="dcasgd")
class DCASGD(Optimizer):
    """Delay-compensated async SGD (optimizer.py DCASGD)."""

    def __init__(self, momentum: float = 0.0, lamda: float = 0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight.data.dtype),
                jnp.array(weight.data))  # (mom, previous_weight)

    def _kernel(self, w, g, lr, wd, t, mom, prev_w):
        g = g + wd * w
        comp = g + self.lamda * g * g * (w - prev_w)
        mom = self.momentum * mom - lr * comp
        new_w = w + mom
        return new_w, mom, new_w


@register(name="adam")
class Adam(Optimizer):
    """Adam (optimizer.py:1069; fused adam_update parity)."""

    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (z, z)

    def _kernel(self, w, g, lr, wd, t, m, v):
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        coef = lr * jnp.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        return w - coef * m / (jnp.sqrt(v) + self.epsilon), m, v


@register(name="adamax")
class Adamax(Adam):
    def __init__(self, learning_rate: float = 0.002, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def _kernel(self, w, g, lr, wd, t, m, u):
        g = g + wd * w
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        return w - lr / (1 - self.beta1 ** t) * m / (u + self.epsilon), m, u


@register(name="nadam")
class Nadam(Adam):
    def __init__(self, learning_rate: float = 0.001, schedule_decay: float = 0.004,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        # momentum schedule Π mom_i is carried in state (the kernel is jitted, so a
        # Python-side accumulator would freeze at trace time)
        return (z, z, jnp.ones((), weight.data.dtype))

    def _kernel(self, w, g, lr, wd, t, m, v, m_sched_prev):
        g = g + wd * w
        mom_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mom_t1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        m_sched = m_sched_prev * mom_t
        m_sched_next = m_sched * mom_t1
        gp = g / (1 - m_sched)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        mp = m / (1 - m_sched_next)
        vp = v / (1 - self.beta2 ** t)
        m_bar = (1 - mom_t) * gp + mom_t1 * mp
        return w - lr * m_bar / (jnp.sqrt(vp) + self.epsilon), m, v, m_sched


@register(name="adagrad")
class AdaGrad(Optimizer):
    def __init__(self, eps: float = 1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight.data.dtype),)

    def _kernel(self, w, g, lr, wd, t, hist):
        g = g + wd * w
        hist = hist + g * g
        return w - lr * g / (jnp.sqrt(hist) + self.float_stable_eps), hist


@register(name="adadelta")
class AdaDelta(Optimizer):
    def __init__(self, rho: float = 0.9, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (z, z)

    def _kernel(self, w, g, lr, wd, t, acc_g, acc_d):
        g = g + wd * w
        acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        delta = jnp.sqrt(acc_d + self.epsilon) / jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * acc_d + (1 - self.rho) * delta * delta
        return w - delta, acc_g, acc_d


@register(name="rmsprop")
class RMSProp(Optimizer):
    """RMSProp, centered variant included (optimizer.py RMSProp)."""

    def __init__(self, learning_rate: float = 0.001, gamma1: float = 0.9,
                 gamma2: float = 0.9, epsilon: float = 1e-8, centered: bool = False,
                 clip_weights: Optional[float] = None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon, self.centered, self.clip_weights = epsilon, centered, clip_weights

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (z, z, z) if self.centered else (z,)

    def _kernel(self, w, g, lr, wd, t, *state):
        g = g + wd * w
        if not self.centered:
            (n,) = state
            n = (1 - self.gamma1) * g * g + self.gamma1 * n
            new_w = w - lr * g / jnp.sqrt(n + self.epsilon)
            out_state = (n,)
        else:
            n, mean_g, delta = state
            n = (1 - self.gamma1) * g * g + self.gamma1 * n
            mean_g = (1 - self.gamma1) * g + self.gamma1 * mean_g
            delta = self.gamma2 * delta - lr * g / jnp.sqrt(
                n - mean_g * mean_g + self.epsilon)
            new_w = w + delta
            out_state = (n, mean_g, delta)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return (new_w,) + out_state


@register(name="ftrl")
class Ftrl(Optimizer):
    def __init__(self, lamda1: float = 0.01, learning_rate: float = 0.1,
                 beta: float = 1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (z, z)  # (z_acc, n_acc)

    def _kernel(self, w, g, lr, wd, t, z, n):
        g = g + wd * w
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + g * g
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) / ((self.beta + jnp.sqrt(n)) / lr + wd),
            0.0).astype(w.dtype)
        return new_w, z, n


@register(name="ftml")
class FTML(Optimizer):
    def __init__(self, learning_rate: float = 0.0025, beta1: float = 0.6,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        return (z, z, z)  # (d, v, z)

    def _kernel(self, w, g, lr, wd, t, d, v, z):
        g = g + wd * w
        v = self.beta2 * v + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * w
        return -z / d_t, d_t, v, z


@register(name="lbsgd")
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptive rate (optimizer.py LBSGD)."""

    elementwise = False          # layer-wise norms couple the whole tensor

    def __init__(self, warmup_strategy: str = "linear", warmup_epochs: int = 5,
                 batch_scale: float = 1.0, updates_per_epoch: int = 32, **kwargs):
        super().__init__(**kwargs)
        self.warmup_strategy = warmup_strategy

    def _kernel(self, w, g, lr, wd, t, *state):
        wnorm = jnp.sqrt(jnp.sum(w * w))
        gnorm = jnp.sqrt(jnp.sum(g * g))
        phi = jnp.where((wnorm > 0) & (gnorm > 0),
                        wnorm / (gnorm + wd * wnorm + 1e-12), 1.0)
        return super()._kernel(w, g, lr * jnp.minimum(phi, 10.0), wd, t, *state)


@register(name="test", aliases=("sgd_test",))
class Test(Optimizer):
    """Plain SGD without extras — the reference's Test optimizer for unit tests."""

    def create_state(self, index, weight):
        return ()

    def _kernel(self, w, g, lr, wd, t):
        return w - lr * (g + wd * w)


# ---------------------------------------------------------------------------
# Updater — kvstore server-side application (optimizer.py Updater/get_updater)
# ---------------------------------------------------------------------------


class Updater:
    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Tuple] = {}

    def __call__(self, index, grad: NDArray, weight: NDArray):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        self.states[index] = self.optimizer.update(index, weight, grad,
                                                  self.states[index])

    def get_states(self):
        import pickle
        return pickle.dumps({k: [jax.device_get(s) for s in v]
                             for k, v in self.states.items()})

    def set_states(self, blob):
        import pickle
        raw = pickle.loads(blob)
        self.states = {k: tuple(jnp.asarray(s) for s in v) for k, v in raw.items()}


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
