"""Evaluation metrics — parity with ``python/mxnet/metric.py`` (1,424 LoC registry:
Accuracy/TopK/F1/MCC/Perplexity/MAE/MSE/RMSE/CrossEntropy/NLL/PearsonCorrelation/Loss +
CompositeEvalMetric + custom)."""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from .base import Registry
from .ndarray.ndarray import NDArray

registry = Registry("metric")
register = registry.register


def create(spec, **kwargs) -> "EvalMetric":
    if isinstance(spec, EvalMetric):
        return spec
    if isinstance(spec, (list, tuple)):
        return CompositeEvalMetric([create(s) for s in spec])
    if callable(spec):
        return CustomMetric(spec, **kwargs)
    return registry.get(spec)(**kwargs)


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def check_label_shapes(labels, preds, shape: bool = False):
    if len(labels) != len(preds):
        raise ValueError(f"labels/preds length mismatch: {len(labels)} vs {len(preds)}")


class EvalMetric:
    def __init__(self, name: str, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register(name="acc", aliases=("accuracy",))
class Accuracy(EvalMetric):
    def __init__(self, axis: int = 1, name: str = "accuracy", **kwargs):
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_list(labels), _as_list(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _np(pred), _np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(np.int32).ravel()
            label = label.astype(np.int32).ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register(name="top_k_accuracy", aliases=("top_k_acc",))
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k: int = 1, name: str = "top_k_accuracy", **kwargs):
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label).astype(np.int32).ravel()
            topk = np.argsort(-pred, axis=-1)[:, :self.top_k]
            self.sum_metric += (topk == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


class _BinaryClassificationStats:
    def __init__(self):
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, label, pred):
        pred_label = pred.argmax(axis=-1) if pred.ndim > 1 else (pred > 0.5)
        pred_label = pred_label.astype(np.int32).ravel()
        label = label.astype(np.int32).ravel()
        self.tp += int(((pred_label == 1) & (label == 1)).sum())
        self.fp += int(((pred_label == 1) & (label == 0)).sum())
        self.tn += int(((pred_label == 0) & (label == 0)).sum())
        self.fn += int(((pred_label == 0) & (label == 1)).sum())

    @property
    def precision(self):
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0

    @property
    def recall(self):
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0

    @property
    def f1(self):
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if p + r else 0.0

    @property
    def mcc(self):
        d = math.sqrt((self.tp + self.fp) * (self.tp + self.fn)
                      * (self.tn + self.fp) * (self.tn + self.fn))
        return ((self.tp * self.tn - self.fp * self.fn) / d) if d else 0.0

    @property
    def total(self):
        return self.tp + self.fp + self.tn + self.fn


@register(name="f1")
class F1(EvalMetric):
    def __init__(self, name: str = "f1", average: str = "macro", **kwargs):
        self.average = average
        self._stats = _BinaryClassificationStats()
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        if hasattr(self, "_stats"):
            self._stats = _BinaryClassificationStats()

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._stats.update(_np(label), _np(pred))
        self.sum_metric = self._stats.f1 * self._stats.total
        self.num_inst = self._stats.total


@register(name="mcc")
class MCC(F1):
    def __init__(self, name: str = "mcc", **kwargs):
        super().__init__(name=name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._stats.update(_np(label), _np(pred))
        self.sum_metric = self._stats.mcc * self._stats.total
        self.num_inst = self._stats.total


@register(name="mae")
class MAE(EvalMetric):
    def __init__(self, name: str = "mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _np(label), _np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)  # reference MAE reshape
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += np.abs(label - pred).mean()
            self.num_inst += 1


@register(name="mse")
class MSE(EvalMetric):
    def __init__(self, name: str = "mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _np(label), _np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)  # reference MSE reshape
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2).mean()
            self.num_inst += 1


@register(name="rmse")
class RMSE(MSE):
    def __init__(self, name: str = "rmse", **kwargs):
        EvalMetric.__init__(self, name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.sqrt(self.sum_metric / self.num_inst)


@register(name="ce", aliases=("cross-entropy", "crossentropy"))
class CrossEntropy(EvalMetric):
    def __init__(self, eps: float = 1e-12, name: str = "cross-entropy", **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _np(label).astype(np.int64).ravel()
            pred = _np(pred)
            prob = pred[np.arange(label.shape[0]), label]
            self.sum_metric += (-np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register(name="nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps: float = 1e-12, name: str = "nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register(name="perplexity")
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label: Optional[int] = None, axis: int = -1,
                 name: str = "perplexity", **kwargs):
        self.ignore_label = ignore_label
        self.axis = axis
        EvalMetric.__init__(self, name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _np(label).astype(np.int64).ravel()
            pred = _np(pred).reshape(-1, _np(pred).shape[-1])
            prob = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += -np.log(np.maximum(prob, 1e-12)).sum()
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@register(name="pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name: str = "pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _np(label).ravel(), _np(pred).ravel()
            self.sum_metric += float(np.corrcoef(label, pred)[0, 1])
            self.num_inst += 1


@register(name="loss")
class Loss(EvalMetric):
    """Dummy metric reporting the mean of the outputs (metric.py Loss)."""

    def __init__(self, name: str = "loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            self.sum_metric += float(_np(pred).sum())
            self.num_inst += _np(pred).size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name: str = "composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            for n, v in m.get_name_value():
                names.append(n)
                values.append(v)
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name: Optional[str] = None, allow_extra_outputs=False,
                 **kwargs):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            out = self._feval(_np(label), _np(pred))
            if isinstance(out, tuple):
                s, n = out
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += out
                self.num_inst += 1


def np_metric(numpy_feval, name=None, allow_extra_outputs=False):
    """Decorator parity with mx.metric.np."""
    def wrapper(label, pred):
        return numpy_feval(label, pred)
    return CustomMetric(wrapper, name or numpy_feval.__name__, allow_extra_outputs)
