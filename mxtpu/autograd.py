"""Imperative autograd — record/pause scopes, tape, backward.

Capability parity with the reference autograd (``src/imperative/imperative.cc`` +
``python/mxnet/autograd.py``: record/pause/train_mode/predict_mode scopes, MarkVariables,
Backward, custom Function), redesigned for JAX:

* The reference's tape is a dynamic NNVM graph with per-node ``AGInfo`` and hand-written
  ``FGradient`` rules. Here the tape is a list of nodes, each holding a **pure
  JAX-traceable closure** of the op it recorded; ``backward()`` walks the tape in
  reverse and gets each node's input cotangents from ``jax.vjp`` — no per-op gradient
  registrations exist anywhere in the framework.
* Hybridized blocks record as a SINGLE node whose closure is the whole compiled
  step (mirroring CachedOp being one node in the reference's graph,
  src/imperative/cached_op.cc Backward :1046).
* ``Function`` (user-defined forward/backward, autograd.py:332-509) records a node with
  an explicit backward callable instead of a vjp.

The scopes also carry the thread-local ``is_training`` flag consumed by Dropout/BatchNorm
(`MXAutogradSetIsTraining` parity).

Performance stance (deliberate): eager ``backward()`` calls ``jax.vjp`` per tape
node, which re-executes that node's forward to build the vjp — eager backward
costs ~2x an eager forward and is unjitted. This is the DEBUGGING path, exactly
as imperative mode is the slow path in the reference (its imperative ops skip
graph optimization too). The production path is ``hybridize()``/``CachedOp``/
``DataParallelTrainer``, where forward+backward+update trace into ONE compiled
XLA program and the tape holds a single node. Per-node vjp caching would only
accelerate the path nobody should be on — rejected in favor of keeping the tape
replay-correct and simple.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "backward", "grad",
           "mark_variables", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.retained = []
    if not hasattr(_state, "retained"):
        _state.retained = []
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, flag
    return prev


def set_training(flag: bool) -> bool:
    st = _st()
    prev, st.training = st.training, flag
    return prev


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._prev
        return False


def record(train_mode: bool = True) -> _Scope:
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------


class _VariableEntry:
    """A gradient sink created by attach_grad (MarkVariables parity)."""

    __slots__ = ("handle", "grad_req")

    def __init__(self, handle, grad_req: str):
        self.handle = handle
        self.grad_req = grad_req


class _TapeNode:
    __slots__ = ("pure_fn", "raw_inputs", "parent_entries", "n_outputs",
                 "backward_fn", "saved")

    def __init__(self, pure_fn, raw_inputs, parent_entries, n_outputs,
                 backward_fn=None, saved=None):
        self.pure_fn = pure_fn            # raw_in -> raw_out(s); None if backward_fn set
        self.raw_inputs = raw_inputs      # list of jax arrays captured at record time
        self.parent_entries = parent_entries  # per input: entry | None
        self.n_outputs = n_outputs
        self.backward_fn = backward_fn    # explicit: (saved, out_grads) -> in_grads
        self.saved = saved


def _mark_variable(handle, grad_req: str = "write"):
    from .ndarray.ndarray import NDArray
    entry = _VariableEntry(handle, grad_req)
    handle._grad_entry = entry
    handle._grad = NDArray(jnp.zeros_like(handle._data))


def retain_grad(handle):
    """Request the gradient of a NON-leaf (tape-produced) array: its cotangent
    is flushed into ``handle.grad`` at the next backward, WITHOUT detaching it
    from the recorded graph (attach_grad would sever the producing edge —
    torch's retain_grad semantics, needed by Module.inputs_need_grad when the
    input is another module's output on the same tape)."""
    if handle._grad_entry is None:
        _mark_variable(handle)
        return
    _st().retained.append(handle)


def mark_variables(variables, gradients=None, grad_reqs="write"):
    """Parity with mx.autograd.mark_variables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, req in zip(variables, grad_reqs):
        _mark_variable(v, req)


def _record(op, args, kwargs, nd_in, outs):
    """Called by ops.registry.invoke while recording (RecordOp parity).

    ``nd_in`` positions are ints (positional args) or strs (keyword args) — both are
    replayed through ``pure_fn`` so kwarg tensors receive gradients too.
    """
    positions = [i for i, _ in nd_in]
    raw_inputs = [a.data for _, a in nd_in]
    parent_entries = [a._grad_entry for _, a in nd_in]
    template = list(args)
    fixed_kwargs = dict(kwargs)
    fn = op.fn

    def pure_fn(*raw):
        full = list(template)
        kw = dict(fixed_kwargs)
        for p, r in zip(positions, raw):
            if isinstance(p, str):
                kw[p] = r
            else:
                full[p] = r
        full = [a.data if hasattr(a, "data") and hasattr(a, "_grad_entry") else a
                for a in full]
        kw = {k: (v.data if hasattr(v, "data") and hasattr(v, "_grad_entry") else v)
              for k, v in kw.items()}
        return fn(*full, **kw)

    node = _TapeNode(pure_fn, raw_inputs, parent_entries, len(outs))
    for j, o in enumerate(outs):
        o._grad_entry = (node, j)
    _st().tape.append(node)


def record_custom_node(pure_fn, input_handles, outputs, backward_fn=None, saved=None):
    """Record one node for a composite computation (CachedOp / custom Function)."""
    node = _TapeNode(pure_fn, [h.data for h in input_handles],
                     [h._grad_entry for h in input_handles], len(outputs),
                     backward_fn=backward_fn, saved=saved)
    for j, o in enumerate(outputs):
        o._grad_entry = (node, j)
    _st().tape.append(node)
    return node


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _entry_key(entry):
    if isinstance(entry, _VariableEntry):
        return ("var", id(entry))
    node, j = entry
    return ("out", id(node), j)


def _accum(a, b):
    """Cotangent accumulation; composes row-sparse carriers with dense arrays."""
    from .ndarray.sparse import RawRowSparse
    if isinstance(a, RawRowSparse):
        return a + b
    if isinstance(b, RawRowSparse):
        return b + a
    return a + b


def _dense_cot(g):
    """Densify a row-sparse cotangent before feeding it to a vjp."""
    from .ndarray.sparse import RawRowSparse
    return g.densify() if isinstance(g, RawRowSparse) else g


def _flush_grad(h, entry, g):
    """Write a backward result into a variable's ``.grad`` buffer, honoring
    grad_req and materializing row-sparse cotangents as RowSparseNDArray (the
    reference's grad_stype='row_sparse' surface for lazy optimizers)."""
    from .ndarray.ndarray import NDArray
    from .ndarray import sparse as sp
    if isinstance(g, sp.RawRowSparse):
        if entry.grad_req == "add":
            if isinstance(h._grad, sp.RowSparseNDArray):
                uniq, vals = g.dedup()
                h._grad = sp.add(h._grad,
                                 sp.RowSparseNDArray._trusted(uniq, vals, g.shape))
                return
            if h._grad is not None:
                h._grad._set_data(h._grad.data + g.densify())
                return
        uniq, vals = g.dedup()
        h._grad = sp.RowSparseNDArray._trusted(
            uniq, vals.astype(h._data.dtype), g.shape)
        return
    dense_existing = (h._grad is not None
                      and getattr(h._grad, "stype", "default") == "default")
    if entry.grad_req == "add" and dense_existing:
        h._grad._set_data(h._grad._data + g)
    else:
        if not dense_existing:
            h._grad = NDArray(jnp.zeros_like(h._data))
        h._grad._set_data(jnp.asarray(g, dtype=h._data.dtype))


def _run_backward(heads, head_grads, retain_graph, train_mode_flag,
                  collect_vars=None):
    st = _st()
    tape: List[_TapeNode] = st.tape
    grads: dict = {}

    if not tape and any(isinstance(h._grad_entry, tuple) for h in heads):
        raise RuntimeError(
            "backward: the recorded graph has been freed (backward already ran "
            "without retain_graph=True, or recording never happened)")

    for i, h in enumerate(heads):
        entry = h._grad_entry
        if entry is None:
            continue
        hg = None if head_grads is None else head_grads[i]
        cot = jnp.ones_like(h.data) if hg is None else jnp.asarray(
            hg.data if hasattr(hg, "data") and hasattr(hg, "_grad_entry") else hg,
            dtype=h.data.dtype)
        k = _entry_key(entry)
        grads[k] = _accum(grads[k], cot) if k in grads else cot

    for node in reversed(tape):
        out_keys = [("out", id(node), j) for j in range(node.n_outputs)]
        if not any(k in grads for k in out_keys):
            continue
        if node.backward_fn is not None:
            out_grads = [grads.get(k) for k in out_keys]
            out_grads = [_dense_cot(g) if g is not None
                         else jnp.zeros_like(_out_like(node, j))
                         for j, (g, k) in enumerate(zip(out_grads, out_keys))]
            in_grads = node.backward_fn(node.saved, out_grads)
        else:
            outs, vjp_fn = jax.vjp(node.pure_fn, *node.raw_inputs)
            multi = isinstance(outs, (tuple, list))
            if multi:
                cots = tuple(
                    _dense_cot(grads[k]) if grads.get(k, None) is not None
                    else jnp.zeros_like(o)
                    for k, o in zip(out_keys, outs))
            else:
                cots = _dense_cot(grads[out_keys[0]])
            in_grads = vjp_fn(cots)
        for entry, g in zip(node.parent_entries, in_grads):
            if entry is None or g is None:
                continue
            k = _entry_key(entry)
            grads[k] = _accum(grads[k], g) if k in grads else g

    # flush into variable .grad buffers / collect for grad()
    from .ndarray.ndarray import NDArray
    for h in st.retained:
        entry = h._grad_entry
        if entry is None:
            continue
        k = _entry_key(entry)
        if k in grads:
            h._grad = NDArray(jnp.asarray(_dense_cot(grads[k]),
                                          dtype=h._data.dtype))
    results = None
    if collect_vars is not None:
        results = []
        for v in collect_vars:
            entry = v._grad_entry
            k = _entry_key(entry) if isinstance(entry, _VariableEntry) else None
            g = _dense_cot(grads.get(k)) if k and k in grads else None
            results.append(NDArray(g if g is not None else jnp.zeros_like(v._data)))
    else:
        seen = set()
        for node in tape:
            for entry in node.parent_entries:
                if isinstance(entry, _VariableEntry) and id(entry) not in seen:
                    seen.add(id(entry))
                    k = _entry_key(entry)
                    if k not in grads or entry.grad_req == "null":
                        continue
                    _flush_grad(entry.handle, entry, grads[k])
        # heads that are themselves marked variables and were NOT flushed above
        # (skipping `seen` keeps this from clobbering grad_req='add' accumulation)
        for i, h in enumerate(heads):
            entry = h._grad_entry
            if isinstance(entry, _VariableEntry) and id(entry) not in seen:
                seen.add(id(entry))
                k = _entry_key(entry)
                if k in grads and entry.grad_req != "null":
                    _flush_grad(h, entry, grads[k])

    if not retain_graph:
        st.tape = []
        st.retained = []
    return results


def _out_like(node, j):
    outs = node.pure_fn(*node.raw_inputs) if node.pure_fn else node.saved["outs"][j]
    if isinstance(outs, (tuple, list)):
        return outs[j]
    return outs


def _run_backward_create_graph(heads, head_grads, collect_vars,
                               retain_graph=True):
    """Backward pass that RECORDS itself: each node's vjp replay is appended to
    the tape as a pure node, and cotangent accumulation is a recorded add, so
    ``grad``/``backward`` over the returned grads differentiates through this
    pass (``create_graph=True``, reference autograd.py:270-307 — the docstring
    example there is literally grad-of-grad).

    The original tape is kept (reference: ``retain_graph`` defaults to
    ``create_graph``). Nodes with an explicit ``backward_fn`` (custom
    ``Function``) replay that backward as a recorded node, so higher-order
    autograd composes through custom Functions (reference
    autograd.py:309-509); only a backward whose body is genuinely host-bound
    (pure_callback) stops the chain, at the next differentiation.
    """
    from .ndarray.ndarray import NDArray
    st = _st()
    tape_snapshot = list(st.tape)
    cots: dict = {}                       # entry key -> NDArray (tracked)

    def shim(raw, entry):
        h = NDArray(raw)
        h._grad_entry = entry
        return h

    def accum_nd(a: NDArray, b: NDArray) -> NDArray:
        out = NDArray(a.data + b.data)
        record_custom_node(lambda x, y: x + y, [a, b], [out])
        return out

    def as_nd(g, like):
        if isinstance(g, NDArray):
            return g
        return NDArray(jnp.asarray(g, dtype=like.dtype))

    for i, h in enumerate(heads):
        entry = h._grad_entry
        if entry is None:
            continue
        hg = None if head_grads is None else head_grads[i]
        cot = NDArray(jnp.ones_like(h.data)) if hg is None else as_nd(hg, h.data)
        k = _entry_key(entry)
        cots[k] = accum_nd(cots[k], cot) if k in cots else cot

    for node in reversed(tape_snapshot):
        out_keys = [("out", id(node), j) for j in range(node.n_outputs)]
        if not any(k in cots for k in out_keys):
            continue
        n_in = len(node.raw_inputs)
        if node.backward_fn is not None:
            # Custom Function / explicit backward: replay the authored
            # backward as a recorded node so grad-of-grad composes through it
            # (reference autograd.py:309-509 — custom Functions participate in
            # higher-order autograd). The replay differentiates iff the
            # backward_fn body is traceable array math; a genuinely host-bound
            # backward (pure_callback) fails at the NEXT differentiation,
            # which is the honest boundary.
            def bwd_replay(*raw, _node=node, _n_in=n_in):
                cs = raw[_n_in:]
                if getattr(_node.backward_fn, "_takes_input_raws", False):
                    gs = _node.backward_fn(_node.saved, list(cs), raw[:_n_in])
                else:
                    gs = _node.backward_fn(_node.saved, list(cs))
                return tuple(
                    jnp.asarray(_dense_cot(g)) if g is not None
                    else jnp.zeros_like(r)
                    for g, r in zip(gs, raw[:_n_in]))

            in_handles = [shim(r, e) for r, e in
                          zip(node.raw_inputs, node.parent_entries)]
            cot_handles = [
                cots[k] if cots.get(k) is not None
                else NDArray(jnp.zeros_like(_out_like(node, j)))
                for j, k in enumerate(out_keys)]
            # pause: the user's backward runs NDArray ops eagerly here — they
            # must not append dead nodes to the tape (the replay node below is
            # the recorded form)
            with pause():
                raw_grads = bwd_replay(*[h.data for h in in_handles],
                                       *[h.data for h in cot_handles])
            grad_handles = [NDArray(g) for g in raw_grads]
            record_custom_node(bwd_replay, in_handles + cot_handles,
                               grad_handles)
            for entry, gh in zip(node.parent_entries, grad_handles):
                if entry is None:
                    continue
                k = _entry_key(entry)
                cots[k] = accum_nd(cots[k], gh) if k in cots else gh
            continue

        def vjp_replay(*raw, _node=node, _n_in=n_in):
            ins, cs = raw[:_n_in], raw[_n_in:]
            outs, vjp_fn = jax.vjp(_node.pure_fn, *ins)
            # tuple-ness resolved inside the trace — no extra eval_shape
            return vjp_fn(tuple(cs) if isinstance(outs, (tuple, list))
                          else cs[0])

        in_handles = [shim(r, e) for r, e in
                      zip(node.raw_inputs, node.parent_entries)]
        out_struct = None                  # traced lazily, only for zero-fill
        cot_handles = []
        for j, k in enumerate(out_keys):
            g = cots.get(k)
            if g is None:
                if out_struct is None:
                    out_struct = jax.eval_shape(node.pure_fn, *node.raw_inputs)
                s = out_struct[j] if isinstance(out_struct, (tuple, list)) \
                    else out_struct
                g = NDArray(jnp.zeros(s.shape, s.dtype))
            cot_handles.append(g)
        raw_grads = vjp_replay(*[h.data for h in in_handles],
                               *[h.data for h in cot_handles])
        grad_handles = [NDArray(g) for g in raw_grads]
        record_custom_node(vjp_replay, in_handles + cot_handles, grad_handles)
        for entry, gh in zip(node.parent_entries, grad_handles):
            if entry is None:
                continue
            k = _entry_key(entry)
            cots[k] = accum_nd(cots[k], gh) if k in cots else gh

    for h in st.retained:
        entry = h._grad_entry
        if entry is not None and _entry_key(entry) in cots:
            h._grad = cots[_entry_key(entry)]
    results = []
    for v in collect_vars:
        entry = v._grad_entry
        k = _entry_key(entry) if isinstance(entry, _VariableEntry) else None
        g = cots.get(k) if k else None
        results.append(g if g is not None else NDArray(jnp.zeros_like(v._data)))
    if not retain_graph:
        # explicit retain_graph=False overrides the create_graph default: the
        # caller is done with this graph — free it (a later backward through
        # the returned grads raises "graph has been freed" loudly, and a loop
        # of create_graph calls doesn't grow the tape without bound)
        st.tape = []
        st.retained = []
    return results


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True):
    """mx.autograd.backward parity: accumulate into attach_grad'ed ``.grad`` buffers."""
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    _run_backward(list(heads), head_grads, retain_graph, train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode: bool = True):
    """mx.autograd.grad parity: return grads w.r.t. ``variables``.

    ``create_graph=True`` records the backward pass itself on the tape, so the
    returned grads are differentiable — grad-of-grad, gradient penalties, and
    d²/dx² compose through the imperative API exactly as in the reference
    (python/mxnet/autograd.py:270-307). ``retain_graph`` defaults to
    ``create_graph``.
    """
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    variables = variables if isinstance(variables, (list, tuple)) else [variables]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    retain = retain_graph if retain_graph is not None else create_graph
    if create_graph:
        return _run_backward_create_graph(list(heads), head_grads,
                                          list(variables), bool(retain))
    return _run_backward(list(heads), head_grads, bool(retain), train_mode,
                         collect_vars=list(variables))


def get_symbol(x):
    """Debug view of the recorded graph that produced ``x`` (reference
    autograd.get_symbol, python/mxnet/autograd.py:466 — returns a Symbol of
    the recorded ops). Here the recorded closures are jaxpr-traceable, so the
    faithful artifact is the jaxpr of the FULL producing subgraph, composed
    from the tape as a function of the marked leaf variables — printable,
    inspectable (``.jaxpr``, ``.in_avals``), and convertible to StableHLO via
    ``mxtpu.jit.trace``.
    """
    entry = getattr(x, "_grad_entry", None)
    if entry is None or isinstance(entry, _VariableEntry):
        raise ValueError("get_symbol: array is not an output of a recorded "
                         "computation")
    target_node, target_j = entry
    tape = _st().tape
    # reverse reachability: the subgraph of tape nodes feeding the target
    deps = {id(target_node)}
    keep = {}
    for node in reversed(tape):
        if id(node) not in deps:
            continue
        keep[id(node)] = node
        for e in node.parent_entries:
            if isinstance(e, tuple):
                deps.add(id(e[0]))
    ordered = [n for n in tape if id(n) in keep]
    leaves: List[_VariableEntry] = []
    for n in ordered:
        if n.pure_fn is None:
            raise ValueError("get_symbol: subgraph contains an opaque custom "
                             "Function node")
        for e in n.parent_entries:
            if isinstance(e, _VariableEntry) and e not in leaves:
                leaves.append(e)

    def full_fn(*leaf_vals):
        lv = {id(e): v for e, v in zip(leaves, leaf_vals)}
        env = {}
        for n in ordered:
            ins = []
            for raw, e in zip(n.raw_inputs, n.parent_entries):
                if isinstance(e, _VariableEntry):
                    ins.append(lv[id(e)])
                elif isinstance(e, tuple):
                    ins.append(env[(id(e[0]), e[1])])
                else:
                    ins.append(raw)
            out = n.pure_fn(*ins)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for j, o in enumerate(outs):
                env[(id(n), j)] = o
        return env[(id(target_node), target_j)]

    return jax.make_jaxpr(full_fn)(*[e.handle.data for e in leaves])


# ---------------------------------------------------------------------------
# custom Function (mx.autograd.Function parity, python/mxnet/autograd.py:332-509)
# ---------------------------------------------------------------------------


class Function:
    """User-defined differentiable function with explicit backward.

    Subclass and implement ``forward(self, *inputs)`` and ``backward(self,
    *output_grads)`` operating on NDArrays; ``save_for_backward`` stashes tensors.
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (tuple, list))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn = self

            def backward_fn(saved, out_grads, input_raws=None):
                if input_raws is None:
                    gs = fn.backward(*[NDArray(g) for g in out_grads])
                else:
                    # higher-order replay (create_graph=True): RE-RUN forward
                    # on the traced inputs so every save_for_backward tensor
                    # is regenerated as a traced function of them — saved
                    # inputs, saved outputs (the sigmoid save-s pattern), and
                    # derived values all carry their chain term into d²/dx².
                    # One extra forward per custom node, the standard
                    # rematerialization price. Tensors saved OUTSIDE forward
                    # remain genuine constants.
                    prev = fn._saved
                    try:
                        with pause():   # replay must never hit the tape
                            fn.forward(*[NDArray(r) for r in input_raws])
                            gs = fn.backward(*[NDArray(g) for g in out_grads])
                    finally:
                        fn._saved = prev
                gs = [gs] if not isinstance(gs, (tuple, list)) else gs
                return [g._data if isinstance(g, NDArray) else g for g in gs]

            backward_fn._takes_input_raws = True
            record_custom_node(None, list(inputs), outs, backward_fn=backward_fn,
                               saved={"outs": [o._data for o in outs]})
        return outs[0] if single else tuple(outs)
