"""Subsystem counter stores (checkpoint / device-feed / comm / sanitizer).

Moved here from ``mxtpu/profiler.py`` when the profiler became a facade over
``mxtpu.observability`` — the public surface is unchanged and re-exported
from ``mxtpu.profiler`` (``record_*`` / ``get_*_stats`` / ``reset_*``), so
every existing call site and test keeps working.

THE module stats lock: every stat dict here is bumped from more than one
thread — the DeviceFeed producer (``device_feed.py``), the checkpoint writer
(``checkpoint/manager.py``), and the main training thread — and
read-modify-write pairs (total+last) tear without mutual exclusion. One lock,
never held across a call that could re-acquire it (tpulint R004 is the static
guard for this contract).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from . import histogram as _hist

_stats_lock = threading.Lock()


# ---------------------------------------------------------------------------
# checkpoint observability (mxtpu.checkpoint manager counters)
# ---------------------------------------------------------------------------

_CKPT_ZERO = {"saves": 0, "commits": 0, "restores": 0,
              "committed_bytes": 0,
              "blocked_step_ms_total": 0.0, "blocked_step_ms_last": 0.0,
              "save_latency_ms_total": 0.0, "save_latency_ms_last": 0.0,
              "write_ms_last": 0.0,
              "shard_writes": 0, "shard_write_ms_last": 0.0}
_ckpt = dict(_CKPT_ZERO)


def record_checkpoint_save(blocked_ms: float):
    """Training-thread side of an async save: how long the step was blocked
    on the snapshot handoff (device→host DMA start + enqueue)."""
    with _stats_lock:
        _ckpt["saves"] += 1
        _ckpt["blocked_step_ms_last"] = blocked_ms
        _ckpt["blocked_step_ms_total"] += blocked_ms


# Commit observers (resilience's committed-step watermark rides here).
# Registered callables run OUTSIDE the stats lock — a hook may call back
# into any record_*/get_* without self-deadlock.
_commit_hooks: list = []


def add_commit_hook(fn):
    """Register ``fn()`` to run after every checkpoint commit (idempotent)."""
    with _stats_lock:
        if fn not in _commit_hooks:
            _commit_hooks.append(fn)


def record_checkpoint_commit(write_ms: float, latency_ms: float, nbytes: int):
    """Writer-thread side: ``write_ms`` is the serialize+fsync+commit work,
    ``latency_ms`` the enqueue→commit wall time (queueing included),
    ``nbytes`` the committed payload size."""
    with _stats_lock:
        _ckpt["commits"] += 1
        _ckpt["write_ms_last"] = write_ms
        _ckpt["save_latency_ms_last"] = latency_ms
        _ckpt["save_latency_ms_total"] += latency_ms
        _ckpt["committed_bytes"] += int(nbytes)
        hooks = list(_commit_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception as e:
            import logging
            logging.getLogger(__name__).warning("commit hook failed: %s", e)


def record_checkpoint_shard_write(write_ms: float):
    """Writer-thread side on ranks != 0: only this rank's shard write is
    measured — commit stats (count/bytes) belong to rank 0, which owns the
    rename and is the only rank that can see the final dir."""
    with _stats_lock:
        _ckpt["shard_writes"] += 1
        _ckpt["shard_write_ms_last"] = write_ms


def record_checkpoint_restore():
    with _stats_lock:
        _ckpt["restores"] += 1


def get_checkpoint_stats() -> dict:
    """Checkpoint counters (saves/commits/restores, committed bytes, save
    latency, blocked-step time) — the observability contract of the async
    checkpoint subsystem; bench.py's `checkpoint` scenario reads these."""
    with _stats_lock:
        return dict(_ckpt)


def reset_checkpoint_stats():
    with _stats_lock:
        _ckpt.update(_CKPT_ZERO)


# ---------------------------------------------------------------------------
# device-feed observability (mxtpu.device_feed input-pipeline counters)
# ---------------------------------------------------------------------------

_FEED_ZERO = {"batches_prefetched": 0, "batches_consumed": 0,
              "transfer_count": 0, "resident_skips": 0,
              "transfer_bytes": 0, "transfer_ms_total": 0.0,
              "stall_ms_total": 0.0, "stall_ms_last": 0.0,
              "queue_depth_max": 0, "feed_depth": 0}
_feed = dict(_FEED_ZERO)


def record_feed_transfer(nbytes: int, ms: float):
    """Producer-thread side: one array dispatched through the host→device
    boundary (``ms`` is the non-blocking dispatch wall time)."""
    with _stats_lock:
        _feed["transfer_count"] += 1
        _feed["transfer_bytes"] += int(nbytes)
        _feed["transfer_ms_total"] += ms


def record_feed_resident():
    """Producer-thread side: an array already committed with the target
    sharding was NOT re-transferred — the double-``device_put`` guard
    counter."""
    with _stats_lock:
        _feed["resident_skips"] += 1


def record_feed_prefetch(queue_depth: int):
    """Producer-thread side: one batch staged device-resident; samples the
    queue-depth high-water mark."""
    with _stats_lock:
        _feed["batches_prefetched"] += 1
        if queue_depth > _feed["queue_depth_max"]:
            _feed["queue_depth_max"] = queue_depth


def record_feed_consume(stall_ms: float):
    """Consumer-thread side: one batch taken; ``stall_ms`` is how long the
    step loop was blocked waiting on data (the input-stall metric)."""
    with _stats_lock:
        _feed["batches_consumed"] += 1
        _feed["stall_ms_last"] = stall_ms
        _feed["stall_ms_total"] += stall_ms


def set_feed_depth(depth: int):
    with _stats_lock:
        _feed["feed_depth"] = int(depth)


def get_feed_stats() -> dict:
    """Input-pipeline counters (input-stall ms, transfer bytes/ms, queue-depth
    high-water mark, batches prefetched vs consumed) — the observability
    contract of the device-feed pipeline. ``Speedometer`` prints these;
    ``bench.py input_pipeline`` reads them as the stall-fraction source of
    truth. Counters are monotone until :func:`reset_feed_stats`."""
    with _stats_lock:
        return dict(_feed)


def reset_feed_stats():
    """Zero the feed counters (tests, per-epoch accounting, bench legs)."""
    with _stats_lock:
        _feed.update(_FEED_ZERO)


# ---------------------------------------------------------------------------
# distributed-comm observability (ZeRO-1 / collectives counters)
# ---------------------------------------------------------------------------

_COMM_ZERO = {"steps": 0, "zero_steps": 0,
              "bytes_reduced": 0, "bytes_gathered": 0, "allreduce_bytes": 0,
              "bucket_count": 0, "shard_bytes_per_device": 0, "dp": 1,
              "collectives": 0, "collective_ms_total": 0.0,
              "collective_bytes": 0}
_comm = dict(_COMM_ZERO)


def record_comm_step(bytes_reduced: int = 0, bytes_gathered: int = 0,
                     bucket_count: int = 0, shard_bytes: int = 0,
                     dp: int = 1, allreduce_bytes: int = 0,
                     zero: bool = False):
    """One training step's gradient-exchange accounting (per-device bytes,
    analytic from the bucket layout and dp degree — ring collectives move
    (N-1)/N of the payload per device). The ZeRO path records reduce-scatter
    + all-gather legs; the replicated-psum path records the full all-reduce
    equivalent, so the two are directly comparable in ``bench.py zero_dp``."""
    with _stats_lock:
        _comm["steps"] += 1
        if zero:
            _comm["zero_steps"] += 1
        _comm["bytes_reduced"] += int(bytes_reduced)
        _comm["bytes_gathered"] += int(bytes_gathered)
        _comm["allreduce_bytes"] += int(allreduce_bytes)
        _comm["bucket_count"] = int(bucket_count)
        _comm["shard_bytes_per_device"] = int(shard_bytes)
        _comm["dp"] = int(dp)


def record_collective(ms: float, nbytes: int):
    """One host-blocking array-level collective (``parallel.collectives``
    cross-process exchange): measured wall ms + payload bytes."""
    with _stats_lock:
        _comm["collectives"] += 1
        _comm["collective_ms_total"] += ms
        _comm["collective_bytes"] += int(nbytes)


def get_comm_stats() -> dict:
    """Per-step comm counters (bytes reduced/gathered, bucket count, shard
    bytes per device, dp degree, measured collective ms) — the observability
    contract of the ZeRO-1 gradient path. ``Speedometer`` prints the per-step
    deltas; ``Module.fit`` logs them per epoch; ``bench.py zero_dp`` compares
    the ZeRO legs against the replicated all-reduce accounting."""
    with _stats_lock:
        return dict(_comm)


def reset_comm_stats():
    with _stats_lock:
        _comm.update(_COMM_ZERO)


# ---------------------------------------------------------------------------
# memory observability (ZeRO/FSDP per-device residency accounting)
# ---------------------------------------------------------------------------

_MEM_ZERO = {"stage": 0, "data_degree": 1, "fsdp_degree": 1,
             "param_bytes_per_device": 0, "grad_bytes_per_device": 0,
             "slot_bytes_per_device": 0,
             "replicated_param_bytes": 0, "replicated_grad_bytes": 0,
             "replicated_slot_bytes": 0}
_mem = dict(_MEM_ZERO)


def record_memory_stats(**kwargs):
    """Per-device resident-byte accounting for params/grads/optimizer slots
    by ZeRO stage (``parallel.fsdp.measure_memory`` computes the figures from
    the actual placed shardings at trace time). ``replicated_*`` keys carry
    the stage-0 equivalent the shrink ratio is quoted against."""
    with _stats_lock:
        for k, v in kwargs.items():
            if k in _mem:
                _mem[k] = int(v)


def get_memory_stats() -> dict:
    """Latest memory accounting snapshot — the number that proves ZeRO-2/3
    actually shrinks the footprint. ``compile_cache_summary()`` prints it,
    ``Module.fit`` logs it per epoch, and ``bench.py fsdp`` compares the
    stages with it."""
    with _stats_lock:
        return dict(_mem)


def reset_memory_stats():
    with _stats_lock:
        _mem.update(_MEM_ZERO)


# ---------------------------------------------------------------------------
# resilience observability (mxtpu.resilience counters)
# ---------------------------------------------------------------------------

_RESIL_ZERO = {"faults_injected": 0,
               "retries": 0, "retries_exhausted": 0, "escalations": 0,
               "watchdog_stalls": 0, "emergency_saves": 0,
               "restarts": 0, "steps_lost": 0,
               "restart_latency_ms_total": 0.0,
               "restart_latency_ms_last": 0.0,
               # live elasticity (mxtpu.resilience.elastic): in-place mesh
               # resizes completed vs process-restart fallbacks taken when an
               # in-place adoption raised
               "live_resizes": 0, "restart_fallbacks": 0,
               "resize_latency_ms_total": 0.0,
               "resize_latency_ms_last": 0.0}
_resil = dict(_RESIL_ZERO)


def record_resilience(key: str, n=1):
    """One resilience event (``mxtpu.resilience``): faults fired, transient
    retries taken/exhausted, non-transient escalations, watchdog stalls,
    emergency saves, supervisor restarts, steps lost since last commit.
    ``*_last`` keys assign; everything else accumulates."""
    with _stats_lock:
        if key.endswith("_last"):
            _resil[key] = n
        else:
            _resil[key] += n


def get_resilience_stats() -> dict:
    """Resilience counters — the observability contract of the fault-
    injection/retry/watchdog/supervisor stack. ``bench.py resilience`` emits
    these as its JSON block; the guard tests assert injected faults left
    fingerprints here."""
    with _stats_lock:
        return dict(_resil)


def reset_resilience_stats():
    with _stats_lock:
        _resil.update(_RESIL_ZERO)


# ---------------------------------------------------------------------------
# serving observability (mxtpu.serving engine counters)
# ---------------------------------------------------------------------------

_SERVING_ZERO = {"submitted": 0, "admitted": 0, "completed": 0,
                 "cancelled": 0, "rejected": 0, "expired": 0,
                 "prefills": 0, "prefill_chunks": 0,
                 "decode_steps": 0, "tokens_out": 0,
                 "kv_promotions": 0,
                 # shared-prefix radix KV reuse (serving/kv.PrefixCache):
                 # hits/misses count PREFILLED requests with at least one
                 # cache-eligible block (prompt > 32 tokens); hit_tokens is
                 # the positions whose prefill was skipped
                 "prefix_hits": 0, "prefix_misses": 0, "prefix_hit_tokens": 0,
                 # partial-block reuse: hits whose matched length ends inside
                 # a 32-token block (token-granular tail rows copied from a
                 # cached child block); partial_tokens is the sub-block
                 # positions saved, already included in prefix_hit_tokens
                 "prefix_partial_hits": 0, "prefix_partial_tokens": 0,
                 "prefix_inserts": 0, "prefix_evictions": 0,
                 "prefix_cache_bytes": 0,
                 # SLO control plane (mxtpu.sched): requests shed before
                 # their deadline, decode slots preempted for a higher tier,
                 # parked requests resumed
                 "shed": 0, "preempted": 0, "resumed": 0,
                 # batched prefill admissions (mxtpu.sched.admission): one
                 # count per PrefillGroup launched, not per member
                 "prefill_groups": 0,
                 # live elasticity: requests carried across an engine
                 # drain()/adopt() handoff (zero-drop contract)
                 "drained": 0, "adopted": 0,
                 # speculative decode (mxtpu.serving.spec): verify dispatches
                 # taken instead of plain decode turns; tokens the drafter
                 # proposed vs how many the verify forward accepted/rejected
                 # (accepted + rejected == drafted over any window); n-gram
                 # side-index probes on the prefix radix tree. The
                 # accept-length distribution itself is histogram-backed
                 # ("serving/accept_len" -> accept_len_mean + percentiles)
                 "spec_dispatches": 0, "tokens_drafted": 0,
                 "tokens_accepted": 0, "tokens_rejected": 0,
                 "ngram_hits": 0, "ngram_misses": 0,
                 "queue_depth_max": 0, "slots": 0,
                 "slot_occupancy_sum": 0.0, "occupancy_samples": 0,
                 "ttft_ms_total": 0.0, "ttft_ms_last": 0.0,
                 # TTFT decomposition: queue wait (submit -> prefill start)
                 # + prefill (prefill start -> first token); first_decode is
                 # admission-complete -> first decode-chunk token; token_ms is
                 # decode wall time per emitted token (one sample/dispatch)
                 "queue_wait_ms_total": 0.0, "queue_wait_ms_last": 0.0,
                 "prefill_ms_total": 0.0, "prefill_ms_last": 0.0,
                 "first_decode_ms_total": 0.0, "first_decode_ms_last": 0.0,
                 "token_ms_total": 0.0, "token_ms_last": 0.0,
                 # decode-only wall clock and tokens: one decode_ms sample
                 # per decode dispatch (the dispatch's full wall time) plus
                 # the tokens it emitted — decode_tokens / decode_ms_total
                 # is pure decode throughput with prefill, queueing, and
                 # scheduler sleeps excluded (the quant_decode_speedup
                 # methodology; see docs/quantization.md)
                 "decode_ms_total": 0.0, "decode_ms_last": 0.0,
                 "decode_tokens": 0,
                 # KV-cache residency (mxtpu.quant): bytes of the resident
                 # paged cache (data + scales when quantized) and its
                 # storage dtype ('float32' | 'bfloat16' | 'int8' | 'fp8');
                 # decode_kernel is the fused dequant-attention path of a
                 # quantized cache ('pallas' | 'xla'; 'none' when the cache
                 # is full-precision and the fused read never engages)
                 "kv_bytes_resident": 0, "kv_dtype": "float32",
                 "decode_kernel": "none",
                 # identity of the engine that last wrote this store (the
                 # exporter's {engine=...} metric label) — the store is
                 # process-global, so with several in-process engines the
                 # label names the LAST writer; a router reads each
                 # engine.load() for per-replica signals instead
                 "engine": "none"}
_serving = dict(_SERVING_ZERO)

# keys that ASSIGN the latest value instead of accumulating
_SERVING_ASSIGN = ("slots", "prefix_cache_bytes", "kv_bytes_resident")
# string-valued keys (assign verbatim)
_SERVING_STR = ("kv_dtype", "decode_kernel", "engine")
# latency series backed by the histogram store (``histogram.record_value``):
# the compat ``<base>_last``/``<base>_total`` keys AND the ``<base>_p*``
# percentiles in ``get_serving_stats()`` all derive from "serving/<base>"
_SERVING_LATENCY = ("ttft_ms", "queue_wait_ms", "prefill_ms",
                    "first_decode_ms", "token_ms", "decode_ms")
# non-latency histogram series: same "<base>_last" -> "serving/<base>"
# routing and readback as the latency keys (accept_len is the per-slot
# accepted-token count of one speculative verify dispatch)
_SERVING_HIST = ("accept_len",)


def record_serving(key: str, n=1):
    """One serving-engine event (``mxtpu.serving.engine``): request
    lifecycle counts (submitted/admitted/completed/cancelled/rejected/
    expired), prefill and decode-step dispatches, tokens emitted, KV-bucket
    promotions, latency accumulators. ``*_last`` keys assign, ``*_max`` keys
    take the high-water mark, everything else accumulates. Latency
    ``*_ms_last`` keys are routed WHOLE into the histogram store — one
    guarded write per sample instead of the old torn last+total scalar
    pair — and read back (last/total/percentiles) by
    :func:`get_serving_stats`."""
    if key.endswith("_ms_last") or (key.endswith("_last")
                                    and key[:-5] in _SERVING_HIST):
        _hist.record_value("serving/" + key[:-5], float(n))
        return
    with _stats_lock:
        if key.endswith("_last"):
            _serving[key] = n
            base = key[:-5] + "_total"
            if base in _serving:
                _serving[base] += n
        elif key.endswith("_max"):
            if n > _serving[key]:
                _serving[key] = n
        elif key in _SERVING_STR:
            _serving[key] = str(n)
        elif key in _SERVING_ASSIGN:
            _serving[key] = int(n)
        else:
            _serving[key] += n


# per-tenant serving series (mxtpu.sched satellite): counters here, latency
# samples in the histogram store under "serving/tenant/<t>/<base>" (the
# "serving/" prefix keeps them inside reset_serving_stats' blast radius and
# gets them exported as quantile gauges for free). Cardinality is BOUNDED:
# past _TENANT_CAP distinct tenants, everything folds into "__other__" so a
# tenant-id-per-user caller can't grow the store (or the Prometheus page)
# without bound.
_TENANT_CAP = 32
_OTHER_TENANT = "__other__"
_tenants: Dict[str, Dict[str, float]] = {}


def _tenant_key(tenant: str) -> str:
    t = str(tenant)
    if t not in _tenants and len(_tenants) >= _TENANT_CAP:
        return _OTHER_TENANT
    return t


def record_tenant(tenant: str, key: str, n=1):
    """One per-tenant serving sample. ``*_ms_last`` keys are histogram
    samples (``serving/tenant/<t>/<base>``: TTFT, goodput latency);
    everything else accumulates in the tenant's counter row (tokens_out,
    completed, shed, ...)."""
    if key.endswith("_ms_last"):
        with _stats_lock:
            t = _tenant_key(tenant)
            _tenants.setdefault(t, {})
        _hist.record_value(f"serving/tenant/{t}/{key[:-8]}", float(n))
        return
    with _stats_lock:
        row = _tenants.setdefault(_tenant_key(tenant), {})
        row[key] = row.get(key, 0) + n


def record_serving_occupancy(active_slots: int, total_slots: int):
    """One decode-step occupancy sample (active slots / capacity) — the
    utilization series behind ``get_serving_stats()['slot_occupancy']``."""
    with _stats_lock:
        _serving["slots"] = int(total_slots)
        _serving["slot_occupancy_sum"] += \
            active_slots / max(1, total_slots)
        _serving["occupancy_samples"] += 1


def get_serving_stats() -> dict:
    """Serving-engine counters (request lifecycle, decode steps, tokens out,
    TTFT/queue-wait accumulators, mean slot occupancy, KV promotions) — the
    observability contract of :class:`mxtpu.serving.ServingEngine`.
    ``bench.py serving`` reads these; ``docs/serving.md`` has the diagnosis
    guide (e.g. rejected≫0 → raise queue depth; occupancy≈1 with queue
    growth → raise MXTPU_SERVING_SLOTS). Latency keys are histogram-backed:
    the legacy ``<base>_last``/``<base>_total`` scalars stay, and each base
    gains ``_p50/_p90/_p99/_p999`` (log-bucket percentiles, ≤ ~2 % relative
    error — see ``observability/histogram.py``)."""
    with _stats_lock:
        out = dict(_serving)
    samples = out.pop("occupancy_samples")
    occ_sum = out.pop("slot_occupancy_sum")
    out["slot_occupancy"] = (occ_sum / samples) if samples else 0.0
    probes = out["prefix_hits"] + out["prefix_misses"]
    out["prefix_hit_rate"] = (out["prefix_hits"] / probes) if probes else 0.0
    # latency series: read outside _stats_lock (histogram store has its own
    # lock; never nest the two — R004 discipline)
    for base in _SERVING_LATENCY + _SERVING_HIST:
        h = _hist.get_histogram("serving/" + base)
        if h is not None and h.count:
            s = h.summary()
            out[base + "_last"] = s["last"]
            out[base + "_total"] = s["sum"]
            out[base + "_count"] = s["count"]
            for _q, name in _hist.QUANTILES:
                out[f"{base}_{name}"] = s[name]
        else:
            out[base + "_count"] = 0
            for _q, name in _hist.QUANTILES:
                out[f"{base}_{name}"] = 0.0
    # the speculative-decode headline number: mean accepted tokens per live
    # slot per verify dispatch (>= 1.0 always — the bonus token; > 1.0 means
    # drafts are landing and decode is running faster than one token/turn)
    out["accept_len_mean"] = (out.get("accept_len_total", 0.0)
                              / out["accept_len_count"]
                              if out["accept_len_count"] else 0.0)
    # per-tenant series (only when something recorded them — the plain
    # engine's stats dict is unchanged): counters + quantiles of every
    # "serving/tenant/<t>/<base>" histogram (read outside _stats_lock)
    with _stats_lock:
        tenants = {t: dict(row) for t, row in _tenants.items()}
    if tenants:
        for name, s in _hist.get_histogram_stats().items():
            if not name.startswith("serving/tenant/"):
                continue
            _, _, rest = name.partition("serving/tenant/")
            t, _, base = rest.partition("/")
            if t in tenants and base:
                tenants[t][base + "_count"] = s["count"]
                for _q, qname in _hist.QUANTILES:
                    tenants[t][f"{base}_{qname}"] = s[qname]
        out["tenants"] = tenants
    return out


def reset_serving_stats():
    with _stats_lock:
        _serving.update(_SERVING_ZERO)
        _tenants.clear()
    _hist.reset_histograms(prefix="serving/")


# ---------------------------------------------------------------------------
# multi-replica router observability (mxtpu.serving.router)
# ---------------------------------------------------------------------------

_ROUTER_ZERO = {"submitted": 0,
                # routing decisions: prefix-affinity target honored /
                # affinity target over headroom so the request spilled to
                # the least-loaded replica / no affinity (short or
                # cache-opted-out prompt) -> least-loaded
                "routed_affinity": 0, "routed_spill": 0,
                "routed_least_loaded": 0,
                # backpressure: one replica's queue was full and the
                # request moved on to the next candidate (overflow), or
                # EVERY replica was full and submit() raised (rejected)
                "overflow": 0, "rejected": 0,
                # live-rebalance lifecycle: engine swaps via drain/adopt,
                # replicas removed, in-flight requests re-routed to a
                # survivor, and requests LOST in a removal (the zero-drop
                # contract: this stays 0; anything else is a bug a chaos
                # test must catch)
                "rebalanced": 0, "replicas_removed": 0,
                "requests_rebalanced": 0, "requests_dropped": 0,
                "fair_share_syncs": 0,
                "replicas": 0}
_router = dict(_ROUTER_ZERO)
_ROUTER_ASSIGN = ("replicas",)


def record_router(key: str, n=1):
    """One router event (``mxtpu.serving.router.Router``): routing
    decisions, backpressure overflow/rejection, rebalance lifecycle.
    ``replicas`` assigns the current replica count; everything else
    accumulates."""
    with _stats_lock:
        if key in _ROUTER_ASSIGN:
            _router[key] = int(n)
        else:
            _router[key] += n


def get_router_stats() -> dict:
    """Router counters — the observability contract of
    :class:`mxtpu.serving.router.Router` (``bench.py serving`` reads
    these; the exporter serves them under the ``router`` block)."""
    with _stats_lock:
        return dict(_router)


def reset_router_stats():
    with _stats_lock:
        _router.update(_ROUTER_ZERO)


# ---------------------------------------------------------------------------
# SLO scheduler observability (mxtpu.sched control plane)
# ---------------------------------------------------------------------------

# assign-style snapshot store: the engine pushes SLOScheduler.stats() (picks/
# sheds/preemptions/resumes, fair-share tenant count, service-rate EWMAs) and
# the autoscaler its latest decision — the exporter serves whatever was
# pushed last, so a scrape never calls back into the scheduler thread
_sched: Dict[str, object] = {}


def record_sched(stats: Dict[str, object]):
    """Replace-merge the scheduler/autoscaler snapshot block served at
    ``collect_snapshot()['sched']``."""
    with _stats_lock:
        _sched.update(stats)


def get_sched_stats() -> dict:
    with _stats_lock:
        return dict(_sched)


def reset_sched_stats():
    with _stats_lock:
        _sched.clear()


# ---------------------------------------------------------------------------
# quantization observability (mxtpu.quant counters)
# ---------------------------------------------------------------------------

_QUANT_ZERO = {"matmuls": 0}
_quant = dict(_QUANT_ZERO)
_quant_err: Dict[str, float] = {}
_quant_ranges: Dict[str, tuple] = {}


def record_quant_matmuls(n: int = 1):
    """``n`` quantized matmul sites staged. Serving records the per-program
    site count at build time; the QAT step hooks record one per Dense/Conv
    site at TRACE time — so the counter reads 'quantized matmuls compiled',
    which is the retrace-stable quantity (per-dispatch counts would need a
    host sync inside jit)."""
    with _stats_lock:
        _quant["matmuls"] += int(n)


def record_quant_error(tensor: str, err: float):
    """Per-tensor max-abs round-trip quantization error, high-water over the
    process (``quantize_lm`` records each weight once; re-quantizing after a
    weight update only raises the mark if the error grew)."""
    with _stats_lock:
        if err > _quant_err.get(tensor, float("-inf")):
            _quant_err[tensor] = float(err)


def record_quant_range(tensor: str, lo: float, hi: float):
    """Calibrated activation range for one site (``quant.calibrate``) —
    widens monotonically so repeated calibration passes compose."""
    with _stats_lock:
        old = _quant_ranges.get(tensor)
        if old is not None:
            lo, hi = min(lo, old[0]), max(hi, old[1])
        _quant_ranges[tensor] = (float(lo), float(hi))


def get_quant_stats() -> dict:
    """Quantization counters: ``matmuls`` (quantized matmul sites staged),
    ``max_abs_error`` (per-tensor weight round-trip error high-water),
    ``ranges`` (per-site calibrated activation (min, max)) — the
    observability contract of ``mxtpu.quant``: a quant regression shows up
    here before it shows up in accuracy."""
    with _stats_lock:
        out = dict(_quant)
        out["max_abs_error"] = dict(_quant_err)
        out["ranges"] = dict(_quant_ranges)
    return out


def reset_quant_stats():
    with _stats_lock:
        _quant.update(_QUANT_ZERO)
        _quant_err.clear()
        _quant_ranges.clear()


# ---------------------------------------------------------------------------
# sanitizer observability (mxtpu.analysis.sanitize counters)
# ---------------------------------------------------------------------------

_SAN_ZERO = {"transfer_guards": 0, "transfer_trips": 0,
             "donation_poisons_armed": 0, "donation_trips": 0,
             "retrace_escalations": 0,
             "ownership_checks": 0, "ownership_trips": 0}
_san = dict(_SAN_ZERO)


def record_sanitizer(key: str, n: int = 1):
    """One sanitizer event (``mxtpu.analysis.sanitize``): guards armed and
    poisons planted count the coverage a sanitized run actually had; trips
    and escalations count violations (a clean run reports zero)."""
    with _stats_lock:
        _san[key] += int(n)


def get_sanitizer_stats() -> dict:
    """Sanitizer counters (transfer-guard arms/trips, donation poisons
    armed/tripped, retrace escalations, ownership assertions checked/
    tripped) — the observability contract of ``MXTPU_SANITIZE``.
    ``compile_cache_summary()`` prints them, ``Module.fit`` logs the
    per-epoch deltas, and ``bench.py --sanitize`` emits them as the
    ``"sanitizer"`` JSON block."""
    with _stats_lock:
        return dict(_san)


def sanitizer_violations(stats: Optional[dict] = None) -> int:
    """Total violations in a stats snapshot (0 for a clean sanitized run)."""
    s = stats if stats is not None else get_sanitizer_stats()
    return (s["transfer_trips"] + s["donation_trips"]
            + s["retrace_escalations"] + s["ownership_trips"])


def reset_sanitizer_stats():
    with _stats_lock:
        _san.update(_SAN_ZERO)
