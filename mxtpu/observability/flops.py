"""MFU accounting: per-program FLOP estimates + a bounded step-time ring.

Two halves:

* **FLOPs per compiled program** — :func:`estimate_step_flops` asks XLA's own
  cost model first (``lowered.compile().cost_analysis()['flops']`` — the same
  source ``bench.py`` has always used for honest MFU) and falls back to an
  analytic jaxpr walk counting ``dot_general``/``conv_general_dilated`` MACs
  (``scan`` bodies × trip count) when the AOT path is unavailable. The
  estimate is cached per step-cache entry by the caller; it is never computed
  on the step hot path.
* **Step-time ring** — :func:`record_step` appends one wall-clock step sample
  into a bounded ring (default 4096; ``MXTPU_STEP_RING``), from which
  :func:`get_mfu_stats` derives ``steps_per_sec``, ``p50_step_ms``,
  ``p99_step_ms``, and ``mfu`` against the detected chip's documented peak.
  ``Module.fit`` records every batch and logs the epoch roll-up;
  ``Speedometer`` prints the rolling p50/p99; ``bench.py`` emits the ``"mfu"``
  JSON block from the same source of truth.

Peak FLOP/s: the documented bf16 peak of the detected TPU generation
(public spec sheets — fp32 convs execute as bf16 MXU passes, so bf16 is the
denominator for both precisions). On CPU hosts there is no meaningful
"documented peak"; a nominal per-core heuristic (``MXTPU_CPU_PEAK_TFLOPS``
overridable, default 0.05 TF/core) keeps the MFU field *defined* so the bench
regression ratchet can track it round-over-round — its absolute value on a
host backend is a ratchet coordinate, not a hardware-utilization claim.
"""

from __future__ import annotations

import math
import os
import threading
from collections import deque
from typing import Optional, Tuple

from . import histogram

__all__ = ["device_peak", "estimate_step_flops", "jaxpr_flops",
           "record_step", "set_step_flops", "get_step_flops",
           "get_mfu_stats", "reset_steps", "step_count", "PEAK_TFLOPS"]

# documented bf16 peak TFLOP/s per chip kind (public spec sheets); the
# canonical copy — bench.py imports this table
PEAK_TFLOPS = {
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,        # v5p
    "TPU v5p": 459.0,
    "TPU v4": 275.0,
    "TPU v6 lite": 918.0,   # v6e (Trillium)
    "TPU v6e": 918.0,
}


def _cpu_peak_tflops() -> float:
    try:
        per_core = float(os.environ.get("MXTPU_CPU_PEAK_TFLOPS", "0.05"))
    except ValueError:
        per_core = 0.05
    return per_core * (os.cpu_count() or 1)


def device_peak() -> Tuple[str, Optional[float]]:
    """``(device_kind, peak_tflops_or_None)`` for device 0. TPU kinds map
    through :data:`PEAK_TFLOPS`; cpu gets the nominal ratchet heuristic
    (see module docstring); anything else returns ``None`` (MFU undefined)."""
    import jax
    kind = jax.devices()[0].device_kind
    peak = PEAK_TFLOPS.get(kind)
    if peak is None:
        for k, v in PEAK_TFLOPS.items():
            if k in kind:
                peak = v
                break
    if peak is None and "cpu" in kind.lower():
        peak = _cpu_peak_tflops()
    return kind, peak


# ---------------------------------------------------------------------------
# FLOP estimation
# ---------------------------------------------------------------------------


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _dot_general_flops(eqn) -> float:
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = _prod(lhs[i] for i in lb)
    k = _prod(lhs[i] for i in lc)
    m = _prod(lhs[i] for i in range(len(lhs)) if i not in set(lb) | set(lc))
    n = _prod(rhs[i] for i in range(len(rhs)) if i not in set(rb) | set(rc))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params["dimension_numbers"]
    out_features = rhs.shape[dn.rhs_spec[0]]
    # MACs per output element = kernel elements feeding it (in_ch/group ×
    # spatial window) = rhs_elems / out_features — feature groups cancel
    return 2.0 * _prod(out.shape) * (_prod(rhs.shape) / max(out_features, 1))


def jaxpr_flops(jaxpr) -> float:
    """Analytic matmul/conv FLOP count over a (Closed)Jaxpr: 2·MACs for every
    ``dot_general`` and ``conv_general_dilated``, recursing into sub-jaxprs
    (``pjit`` bodies, custom-derivative calls; ``scan`` bodies × trip count).
    Elementwise/reduction work is excluded — on matmul-dominated training
    steps it is noise, and XLA's own model is preferred when available."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0.0
    for eqn in inner.eqns:
        prim = eqn.primitive.name
        if prim == "dot_general":
            total += _dot_general_flops(eqn)
        elif prim == "conv_general_dilated":
            total += _conv_flops(eqn)
        else:
            mult = int(eqn.params.get("length", 1)) if prim == "scan" else 1
            for v in eqn.params.values():
                if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                    total += mult * jaxpr_flops(v)
    return total


def estimate_step_flops(jitted, avals) -> Optional[float]:
    """FLOPs of one execution of ``jitted(*avals)``.

    Primary: XLA cost analysis on the AOT-lowered program (exact fusion-aware
    accounting; pays one extra lower+compile per unique signature, which is
    why callers cache the result per step-cache entry and compute it OFF the
    step path). Fallback: the analytic jaxpr walk. ``MXTPU_FLOPS_MODE``
    selects ``xla`` (default), ``analytic``, or ``off``."""
    mode = os.environ.get("MXTPU_FLOPS_MODE", "xla").lower()
    if mode in ("off", "0", "none"):
        return None
    if mode != "analytic":
        try:
            ca = jitted.lower(*avals).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            flops = float(dict(ca or {}).get("flops", 0.0))
            if flops > 0:
                return flops
        except Exception:
            pass  # AOT path unavailable on this backend: analytic below
    try:
        import jax
        return jaxpr_flops(jax.make_jaxpr(jitted)(*avals))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# step-time ring
# ---------------------------------------------------------------------------

_ring_lock = threading.Lock()


def _ring_cap() -> int:
    try:
        return max(64, int(os.environ.get("MXTPU_STEP_RING", "4096")))
    except ValueError:
        return 4096


_ring: "deque" = deque(maxlen=_ring_cap())
_state = {"flops_per_step": None, "total_steps": 0}


def record_step(seconds: float, flops: Optional[float] = None):
    """One training step's wall time (and, optionally, its FLOP count — when
    omitted the last :func:`set_step_flops` value applies at read time).
    Also lands in the bounded ``step/fused_step_ms`` log-bucket histogram
    (``observability.histogram``) so fused-step tails survive past the
    ring's window and export alongside the serving latency series."""
    with _ring_lock:
        _ring.append((float(seconds), flops))
        _state["total_steps"] += 1
    histogram.record_value("step/fused_step_ms", float(seconds) * 1e3)


def set_step_flops(flops: Optional[float]):
    """Register the FLOPs of the CURRENT compiled step program (called by the
    fit loop / bench once per traced signature, off the hot path)."""
    with _ring_lock:
        _state["flops_per_step"] = flops


def get_step_flops() -> Optional[float]:
    with _ring_lock:
        return _state["flops_per_step"]


def step_count() -> int:
    with _ring_lock:
        return _state["total_steps"]


def reset_steps():
    """Clear the ring + the fused-step histogram (epoch boundaries, bench
    legs, tests)."""
    with _ring_lock:
        _ring.clear()
        _state["total_steps"] = 0
    histogram.reset_histograms(prefix="step/")


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = (len(sorted_vals) - 1) * q
    lo = math.floor(idx)
    hi = math.ceil(idx)
    if lo == hi:
        return sorted_vals[lo]
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def get_mfu_stats(flops_per_step: Optional[float] = None) -> dict:
    """Roll up the step-time ring: ``steps``, ``steps_per_sec``,
    ``p50_step_ms``/``p99_step_ms``, ``flops_per_step``, and ``mfu`` against
    the detected chip peak (None when FLOPs or peak are unknown)."""
    with _ring_lock:
        samples = list(_ring)
        default_flops = _state["flops_per_step"]
    if flops_per_step is None:
        flops_per_step = default_flops
    times = sorted(s for s, _ in samples)
    n = len(times)
    wall = sum(times)
    out = {"steps": n,
           "steps_per_sec": round(n / wall, 3) if wall > 0 else 0.0,
           "p50_step_ms": round(_percentile(times, 0.50) * 1e3, 3),
           "p99_step_ms": round(_percentile(times, 0.99) * 1e3, 3),
           "flops_per_step": flops_per_step,
           "mfu": None, "device_kind": None, "peak_tflops": None}
    try:
        kind, peak = device_peak()
        out["device_kind"], out["peak_tflops"] = kind, peak
    except Exception:
        peak = None
    if n and wall > 0 and flops_per_step and peak:
        out["mfu"] = round((n * flops_per_step / wall) / (peak * 1e12), 6)
    return out
