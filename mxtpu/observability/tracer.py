"""Per-thread span recorder — the unified step timeline.

The reference's profiler (``src/profiler/profiler.h:87,437``) records every
engine op into per-device ``ProfileStat`` ring buffers and serializes them to
chrome://tracing JSON. Here the interesting "ops" are framework-level phases —
``step/compile``, ``step/execute``, ``feed/transfer``, ``feed/stall``,
``comm/exchange``, ``ckpt/snapshot``/``write``/``commit`` — each recorded as a
duration span on the thread that ran it, so one trace shows the main step
loop, the DeviceFeed producer, and the checkpoint writer as separate timeline
rows (pid/tid lanes in the viewer).

Design (lock-free-ish): every thread owns a private bounded ring buffer,
created on first use and registered once under the module lock. Appends touch
only the owning thread's buffer (no lock on the hot path); readers
(``export.py``) snapshot the registered buffers under the lock. The only
module-level mutations are the registration list and the enable/pause flags —
all lock-guarded (tpulint R004 contract for thread-spawning modules).

Cost when off: ``span()`` is one module-global bool test returning a shared
no-op context manager — measured in ``bench.py``'s trace block as <2% of a
LeNet fused step. Opt in with ``MXTPU_TRACE=1`` (read at import) or
``profiler.set_state('run')``; each span is also mirrored into
``jax.profiler.TraceAnnotation`` so XLA device traces (Perfetto/XPlane) line
up with the framework spans.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

__all__ = ["span", "instant", "counter", "record_span", "enabled", "start",
           "stop", "pause", "resume", "reset", "snapshot_buffers",
           "buffer_capacity"]

# ring capacity per thread (events); a 2-epoch traced fit generates a few
# thousand spans, so the default keeps hours of steps without growing
_DEFAULT_CAP = 65536

_reg_lock = threading.Lock()
_buffers: list = []          # [_ThreadBuf] — append/clear under _reg_lock only
_tls = threading.local()

_enabled = False             # flipped by start()/stop() (scalar rebind: atomic)
_paused = False


def buffer_capacity() -> int:
    try:
        return max(1024, int(os.environ.get("MXTPU_TRACE_BUFFER",
                                            str(_DEFAULT_CAP))))
    except ValueError:
        return _DEFAULT_CAP


class _ThreadBuf:
    """One thread's bounded event ring. Only the owning thread appends;
    readers copy via :func:`snapshot_buffers` (a list copy is atomic enough
    under the GIL for the monotonically-appended prefix)."""

    __slots__ = ("tid", "name", "events", "dropped", "cap")

    def __init__(self, tid: int, name: str, cap: int):
        self.tid = tid
        self.name = name
        self.cap = cap
        self.events: list = []
        self.dropped = 0

    def append(self, ev: dict):
        if len(self.events) >= self.cap:
            # drop-oldest keeps the tail of a long run (the part a post-mortem
            # dump wants); the dropped count is exported as trace metadata
            del self.events[0]
            self.dropped += 1
        self.events.append(ev)


def _buf() -> _ThreadBuf:
    b = getattr(_tls, "buf", None)
    if b is None:
        t = threading.current_thread()
        b = _ThreadBuf(t.ident or 0, t.name, buffer_capacity())
        _tls.buf = b
        with _reg_lock:
            _buffers.append(b)
    return b


# -- lifecycle ---------------------------------------------------------------

def enabled() -> bool:
    return _enabled and not _paused


def start():
    """Arm span recording (``profiler.set_state('run')`` / ``MXTPU_TRACE``)."""
    global _enabled, _paused
    _enabled = True
    _paused = False


def stop():
    global _enabled
    _enabled = False


def pause():
    global _paused
    _paused = True


def resume():
    global _paused
    _paused = False


def reset():
    """Drop all recorded events (tests, fresh dump epochs). Live threads'
    buffers stay registered (their thread-locals still point at them); dead
    producers' buffers — every traced DeviceFeed generation spawns one — are
    unregistered so back-to-back traced legs don't accumulate rows."""
    live = {t.ident for t in threading.enumerate()}
    with _reg_lock:
        _buffers[:] = [b for b in _buffers if b.tid in live]
        for b in _buffers:
            b.events = []
            b.dropped = 0


def snapshot_buffers():
    """Read-side snapshot: ``[(tid, thread_name, events_copy, dropped)]``."""
    with _reg_lock:
        return [(b.tid, b.name, list(b.events), b.dropped) for b in _buffers]


# -- recording ---------------------------------------------------------------


class _NullSpan:
    """Shared no-op for the tracing-off fast path (one allocation, ever)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kwargs):
        return self


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "_t0", "_ann")

    def __init__(self, name: str, cat: Optional[str], args: Optional[dict]):
        self.name = name
        self.cat = cat or name.split("/", 1)[0]
        self.args = dict(args) if args else None
        self._t0 = 0
        self._ann = None

    def set(self, **kwargs):
        """Attach args discovered mid-span (payload bytes, cache key…)."""
        if self.args is None:
            self.args = {}
        self.args.update(kwargs)
        return self

    def __enter__(self):
        try:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None  # device tracing unavailable: framework span only
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        ev = {"name": self.name, "ph": "X", "cat": self.cat,
              "ts": self._t0 / 1e3, "dur": (t1 - self._t0) / 1e3}
        if self.args:
            ev["args"] = self.args
        _buf().append(ev)
        return False


def span(name: str, cat: Optional[str] = None, args: Optional[dict] = None):
    """Context manager recording one duration span on the calling thread.
    When tracing is off this returns a shared no-op (the fast path)."""
    if not _enabled or _paused:
        return _NULL
    return _Span(name, cat, args)


def instant(name: str, cat: Optional[str] = None,
            args: Optional[dict] = None, scope: str = "t"):
    """One instant event (chrome-trace ``ph: 'i'``)."""
    if not _enabled or _paused:
        return
    ev = {"name": name, "ph": "i", "cat": cat or name.split("/", 1)[0],
          "ts": time.perf_counter_ns() / 1e3, "s": scope}
    if args:
        ev["args"] = dict(args)
    _buf().append(ev)


def record_span(name: str, t0_ns: int, dur_ns: int,
                cat: Optional[str] = None, args: Optional[dict] = None):
    """Append an already-measured span (legacy Domain/Task/Frame objects
    measured their own window before the tracer existed; they mirror here so
    user spans land on the same timeline rows as the framework's)."""
    if not _enabled or _paused:
        return
    ev = {"name": name, "ph": "X", "cat": cat or name.split("/", 1)[0],
          "ts": t0_ns / 1e3, "dur": dur_ns / 1e3}
    if args:
        ev["args"] = dict(args)
    _buf().append(ev)


def counter(name: str, value, cat: str = "counters"):
    """One counter sample (chrome-trace ``ph: 'C'`` — rendered as a stacked
    area track in the viewer). Used for queue depths and rate gauges."""
    if not _enabled or _paused:
        return
    _buf().append({"name": name, "ph": "C", "cat": cat,
                   "ts": time.perf_counter_ns() / 1e3,
                   "args": {name.rsplit("/", 1)[-1]: value}})


# MXTPU_TRACE=1 arms tracing for the whole process at import (the env-var
# analogue of the reference's MXNET_PROFILER_AUTOSTART)
if os.environ.get("MXTPU_TRACE", "").lower() in ("1", "true", "on", "run"):
    start()
