"""mxtpu.observability — unified step-timeline tracing + MFU accounting.

The reference ships profiling as a first-class subsystem (``src/profiler/``:
chrome://tracing export, aggregate stats, Domain/Task/Counter/Marker
objects). This package is that subsystem TPU-natively, unifying every
instrumentation point the framework already had — the fused-step cache, the
DeviceFeed producer, the ZeRO comm path, the async checkpoint writer — into
**spans on one step timeline**:

* :mod:`.tracer` — per-thread span recorder (lock-free-ish bounded rings;
  near-zero cost when off; ``MXTPU_TRACE=1`` or ``profiler.set_state('run')``
  arms it; spans mirror into ``jax.profiler.TraceAnnotation``).
* :mod:`.export` — chrome-trace JSON serialization (pid/tid rows per thread,
  metadata names, the ``profiler.dump()``/``dumps()`` body).
* :mod:`.flops` — MFU accounting (XLA cost-analysis FLOPs with an analytic
  conv/matmul fallback, bounded step-time ring → steps/s + p50/p99 + MFU).
* :mod:`.metrics` — the subsystem counter stores (checkpoint / feed / comm /
  sanitizer), moved here from ``profiler.py``; the profiler re-exports them.

``mxtpu.profiler`` remains the user-facing facade — importing this package
directly is for framework internals and tests.

Span catalog (see docs/observability.md):

====================  =======================================================
``step/compile``      trace+lower+compile of a fused step (args: signature)
``step/execute``      one cache-hit fused-step dispatch
``feed/transfer``     DeviceFeed producer staging one batch host→device
``feed/stall``        consumer blocked waiting on the feed queue
``comm/exchange``     cross-process collective (``_process_exchange``)
``ckpt/snapshot``     device→host state capture (training thread)
``ckpt/write``        serialize+fsync of one step (writer thread)
``ckpt/commit``       atomic rename+COMMIT marker (writer thread)
``feed/queue_depth``  counter: prefetch queue occupancy
====================  =======================================================
"""

from . import export, flops, metrics, tracer
from .tracer import counter, enabled, instant, span

__all__ = ["tracer", "export", "flops", "metrics",
           "span", "instant", "counter", "enabled"]
