"""mxtpu.observability — unified step-timeline tracing + MFU accounting.

The reference ships profiling as a first-class subsystem (``src/profiler/``:
chrome://tracing export, aggregate stats, Domain/Task/Counter/Marker
objects). This package is that subsystem TPU-natively, unifying every
instrumentation point the framework already had — the fused-step cache, the
DeviceFeed producer, the ZeRO comm path, the async checkpoint writer — into
**spans on one step timeline**:

* :mod:`.tracer` — per-thread span recorder (lock-free-ish bounded rings;
  near-zero cost when off; ``MXTPU_TRACE=1`` or ``profiler.set_state('run')``
  arms it; spans mirror into ``jax.profiler.TraceAnnotation``).
* :mod:`.export` — chrome-trace JSON serialization (pid/tid rows per thread,
  metadata names, per-request swim-lanes, the ``profiler.dump()``/
  ``dumps()`` body, ``request_timeline``).
* :mod:`.flops` — MFU accounting (XLA cost-analysis FLOPs with an analytic
  conv/matmul fallback, bounded step-time ring → steps/s + p50/p99 + MFU).
* :mod:`.metrics` — the subsystem counter stores (checkpoint / feed / comm /
  sanitizer), moved here from ``profiler.py``; the profiler re-exports them.
* :mod:`.histogram` — bounded log-bucketed streaming histograms backing the
  serving latency percentiles (TTFT/queue-wait/prefill/first-decode/
  per-token) and fused-step times.
* :mod:`.exporter` — pull-based Prometheus/JSON metrics endpoint
  (``MXTPU_METRICS_PORT``; off by default).
* :mod:`.flight` — always-on crash flight recorder; postmortem bundles to
  ``MXTPU_FLIGHT_DIR`` on stalls, resize failures, scheduler-thread
  exceptions, and SIGTERM drains.

``mxtpu.profiler`` remains the user-facing facade — importing this package
directly is for framework internals and tests.

Span catalog (see docs/observability.md):

==========================  =================================================
``step/compile``            trace+lower+compile of a fused step
``step/execute``            one cache-hit fused-step dispatch
``feed/transfer``           DeviceFeed producer staging one batch
``feed/stall``              consumer blocked waiting on the feed queue
``comm/exchange``           cross-process collective (``_process_exchange``)
``ckpt/snapshot``           device→host state capture (training thread)
``ckpt/write``              serialize+fsync of one step (writer thread)
``ckpt/commit``             atomic rename+COMMIT marker (writer thread)
``feed/queue_depth``        counter: prefetch queue occupancy
``serving/submit``          instant: request enqueued (args: id)
``serving/admit``           instant: request admitted to a slot (args: id)
``serving/prefix_hit``      instant: radix prefix-cache hit (args: id)
``serving/prefix_miss``     instant: probe found nothing (args: id)
``serving/prefill_chunk``   one chunked-prefill dispatch (args: id)
``serving/first_token``     instant: first generated token (args: id)
``serving/decode``          one slot-batch decode dispatch (args: ids)
``serving/first_decode``    instant: slot's first decode emission (args: id)
``serving/retire``          instant: request left its slot (args: id)
``serving/drain_freeze``    instant: request frozen into a handoff (args: id)
``serving/adopt_resume``    instant: request resumed from a handoff (id)
``serving/drained``         instant: handoff complete (args: ids)
``serving/adopted``         instant: adoption complete (args: ids)
==========================  =================================================
"""

from . import (exporter, export, flight, flops, histogram, metrics,  # noqa
               tracer)
from .tracer import counter, enabled, instant, span

__all__ = ["tracer", "export", "flops", "metrics", "histogram",
           "exporter", "flight",
           "span", "instant", "counter", "enabled"]

# MXTPU_METRICS_PORT arms the scrape endpoint at import, mirroring how
# MXTPU_TRACE arms the tracer — off (no socket) when unset
exporter._maybe_start_from_env()
