"""Chrome-trace serialization of the span recorder (``profiler.dump`` body).

Produces the JSON Trace Event Format that chrome://tracing and Perfetto's
legacy importer open directly (the reference CLI surface:
``mx.profiler.dump()`` writes ``profile.json`` next to the run). Every
registered thread buffer becomes its own ``tid`` row under this process's
``pid``, with ``thread_name`` metadata events so the viewer labels the rows
("MainThread", "mxtpu-device-feed", "mxtpu-ckpt-writer") instead of showing
bare ids.

Events carry the recorder's monotonic ``perf_counter_ns``-derived
microsecond timestamps — a single clock across threads, so producer spans
visibly overlap the consumer's stall spans.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from . import tracer

__all__ = ["collect_events", "chrome_trace", "write_chrome_trace",
           "aggregate", "request_timeline", "request_lane_events",
           "REQUIRED_SPAN_KEYS", "REQUEST_LANE_PID"]

# the schema contract tests validate exported "X" events against
REQUIRED_SPAN_KEYS = ("name", "ph", "ts", "dur", "pid", "tid")

# synthetic pid for the per-request lane rows (one tid per request id) —
# far above any real pid so the viewer groups them as their own process
REQUEST_LANE_PID = 1 << 22


def collect_events(legacy_events: Optional[List[dict]] = None) -> List[dict]:
    """Snapshot every thread ring + the legacy Domain/Task/Counter/Marker
    event list into one flat chrome-trace event array (metadata rows first).
    Read-only: repeated calls over an unchanged recorder return identical
    output (the ``dump(finished=True)`` idempotency contract builds on
    this)."""
    pid = os.getpid()
    events: List[dict] = [{"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": "mxtpu"}}]
    for tid, tname, evs, dropped in tracer.snapshot_buffers():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
        if dropped:
            events.append({"ph": "i", "name": "trace/dropped_events",
                           "cat": "trace", "pid": pid, "tid": tid,
                           "ts": evs[0]["ts"] if evs else 0, "s": "t",
                           "args": {"dropped": dropped}})
        for ev in evs:
            e = dict(ev)
            e["pid"] = pid
            e["tid"] = tid
            events.append(e)
    for ev in legacy_events or []:
        e = dict(ev)
        e.setdefault("pid", pid)
        e.setdefault("tid", 0)
        events.append(e)
    return events


def _event_request_ids(ev: dict):
    """Request ids an event is tagged with: the serving spans carry
    ``args.id`` (one request) or ``args.ids`` (a decode dispatch over the
    whole slot batch)."""
    args = ev.get("args")
    if not isinstance(args, dict):
        return ()
    rid = args.get("id")
    ids = args.get("ids")
    if rid is not None and not isinstance(ids, (list, tuple)):
        return (rid,)
    if rid is not None:
        return (rid, *ids)
    return tuple(ids) if isinstance(ids, (list, tuple)) else ()


def request_timeline(rid: int,
                     events: Optional[List[dict]] = None) -> List[dict]:
    """Every recorded event tagged with request ``rid``, time-sorted — one
    request's full life (submit → admission → prefill chunks → decode
    dispatches → retire, including the drain/adopt markers when the request
    crossed an engine handoff). ``ServingEngine.request_timeline`` is the
    public face."""
    if events is None:
        events = collect_events()
    out = [e for e in events if rid in _event_request_ids(e)]
    out.sort(key=lambda e: e.get("ts", 0))
    return out


def request_lane_events(events: List[dict]) -> List[dict]:
    """Synthetic per-request chrome-trace lanes: every request-tagged event
    duplicated under ``pid = REQUEST_LANE_PID`` with ``tid = request id``,
    plus naming metadata — so the viewer shows one swim-lane per request
    alongside the real thread rows (a decode span over N active slots lands
    in all N lanes)."""
    lanes: List[dict] = []
    seen: set = set()
    for ev in events:
        for rid in _event_request_ids(ev):
            if rid not in seen:
                seen.add(rid)
                lanes.append({"ph": "M", "name": "thread_name",
                              "pid": REQUEST_LANE_PID, "tid": rid,
                              "args": {"name": f"request {rid}"}})
            e = dict(ev)
            e["pid"] = REQUEST_LANE_PID
            e["tid"] = rid
            lanes.append(e)
    if seen:
        lanes.insert(0, {"ph": "M", "name": "process_name",
                         "pid": REQUEST_LANE_PID, "tid": 0,
                         "args": {"name": "mxtpu-requests"}})
    return lanes


def chrome_trace(legacy_events: Optional[List[dict]] = None,
                 xplane_dir: Optional[str] = None,
                 events: Optional[List[dict]] = None,
                 request_lanes: bool = False) -> dict:
    """The full dump payload. ``events`` short-circuits collection (used by
    the profiler's frozen final snapshot); ``request_lanes=True`` appends
    the synthetic per-request swim-lanes (flight-recorder bundles use it)."""
    if events is None:
        events = collect_events(legacy_events)
    if request_lanes:
        events = list(events) + request_lane_events(events)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    if xplane_dir:
        # the paired XLA device trace (jax.profiler XPlane dir, open in
        # Perfetto/TensorBoard); span names match via TraceAnnotation
        payload["otherData"] = {"xplane_dir": xplane_dir}
    return payload


def write_chrome_trace(fname: str, payload: dict) -> str:
    tmp = f"{fname}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, fname)   # readers never observe a torn dump
    return fname


def aggregate(events: List[dict]) -> dict:
    """Per-name duration stats over "X" spans:
    ``{name: [count, total_ms, min_ms, max_ms]}`` — the data behind the
    reference's aggregate-stats table (``profiler.get_summary()``)."""
    stats: dict = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        s = stats.setdefault(e["name"], [0, 0.0, float("inf"), 0.0])
        dur = e.get("dur", 0.0) / 1000.0  # us -> ms
        s[0] += 1
        s[1] += dur
        s[2] = min(s[2], dur)
        s[3] = max(s[3], dur)
    return stats
