"""Pull-based metrics endpoint — Prometheus text + JSON over stdlib http.

Nothing here changes what is recorded; this is the scrape surface over the
stores that already exist (``metrics.get_*_stats``, the compile-cache
registry, MFU, and the ``histogram`` store), so an external collector can
watch a serving or training process without attaching a profiler:

* ``GET /metrics``  — Prometheus text exposition (``mxtpu_<store>_<key>``
  gauges; histograms as ``mxtpu_hist_<name>{quantile="…"}`` plus
  ``_count``/``_sum``).
* ``GET /json``     — the same snapshot as one JSON document (also served
  at ``/metrics.json``).

Off by default. Arm with ``MXTPU_METRICS_PORT`` (read when
``mxtpu.observability`` imports — the env analogue of ``MXTPU_TRACE``) or
programmatically via :func:`start`. Port ``0`` asks the OS for a free port
(tests); the bound port is ``exporter.active().port``. Binds
``MXTPU_METRICS_HOST`` (default 127.0.0.1 — scraping a fleet through
0.0.0.0 is an explicit opt-in, not a default listening socket).

The server runs daemon threads (``ThreadingHTTPServer``) and every scrape
takes fresh snapshots under each store's own lock — a scrape can never tear
a counter pair or block the scheduler for more than one dict copy.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import histogram

__all__ = ["MetricsExporter", "collect_snapshot", "prometheus_text",
           "start", "stop", "active", "ENV_PORT", "ENV_HOST"]

ENV_PORT = "MXTPU_METRICS_PORT"
ENV_HOST = "MXTPU_METRICS_HOST"

_log = logging.getLogger("mxtpu.observability")


def collect_snapshot() -> dict:
    """One consistent-enough snapshot of every stats store (each block is
    internally consistent under its own lock). The JSON endpoint serves this
    verbatim; the Prometheus endpoint flattens it."""
    from . import metrics
    snap = {
        "serving": metrics.get_serving_stats(),
        "router": metrics.get_router_stats(),
        "sched": metrics.get_sched_stats(),
        "quant": metrics.get_quant_stats(),
        "comm": metrics.get_comm_stats(),
        "feed": metrics.get_feed_stats(),
        "checkpoint": metrics.get_checkpoint_stats(),
        "resilience": metrics.get_resilience_stats(),
        "memory": metrics.get_memory_stats(),
        "sanitizer": metrics.get_sanitizer_stats(),
        "histograms": histogram.get_histogram_stats(),
    }
    try:
        from ..step_cache import snapshot as _caches
        snap["compile_caches"] = _caches()
    except Exception:
        snap["compile_caches"] = {}
    try:
        from . import flops
        snap["mfu"] = flops.get_mfu_stats()
    except Exception:
        snap["mfu"] = {}
    return snap


def _metric_name(*parts: str) -> str:
    out = "_".join(p for p in parts if p)
    return "".join(c if c.isalnum() or c == "_" else "_" for c in out)


def _flatten(prefix: str, obj, lines: list) -> None:
    if isinstance(obj, dict):
        for k, v in sorted(obj.items()):
            _flatten(_metric_name(prefix, str(k)), v, lines)
    elif isinstance(obj, bool):
        lines.append(f"{prefix} {int(obj)}")
    elif isinstance(obj, (int, float)) and obj == obj:   # drop NaN
        val = f"{obj:.10g}" if isinstance(obj, float) else str(obj)
        lines.append(f"{prefix} {val}")
    # strings / None / lists are labels or metadata, not gauges


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Prometheus text exposition format (0.0.4): one ``mxtpu_<store>_<key>``
    gauge per numeric leaf; each histogram summarized as quantile gauges with
    the classic ``_count``/``_sum`` pair."""
    if snap is None:
        snap = collect_snapshot()
    lines: list = []
    for store, block in snap.items():
        if store == "histograms":
            continue
        # the serving series carry the engine identity (minted at
        # ServingEngine construction) as a proper Prometheus label, so a
        # scrape of N sequential single-engine processes stays
        # distinguishable; the store itself is process-global — with
        # several in-process engines the label names the LAST writer
        if store == "serving" and isinstance(block, dict) \
                and block.get("engine") not in (None, "none"):
            sub: list = []
            _flatten(_metric_name("mxtpu", store), block, sub)
            eng = str(block["engine"]).replace('"', "'")
            lines.extend(f'{name}{{engine="{eng}"}} {val}'
                         for name, _, val in
                         (ln.rpartition(" ") for ln in sub))
            continue
        _flatten(_metric_name("mxtpu", store), block, lines)
    for name, s in snap.get("histograms", {}).items():
        base = _metric_name("mxtpu_hist", name)
        lines.append(f"{base}_count {s['count']}")
        lines.append(f"{base}_sum {s['sum']:.10g}")
        for q, qname in histogram.QUANTILES:
            lines.append(f'{base}{{quantile="{q}"}} {s[qname]:.10g}')
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path in ("/json", "/metrics.json"):
                body = json.dumps(collect_snapshot(), default=str).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "try /metrics or /json")
                return
        except Exception as e:
            self.send_error(500, f"{type(e).__name__}: {e}")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        _log.debug("exporter: " + fmt, *args)


class MetricsExporter:
    """One scrape endpoint. ``start()`` binds and serves on a daemon thread;
    ``port`` is the actual bound port (useful with port 0)."""

    def __init__(self, port: int, host: Optional[str] = None):
        self.host = host if host is not None \
            else os.environ.get(ENV_HOST, "127.0.0.1")
        self._requested_port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._requested_port

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="mxtpu-metrics-exporter",
                                        daemon=True)
        self._thread.start()
        _log.info("metrics exporter serving on %s:%d (/metrics, /json)",
                  self.host, self.port)
        return self

    def stop(self) -> None:
        srv, self._server = self._server, None
        t, self._thread = self._thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if t is not None:
            t.join(timeout=5)

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False


# -- module singleton (env-armed) --------------------------------------------

_singleton_lock = threading.Lock()
_singleton: Optional[MetricsExporter] = None


def start(port: Optional[int] = None,
          host: Optional[str] = None) -> MetricsExporter:
    """Start (or return) the process-wide exporter. ``port`` defaults to
    ``MXTPU_METRICS_PORT``."""
    global _singleton
    with _singleton_lock:
        if _singleton is not None:
            return _singleton
        if port is None:
            raw = os.environ.get(ENV_PORT, "")
            if not raw:
                raise ValueError(
                    f"no port given and {ENV_PORT} unset — the exporter is "
                    "off by default")
            port = int(raw)
        _singleton = MetricsExporter(port, host=host).start()
        return _singleton


def stop() -> None:
    global _singleton
    with _singleton_lock:
        ex, _singleton = _singleton, None
    if ex is not None:
        ex.stop()


def active() -> Optional[MetricsExporter]:
    return _singleton


def _maybe_start_from_env() -> None:
    raw = os.environ.get(ENV_PORT, "")
    if not raw:
        return
    try:
        start(int(raw))
    except Exception as e:   # a bad port must never kill the import
        _log.warning("metrics exporter failed to start on %s=%r: %s",
                     ENV_PORT, raw, e)
