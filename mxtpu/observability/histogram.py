"""Bounded log-bucketed streaming histograms (HDR-style) + the latency store.

``get_serving_stats()`` used to expose latency only as ``*_ms_last`` scalars —
one overwrite per event, torn between the scheduler thread and whatever thread
read it, and useless for tail latency (ROADMAP item 1 demands p50/p99 TTFT).
:class:`LogHistogram` is the replacement: a fixed-size array of
geometrically-spaced buckets, so recording is O(1) with no allocation after
construction, memory is bounded regardless of sample count, and two histograms
recorded on different engines (or across a ``drain()``/``adopt()`` handoff)
merge by adding bucket counts — exactly the HDRHistogram/Prometheus-classic
trick.

Bucket scheme: bucket ``i`` covers ``(lo·g^(i-1), lo·g^i]`` with growth
``g = 1.04`` from ``lo = 1 µs`` (1e-3 ms) — ~590 buckets spanning 1 µs to
~3 h. A quantile is reported as the geometric midpoint of its bucket, clamped
to the observed min/max, so the relative error is bounded by ``√g − 1 ≈ 2 %``
(the bound ``tests/test_telemetry.py`` checks against ``numpy.percentile``).
Quantile rank follows the inverted-CDF convention (the value of the
``⌈q·n⌉``-th order statistic), matching
``numpy.percentile(..., method="inverted_cdf")``.

The module-level store (``record_value`` / ``get_histogram`` /
``get_histogram_stats`` / ``reset_histograms``) is THE guarded record path for
last-value latency scalars: ``metrics.record_serving`` routes every
``*_ms_last`` key here, and ``get_serving_stats()`` derives the compat
``*_last``/``*_total`` keys plus ``*_p50/p90/p99/p999`` from the same
histogram — one lock, one writer discipline, no torn scalar pairs.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

__all__ = ["LogHistogram", "record_value", "get_histogram",
           "get_histogram_stats", "reset_histograms", "QUANTILES"]

# the quantile set every summary reports (serving stats, exporter, bench)
QUANTILES = ((0.50, "p50"), (0.90, "p90"), (0.99, "p99"), (0.999, "p999"))


class LogHistogram:
    """One bounded log-bucketed histogram. Not internally locked — the
    module store (or any single owning thread) provides exclusion; `record`
    is O(1) into a preallocated count array."""

    __slots__ = ("lo", "growth", "_log_g", "counts", "count", "sum",
                 "min", "max", "last")

    #: default range: 1 µs .. ~3 h in ms units, 4 % geometric buckets
    LO = 1e-3
    HI = 1e7
    GROWTH = 1.04

    def __init__(self, lo: float = LO, hi: float = HI,
                 growth: float = GROWTH):
        if not (lo > 0 and hi > lo and growth > 1):
            raise ValueError("need 0 < lo < hi and growth > 1")
        self.lo = float(lo)
        self.growth = float(growth)
        self._log_g = math.log(growth)
        n = int(math.ceil(math.log(hi / lo) / self._log_g)) + 1
        self.counts: List[int] = [0] * (n + 1)   # +1: overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.last = 0.0

    # -- recording -----------------------------------------------------------
    def _index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        i = int(math.ceil(math.log(v / self.lo) / self._log_g))
        return min(i, len(self.counts) - 1)

    def record(self, v: float) -> None:
        v = float(v)
        if v < 0 or v != v:          # negative clock skew / NaN: clamp out
            v = 0.0
        self.counts[self._index(v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if v < self.min else self.min
        self.max = v if v > self.max else self.max
        self.last = v

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Add ``other``'s buckets into self (associative + commutative on
        counts/sum/min/max; ``last`` takes the non-empty operand's)."""
        if (other.lo != self.lo or other.growth != self.growth
                or len(other.counts) != len(self.counts)):
            raise ValueError("cannot merge histograms with different buckets")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        if other.count:
            self.last = other.last
        return self

    def copy(self) -> "LogHistogram":
        h = LogHistogram.__new__(LogHistogram)
        h.lo, h.growth, h._log_g = self.lo, self.growth, self._log_g
        h.counts = list(self.counts)
        h.count, h.sum = self.count, self.sum
        h.min, h.max, h.last = self.min, self.max, self.last
        return h

    # -- reading -------------------------------------------------------------
    def _bucket_value(self, i: int) -> float:
        if i <= 0:
            v = self.lo
        else:
            # geometric midpoint of (lo·g^(i-1), lo·g^i]: √g off either edge
            v = self.lo * self.growth ** (i - 0.5)
        if self.min <= self.max:     # clamp into the observed range
            v = min(max(v, self.min), self.max)
        return v

    def percentile(self, q: float) -> float:
        """Inverted-CDF quantile: the bucket holding the ⌈q·n⌉-th sample,
        reported at its geometric midpoint (≤ √g−1 relative error)."""
        if not self.count:
            return 0.0
        rank = max(1, int(math.ceil(q * self.count)))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                return self._bucket_value(i)
        return self._bucket_value(len(self.counts) - 1)

    def summary(self) -> dict:
        out = {"count": self.count,
               "sum": round(self.sum, 6),
               "last": self.last,
               "min": self.min if self.count else 0.0,
               "max": self.max if self.count else 0.0}
        for q, name in QUANTILES:
            out[name] = self.percentile(q)
        return out

    def to_dict(self) -> dict:
        """Serializable form (flight-recorder bundles; sparse buckets)."""
        return {"lo": self.lo, "growth": self.growth,
                "buckets": {str(i): c for i, c in enumerate(self.counts) if c},
                "count": self.count, "sum": self.sum,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0, "last": self.last}


# ---------------------------------------------------------------------------
# module store — THE guarded record path for latency series
# ---------------------------------------------------------------------------

_hist_lock = threading.Lock()
_hists: Dict[str, LogHistogram] = {}


def record_value(name: str, value: float) -> None:
    """Record one sample into the named histogram (created on first use).
    This is the locked single-writer path ``metrics.record_serving`` routes
    every ``*_ms_last`` scalar through."""
    with _hist_lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = LogHistogram()
        h.record(value)


def get_histogram(name: str) -> Optional[LogHistogram]:
    """A consistent COPY of one named histogram (None when never recorded)."""
    with _hist_lock:
        h = _hists.get(name)
        return h.copy() if h is not None else None


def get_histogram_stats() -> Dict[str, dict]:
    """``{name: summary}`` for every live histogram — the exporter's and
    ``profiler.dumps()``'s histogram block."""
    with _hist_lock:
        snap = {k: h.copy() for k, h in _hists.items()}
    return {k: h.summary() for k, h in sorted(snap.items())}


def reset_histograms(prefix: Optional[str] = None) -> None:
    """Drop histograms (all, or only names under ``prefix``) — tests, bench
    legs, ``reset_serving_stats``."""
    with _hist_lock:
        if prefix is None:
            _hists.clear()
        else:
            for k in [k for k in _hists if k.startswith(prefix)]:
                del _hists[k]
