"""Crash flight recorder — always-on bounded rings + a postmortem bundle.

The tracer answers "what is this process doing *right now*" only while
someone is watching. The flight recorder answers the question that actually
gets asked in production: "it just died / stalled at 3am — what was it doing
*right before that*?" It keeps three bounded, always-on rings (costing a few
dict appends per event, nothing on the step hot path):

* **events** — :func:`record` notes from the crash-adjacent code paths
  (watchdog stall reports, resize failures, scheduler-thread exceptions,
  SIGTERM drains), capped at ``MXTPU_FLIGHT_EVENTS`` (default 256);
* **requests** — :func:`note_request` one-line summaries of the last N
  finished serving requests (``MXTPU_FLIGHT_REQUESTS``, default 32), written
  by ``ServingRequest._finish`` at the single terminal transition;
* **counters** — a baseline of the cumulative stats stores taken at import
  (and each :func:`dump`), so a bundle shows *deltas over the crash window*,
  not lifetime totals.

:func:`dump` writes a bundle directory ``flight-<reason>-<pid>-<seq>/``
containing ``trace.json`` (the chrome trace with per-request lanes — open in
Perfetto) and ``stats.json`` (reason, rings, counter deltas, and a full
stats snapshot). The rings are always on; **disk writes are opt-in** via
``MXTPU_FLIGHT_DIR`` (or an explicit ``out_dir``) — with neither set,
``dump`` returns ``None`` and touches nothing. Every step of the dump path
is exception-guarded: the crash handler must never crash the crash.

Wired dump sites: ``Watchdog._handle_stall`` (reason ``"stall"``),
``ElasticMesh.resize_now`` failure paths (``"resize_error"``), the serving
scheduler thread's exception latch (``"scheduler_error"``), and the SIGTERM
preemption handler (``"sigterm_drain"``).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["record", "note_request", "dump", "load", "reset",
           "snapshot_rings", "ENV_DIR", "ENV_EVENTS", "ENV_REQUESTS"]

ENV_DIR = "MXTPU_FLIGHT_DIR"
ENV_EVENTS = "MXTPU_FLIGHT_EVENTS"
ENV_REQUESTS = "MXTPU_FLIGHT_REQUESTS"

_log = logging.getLogger("mxtpu.observability")


def _cap(env: str, default: int) -> int:
    try:
        return max(8, int(os.environ.get(env, str(default))))
    except ValueError:
        return default


_lock = threading.Lock()
_events: "deque" = deque(maxlen=_cap(ENV_EVENTS, 256))
_requests: "deque" = deque(maxlen=_cap(ENV_REQUESTS, 32))
_baseline: dict = {}          # cumulative counters at the window start
_seq = itertools.count()


# counters worth delta-ing across a crash window (cumulative stores only —
# gauges like occupancy delta to noise)
_COUNTER_STORES = ("serving", "resilience", "comm", "feed", "checkpoint",
                   "quant")


def _counters() -> dict:
    from . import metrics
    out = {}
    for store in _COUNTER_STORES:
        try:
            block = getattr(metrics, f"get_{store}_stats")()
        except Exception:
            continue
        out[store] = {k: v for k, v in block.items()
                      if isinstance(v, (int, float))
                      and not isinstance(v, bool)}
    return out


def _rebaseline() -> None:
    global _baseline
    try:
        _baseline = _counters()
    except Exception:
        _baseline = {}


_rebaseline()


def record(kind: str, **args) -> None:
    """One crash-context breadcrumb into the bounded event ring (always on;
    never raises)."""
    try:
        with _lock:
            _events.append({"ts": time.time(), "kind": str(kind),
                            "args": args})
    except Exception:
        pass


def note_request(info: dict) -> None:
    """One finished request's summary into the last-N ring (called from the
    ``ServingRequest`` terminal transition; never raises)."""
    try:
        with _lock:
            _requests.append(dict(info))
    except Exception:
        pass


def snapshot_rings() -> dict:
    with _lock:
        return {"events": list(_events), "requests": list(_requests)}


def _counter_deltas(now: dict) -> dict:
    deltas: dict = {}
    for store, block in now.items():
        base = _baseline.get(store, {})
        d = {}
        for k, v in block.items():
            dv = v - base.get(k, 0)
            if dv:
                d[k] = round(dv, 6) if isinstance(dv, float) else dv
        if d:
            deltas[store] = d
    return deltas


def dump(reason: str, extra: Optional[dict] = None,
         out_dir: Optional[str] = None) -> Optional[str]:
    """Write one postmortem bundle; returns its directory path, or ``None``
    when disk writes are not armed (neither ``out_dir`` nor
    ``MXTPU_FLIGHT_DIR``). Exception-guarded end to end — a failed dump logs
    and returns ``None`` rather than propagating into the crash path that
    triggered it."""
    try:
        target = out_dir or os.environ.get(ENV_DIR, "")
        if not target:
            return None
        bundle = os.path.join(
            target, f"flight-{reason}-{os.getpid()}-{next(_seq)}")
        os.makedirs(bundle, exist_ok=True)

        stats: dict = {"reason": reason, "ts": time.time(),
                       "pid": os.getpid(), "extra": extra or {}}
        stats.update(snapshot_rings())
        try:
            from . import exporter
            now = _counters()
            stats["counter_deltas"] = _counter_deltas(now)
            stats["stats"] = exporter.collect_snapshot()
        except Exception as e:
            stats["stats_error"] = f"{type(e).__name__}: {e}"
        try:
            from . import export
            export.write_chrome_trace(
                os.path.join(bundle, "trace.json"),
                export.chrome_trace(request_lanes=True))
        except Exception as e:
            stats["trace_error"] = f"{type(e).__name__}: {e}"

        tmp = os.path.join(bundle, f".stats.tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(stats, f, default=str)
        os.replace(tmp, os.path.join(bundle, "stats.json"))
        _rebaseline()   # next bundle's deltas start from this window's end
        _log.error("flight recorder: wrote %s bundle to %s", reason, bundle)
        return bundle
    except Exception as e:
        try:
            _log.error("flight recorder dump failed: %s", e)
        except Exception:
            pass
        return None


def load(path: str) -> dict:
    """Load a bundle back: ``{"stats": ..., "trace": ...}`` (triage tooling
    and the tier-1 flight test)."""
    out: dict = {}
    with open(os.path.join(path, "stats.json")) as f:
        out["stats"] = json.load(f)
    trace_path = os.path.join(path, "trace.json")
    if os.path.exists(trace_path):
        with open(trace_path) as f:
            out["trace"] = json.load(f)
    return out


def reset() -> None:
    """Clear the rings and re-baseline the counters (tests)."""
    with _lock:
        _events.clear()
        _requests.clear()
    _rebaseline()
