"""Symbol — the declarative graph frontend (mx.sym parity).

Capability parity with ``python/mxnet/symbol/symbol.py``: Variable/op composition,
``infer_shape``/``infer_type`` (:841-1021), ``bind``/``simple_bind`` (:1288,1552),
JSON save/load (``tojson`` :1218, load :2549-2582), Group, get_internals.

Re-design for this stack: the reference Symbol wraps an NNVM graph handle and its
passes (InferShape/InferType as C++ graph passes, GraphExecutor for binding). Here a
Symbol is a small Python DAG over the SAME op registry the imperative layer uses:

* shape/type inference = a topological walk that calls ``jax.eval_shape`` per node
  (XLA's abstract evaluation IS the InferShape pass) plus per-op *parameter shape
  rules* for the learnable inputs the reference infers backwards (conv weight from
  data channels etc. — the only genuinely bidirectional part of nnvm's pass);
* ``bind`` returns an Executor that evaluates the DAG on raw jax arrays (forward) and
  differentiates it with one ``jax.vjp`` (backward) — the GraphExecutor's Gradient +
  PlanMemory + engine-push machinery collapses into XLA;
* loss-fused heads (SoftmaxOutput) keep their reference backward semantics because
  the registered ops already carry ``jax.custom_vjp`` rules.
"""

from __future__ import annotations

import ast
import inspect
import json
import threading
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..attribute import apply as _with_scope_attrs
from ..base import dtype_np, dtype_name
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "fromjson"]

# aux-state parameter names (reference: op-declared mutable inputs; BatchNorm's
# moving stats are the only instance in the op corpus)
_AUX_PARAMS = {"moving_mean", "moving_var"}

_name_lock = threading.Lock()
_name_counters: Dict[str, int] = {}


def _auto_name(base: str) -> str:
    with _name_lock:
        n = _name_counters.get(base, 0)
        _name_counters[base] = n + 1
    return f"{base}{n}"


def _reset_names():  # test helper (NameManager parity)
    with _name_lock:
        _name_counters.clear()


class _Node:
    """One DAG node: a variable (op_key None) or an op application.

    ``attrs`` holds op config AND user/scope attrs (both visible to
    ``Symbol.attr``, as in the reference); ``user_keys`` names the subset that
    is user metadata (AttrScope / ``attr=``) so op-kwarg extraction skips it —
    user attrs keep their plain reference names (``ctx_group``, not
    ``__ctx_group__``)."""

    __slots__ = ("op_key", "name", "attrs", "inputs", "input_params", "is_aux",
                 "num_outputs", "user_keys")

    def __init__(self, op_key, name, attrs=None, inputs=(), input_params=(),
                 is_aux=False, num_outputs=1, user_keys=()):
        self.op_key = op_key
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)           # [(node, out_idx)]
        self.input_params = list(input_params)  # param name per input; "*" varargs
        self.is_aux = is_aux
        self.num_outputs = num_outputs
        self.user_keys = frozenset(user_keys)


def _op_attrs(node: _Node) -> dict:
    """The op-kwarg subset of a node's attrs: internal ``__*__`` markers and
    user/scope attrs excluded."""
    return {k: v for k, v in node.attrs.items()
            if not k.startswith("__") and k not in node.user_keys}


def _tensor_params(op) -> List[str]:
    """Which signature params of an op fn are tensor inputs (vs attrs)."""
    out = []
    for p in inspect.signature(op.fn).parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            out.append("*")
        elif p.kind == inspect.Parameter.POSITIONAL_OR_KEYWORD and (
                p.default is inspect.Parameter.empty
                or p.name in ("bias", "gamma", "beta", "moving_mean", "moving_var",
                              "weight", "label")):
            out.append(p.name)
    return out


def _topo(heads) -> List[_Node]:
    seen, order = set(), []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child, _ in node.inputs:
            visit(child)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


# ---------------------------------------------------------------------------
# parameter shape rules — the "backward" half of InferShape
# (reference: per-op FInferShape filling unknown arg shapes, e.g.
# src/operator/nn/convolution.cc ConvolutionShape)
# ---------------------------------------------------------------------------


def _fc_rule(ins, attrs):
    d = ins["data"]
    nh = int(attrs.get("num_hidden", 0))
    in_units = int(np.prod(d[1:])) if attrs.get("flatten", True) else d[-1]
    return {"weight": (nh, in_units), "bias": (nh,)}


def _conv_rule(ins, attrs):
    d = ins["data"]
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    kernel = tuple(attrs.get("kernel", ()))
    return {"weight": (nf, d[1] // ng) + kernel, "bias": (nf,)}


def _deconv_rule(ins, attrs):
    d = ins["data"]
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    kernel = tuple(attrs.get("kernel", ()))
    return {"weight": (d[1], nf // ng) + kernel, "bias": (nf,)}


def _norm_rule(ins, attrs):
    c = ins["data"][attrs.get("axis", 1)]
    return {k: (c,) for k in ("gamma", "beta", "moving_mean", "moving_var")}


def _ln_rule(ins, attrs):
    c = ins["data"][attrs.get("axis", -1)]
    return {"gamma": (c,), "beta": (c,)}


def _embedding_rule(ins, attrs):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _softmax_output_rule(ins, attrs):
    d = ins["data"]
    return {"label": d[:-1] if len(d) > 1 else d}


_PARAM_SHAPE_RULES = {
    "FullyConnected": _fc_rule,
    "Convolution": _conv_rule,
    "Deconvolution": _deconv_rule,
    "BatchNorm": _norm_rule,
    "InstanceNorm": _ln_rule,
    "LayerNorm": _ln_rule,
    "Embedding": _embedding_rule,
    "SoftmaxOutput": _softmax_output_rule,
    "LinearRegressionOutput": _softmax_output_rule,
    "LogisticRegressionOutput": _softmax_output_rule,
    "MAERegressionOutput": _softmax_output_rule,
}


# ---------------------------------------------------------------------------
# graph evaluation (shared by Executor and SymbolBlock)
# ---------------------------------------------------------------------------


def eval_graph(heads, feed: Dict[str, Any], is_train: bool = False,
               aux_updates: Optional[dict] = None,
               resolved: Optional[dict] = None):
    """Topologically evaluate the DAG on raw arrays.

    ``resolved`` caches per-node resolved attrs (RNG keys, training flags) so a
    backward vjp replay sees the identical program as the forward pass.
    ``aux_updates`` (name → new value) collects BatchNorm moving-stat updates — the
    reference mutates aux NDArrays inside the op; here the executor owns the write.
    """
    cache: Dict[int, tuple] = {}

    def ev(node: _Node):
        got = cache.get(id(node))
        if got is not None:
            return got
        if node.op_key is None:
            if node.name not in feed:
                raise ValueError(f"eval_graph: no value bound for argument "
                                 f"{node.name!r}")
            out = (feed[node.name],)
            cache[id(node)] = out
            return out
        op = _reg.get_op(node.op_key)
        var_args, kw = [], {}
        for (child, idx), pname in zip(node.inputs, node.input_params):
            val = ev(child)[idx]
            if pname == "*":
                var_args.append(val)
            else:
                kw[pname] = val
        attrs = _op_attrs(node)
        # the whole BatchNorm FAMILY takes the batch-stats path in training
        # (SyncBatchNorm's cross-device sync = global-batch stats under a
        # dp-sharded input; the v1/cuDNN names alias the same op)
        if node.op_key in ("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm",
                           "contrib.SyncBatchNorm") and is_train \
                and not attrs.get("use_global_stats", False):
            res, mean, v = _reg.get_op("batch_norm_train").fn(
                kw["data"], kw["gamma"], kw["beta"],
                eps=attrs.get("eps", 1e-3),
                fix_gamma=attrs.get("fix_gamma", True),
                axis=attrs.get("axis", 1))
            if aux_updates is not None:
                mom = attrs.get("momentum", 0.9)
                for pname, new in (("moving_mean", mean), ("moving_var", v)):
                    i = node.input_params.index(pname)
                    aux_node = node.inputs[i][0]
                    aux_updates[aux_node.name] = mom * kw[pname] + (1 - mom) * new
            out = (res,)
        else:
            if op.resolve_kwargs is not None:
                if resolved is not None and id(node) in resolved:
                    attrs = resolved[id(node)]
                else:
                    attrs = op.resolve_kwargs(attrs)
                    if resolved is not None:
                        resolved[id(node)] = attrs
            res = op.fn(*var_args, **kw, **attrs)
            out = tuple(res) if isinstance(res, (tuple, list)) else (res,)
        cache[id(node)] = out
        return out

    return [ev(node)[idx] for node, idx in heads]


# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------


class Symbol:
    """One or more DAG heads (a Group is just a multi-head Symbol)."""

    __slots__ = ("_heads",)

    def __init__(self, heads):
        self._heads = list(heads)

    # -- identity ----------------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._heads)
        return f"<Symbol {names}>"

    def __iter__(self):
        return (self[i] for i in range(len(self.list_outputs())))

    # -- graph views -------------------------------------------------------
    def list_arguments(self) -> List[str]:
        return [n.name for n in _topo(self._heads)
                if n.op_key is None and not n.is_aux]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in _topo(self._heads) if n.op_key is None and n.is_aux]

    def list_outputs(self) -> List[str]:
        out = []
        for node, idx in self._heads:
            suffix = "" if node.num_outputs == 1 else str(idx)
            out.append(f"{node.name}_output{suffix}" if node.op_key is not None
                       else node.name)
        return out

    def list_inputs(self) -> List[str]:
        return self.list_arguments() + self.list_auxiliary_states()

    def get_internals(self) -> "Symbol":
        heads = []
        for node in _topo(self._heads):
            for i in range(max(1, node.num_outputs)):
                heads.append((node, i))
        return Symbol(heads)

    def get_children(self) -> Optional["Symbol"]:
        node, _ = self._heads[0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise ValueError(f"no output named {index!r}; have {names}")
            index = names.index(index)
        return Symbol([self._heads[index]])

    # -- attrs -------------------------------------------------------------
    def attr(self, key: str):
        v = self._heads[0][0].attrs.get(key)
        return None if v is None else str(v)

    def list_attr(self) -> Dict[str, str]:
        return {k: str(v) for k, v in self._heads[0][0].attrs.items()
                if not k.startswith("__")}

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        return {n.name: {k: str(v) for k, v in n.attrs.items()}
                for n in _topo(self._heads) if n.attrs}

    # -- inference -----------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes) (symbol.py:841 parity).

        Known shapes are given as kwargs ``name=shape``; unknown learnable-input
        shapes are derived by per-op parameter rules + ``jax.eval_shape``.
        """
        if args:
            kwargs.update(zip(self.list_arguments(), args))
        known: Dict[str, tuple] = {}
        for node in _topo(self._heads):
            if node.op_key is None and node.attrs.get("__shape__") is not None:
                known[node.name] = tuple(node.attrs["__shape__"])
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        memo: Dict[int, tuple] = {}

        def shapes_of(node: _Node):
            got = memo.get(id(node))
            if got is not None:
                return got
            if node.op_key is None:
                if node.name not in known:
                    return None
                out = (known[node.name],)
                memo[id(node)] = out
                return out
            op = _reg.get_op(node.op_key)
            in_shapes: Dict[str, tuple] = {}
            var_shapes: List[tuple] = []
            unknown: List[tuple] = []
            for (child, idx), pname in zip(node.inputs, node.input_params):
                s = shapes_of(child)
                if s is None:
                    if child.op_key is None:
                        unknown.append((pname, child))
                        in_shapes[pname] = None
                    else:
                        return None
                elif pname == "*":
                    var_shapes.append(s[idx])
                else:
                    in_shapes[pname] = s[idx]
            if unknown:
                rule = _PARAM_SHAPE_RULES.get(node.op_key)
                if rule is None:
                    raise ValueError(
                        f"infer_shape: cannot infer shape of "
                        f"{[c.name for _, c in unknown]} for op {node.op_key} "
                        f"(no parameter rule; declare the shape on the Variable)")
                derived = rule({k: v for k, v in in_shapes.items()
                                if v is not None}, node.attrs)
                for pname, child in unknown:
                    if pname not in derived:
                        raise ValueError(f"infer_shape: rule for {node.op_key} "
                                         f"cannot derive {pname!r}")
                    known[child.name] = tuple(int(x) for x in derived[pname])
                    memo[id(child)] = (known[child.name],)
                    in_shapes[pname] = known[child.name]
            attrs = _op_attrs(node)
            if op.resolve_kwargs is not None:
                attrs = op.resolve_kwargs(attrs)

            def f(*va, **kw):
                return op.fn(*va, **kw, **attrs)

            structs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in var_shapes]
            kw_structs = {k: jax.ShapeDtypeStruct(v, jnp.float32)
                          for k, v in in_shapes.items() if v is not None}
            res = jax.eval_shape(f, *structs, **kw_structs)
            out = tuple(tuple(r.shape) for r in res) \
                if isinstance(res, (tuple, list)) else (tuple(res.shape),)
            memo[id(node)] = out
            return out

        out_shapes = []
        for node, idx in self._heads:
            s = shapes_of(node)
            if s is None:
                return None, None, None
            out_shapes.append(s[idx])
        arg_shapes = [known.get(n) for n in self.list_arguments()]
        aux_shapes = [known.get(n) for n in self.list_auxiliary_states()]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """All-float32 default typing (the registry ops are dtype-polymorphic;
        mixed-precision symbolic typing is driven by the executor's array dtypes)."""
        n_args = len(self.list_arguments())
        return ([np.float32] * n_args,
                [np.float32] * len(self._heads),
                [np.float32] * len(self.list_auxiliary_states()))

    # -- binding -------------------------------------------------------------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        return Executor(self, ctx, dict(args or {}), dict(aux_states or {}),
                        dict(args_grad or {}), grad_req)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None, **kwargs):
        """Infer shapes from the given input shapes and allocate all arrays
        (symbol.py:1552 parity)."""
        from ..ndarray.ndarray import NDArray
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names, aux_names = self.list_arguments(), self.list_auxiliary_states()
        if arg_shapes is None or any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes or []) if s is None]
            raise ValueError(f"simple_bind: could not infer shapes for {missing}")
        args = {n: NDArray(jnp.zeros(s, jnp.float32))
                for n, s in zip(arg_names, arg_shapes)}
        auxs = {n: NDArray(jnp.zeros(s, jnp.float32))
                for n, s in zip(aux_names, aux_shapes)}
        grads = {n: NDArray(jnp.zeros(s, jnp.float32))
                 for n, s in zip(arg_names, arg_shapes)
                 if _req_of(grad_req, n, arg_names) != "null"}
        return self.bind(ctx, args, grads, grad_req, auxs)

    def eval(self, ctx=None, **kwargs):
        """One-shot evaluation with named NDArray inputs (symbol.py eval parity)."""
        from ..ndarray.ndarray import NDArray
        feed = {k: (v.data if isinstance(v, NDArray) else jnp.asarray(v))
                for k, v in kwargs.items()}
        outs = eval_graph(self._heads, feed)
        return [NDArray(o) for o in outs]

    # -- gradient ------------------------------------------------------------
    def gradient(self, wrt: Sequence[str]):
        raise NotImplementedError(
            "Symbol.gradient: bind an executor and call backward() — gradients "
            "come from jax.vjp, there is no separate grad graph to return")

    # -- serialization -------------------------------------------------------
    def tojson(self) -> str:
        nodes = _topo(self._heads)
        index = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            out_nodes.append({
                "op": n.op_key if n.op_key is not None else "null",
                "name": n.name,
                "attrs": {k: repr(v) for k, v in n.attrs.items()},
                "inputs": [[index[id(c)], i] for c, i in n.inputs],
                "param_names": list(n.input_params),
                "is_aux": n.is_aux,
                "num_outputs": n.num_outputs,
                "user_keys": sorted(n.user_keys),
            })
        payload = {
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.op_key is None],
            "heads": [[index[id(n)], i] for n, i in self._heads],
            "attrs": {"mxtpu_version": "1", "format": "mxtpu-symbol-json"},
        }
        return json.dumps(payload, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- operator overloads --------------------------------------------------
    def _scalar_op(self, op_name, scalar):
        return _apply_op(_reg.get_op(op_name), op_name, (self,),
                         {"scalar": float(scalar)})

    def _binary_op(self, op_name, other, rop_name=None):
        if isinstance(other, Symbol):
            return _apply_op(_reg.get_op(op_name), op_name, (self, other), {})
        raise TypeError(f"unsupported operand {type(other)}")

    def __add__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_plus_scalar", other)
        return self._binary_op("broadcast_add", other)

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_minus_scalar", other)
        return self._binary_op("broadcast_sub", other)

    def __rsub__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_rminus_scalar", other)
        return NotImplemented

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_mul_scalar", other)
        return self._binary_op("broadcast_mul", other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_div_scalar", other)
        return self._binary_op("broadcast_div", other)

    def __rtruediv__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_rdiv_scalar", other)
        return NotImplemented

    def __pow__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_power_scalar", other)
        return self._binary_op("broadcast_power", other)

    def __neg__(self):
        return self._scalar_op("_mul_scalar", -1.0)

    # comparisons produce 0/1 floats (reference parity; symbol.py __gt__ et al.
    # lower to _greater_scalar / broadcast_greater). __eq__/__ne__ build graph
    # nodes like NDArray's do, so identity hashing must be restored explicitly.
    def __eq__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_equal_scalar", other)
        return self._binary_op("broadcast_equal", other)

    def __ne__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_not_equal_scalar", other)
        return self._binary_op("broadcast_not_equal", other)

    def __hash__(self):
        return id(self)

    def __bool__(self):
        # reference symbol.py:107: since __eq__ builds a graph node, truthiness
        # of `a == b` would silently be True for any pair — raise instead
        from ..base import NotImplementedForSymbol
        raise NotImplementedForSymbol(self.__bool__, "bool")

    __nonzero__ = __bool__

    def __gt__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_greater_scalar", other)
        return self._binary_op("broadcast_greater", other)

    def __ge__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_greater_equal_scalar", other)
        return self._binary_op("broadcast_greater_equal", other)

    def __lt__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_lesser_scalar", other)
        return self._binary_op("broadcast_lesser", other)

    def __le__(self, other):
        if isinstance(other, (int, float)):
            return self._scalar_op("_lesser_equal_scalar", other)
        return self._binary_op("broadcast_lesser_equal", other)


def _req_of(grad_req, name, arg_names):
    if isinstance(grad_req, str):
        return grad_req
    if isinstance(grad_req, dict):
        return grad_req.get(name, "null")
    return dict(zip(arg_names, grad_req)).get(name, "null")


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------


def Variable(name: str, attr=None, shape=None, dtype=None, init=None,
             stype=None, **kwargs) -> Symbol:
    attrs = _with_scope_attrs(attr)
    user_keys = set(attrs)
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = dtype_name(dtype_np(dtype))
    node = _Node(None, name, attrs, user_keys=user_keys)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def _base_name(op_key: str) -> str:
    """Auto-name stem for an op key; namespaced keys drop the prefix so
    'contrib.Proposal' names nodes proposal0, not 'contrib.proposal0'."""
    return {"SoftmaxOutput": "softmax"}.get(
        op_key, op_key.rsplit(".", 1)[-1].lower().lstrip("_"))


def _apply_op(op, op_key: str, sym_args: Sequence[Symbol], attrs: dict,
              name: Optional[str] = None) -> Symbol:
    """Create an op node from positional Symbol inputs + attr kwargs.
    Operator-overload nodes inherit ambient AttrScope attrs like every other
    frontend-created symbol."""
    scope = _with_scope_attrs(None)
    user_keys = set(scope) - set(attrs)   # an op kwarg shadowing a scope name wins
    attrs = dict(scope, **attrs)
    name = name or _auto_name(_base_name(op_key))
    tparams = _tensor_params(op)
    inputs, input_params = [], []
    if tparams and tparams[0] == "*":
        for s in sym_args:
            inputs.append(s._heads[0])
            input_params.append("*")
    else:
        for pname, s in zip(tparams, sym_args):
            inputs.append(s._heads[0])
            input_params.append(pname)
    n_out = op.num_outputs if op.num_outputs > 0 else \
        int(attrs.get("num_outputs", 1))
    node = _Node(op_key, name, attrs, inputs, input_params, num_outputs=n_out,
                 user_keys=user_keys)
    if n_out == 1:
        return Symbol([(node, 0)])
    return Symbol([(node, i) for i in range(n_out)])


def make_op_wrapper(op_key: str):
    """Build the mx.sym.<Op> composition wrapper: Symbol inputs positionally or by
    parameter name; missing learnable inputs become auto-named Variables
    (reference: sym.Convolution auto-creates convN_weight/convN_bias)."""
    op = _reg.get_op(op_key)
    tparams = _tensor_params(op)

    def wrapper(*args, name: Optional[str] = None, attr=None, **kwargs):
        sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        attrs = {k: v for k, v in kwargs.items()
                 if not isinstance(v, Symbol) and v is not None}
        name = name or _auto_name(_base_name(op_key))
        inputs, input_params = [], []
        if tparams and tparams[0] == "*":
            seq = list(args) or [sym_kwargs[k] for k in sorted(sym_kwargs)]
            for s in seq:
                inputs.append(s._heads[0])
                input_params.append("*")
        else:
            supplied = dict(zip(tparams, args))
            supplied.update(sym_kwargs)
            for pname in tparams:
                if pname in supplied:
                    inputs.append(supplied[pname]._heads[0])
                    input_params.append(pname)
                    continue
                if pname == "bias" and (attrs.get("no_bias", False)):
                    continue
                if pname == "data":
                    raise ValueError(f"sym.{op_key}: 'data' input required")
                node = _Node(None, f"{name}_{pname}",
                             is_aux=pname in _AUX_PARAMS)
                inputs.append((node, 0))
                input_params.append(pname)
        n_out = op.num_outputs if op.num_outputs > 0 else \
            int(attrs.get("num_outputs", 1))
        scope = _with_scope_attrs(attr)
        node_attrs = dict(scope, **attrs)
        node = _Node(op_key, name, node_attrs, inputs,
                     input_params, num_outputs=n_out,
                     user_keys=set(scope) - set(attrs))
        if n_out == 1:
            return Symbol([(node, 0)])
        return Symbol([(node, i) for i in range(n_out)])

    wrapper.__name__ = op_key
    wrapper.__doc__ = op.doc
    return wrapper


# ---------------------------------------------------------------------------
# JSON load
# ---------------------------------------------------------------------------


def load_json(json_str: str) -> Symbol:
    """Parse a symbol JSON — native mxtpu schema, or the reference's nnvm
    graph schema (symbol.py:2549-2582 / nnvm SaveJSON: nodes with all-string
    attrs, explicit weight/bias null inputs, ``arg_nodes``/``heads``) so a
    ``*-symbol.json`` exported by the reference loads directly."""
    payload = json.loads(json_str)
    if payload.get("attrs", {}).get("format") != "mxtpu-symbol-json":
        if isinstance(payload.get("nodes"), list) and "arg_nodes" in payload:
            return _load_reference_json(payload)
        raise ValueError("not a recognizable symbol json (expected mxtpu or "
                         "reference nnvm graph schema)")
    nodes: List[_Node] = []
    for spec in payload["nodes"]:
        attrs = {k: _parse_attr(v) for k, v in spec.get("attrs", {}).items()}
        node = _Node(None if spec["op"] == "null" else spec["op"], spec["name"],
                     attrs, is_aux=spec.get("is_aux", False),
                     num_outputs=spec.get("num_outputs", 1),
                     user_keys=spec.get("user_keys", ()))
        node.inputs = [(nodes[i], j) for i, j in spec.get("inputs", [])]
        node.input_params = list(spec.get("param_names", []))
        nodes.append(node)
    heads = [(nodes[i], j) for i, j in payload["heads"]]
    return Symbol(heads)


fromjson = load_json

#: reference-graph attrs that are pure backend tuning noise on TPU (GPU
#: workspace sizing / cuDNN autotune knobs) — dropped on import
_REF_NOISE_ATTRS = {"workspace", "cudnn_tune", "cudnn_off"}

#: reference op names whose registry key differs here
_REF_OP_ALIASES = {
    "_copy": "identity",
    "_plus": "elemwise_add",
    "_minus": "elemwise_sub",
    "_mul": "elemwise_mul",
    "_div": "elemwise_div",
}


def _load_reference_json(payload: dict) -> Symbol:
    """Replay a reference nnvm graph through the op wrappers: null nodes
    become Variables, op nodes are re-composed positionally over each op's
    tensor-parameter order (all inputs are explicit in the reference schema,
    so the wrappers never auto-create params). Version-tolerant: accepts
    ``attrs``/``attr``/``param`` attr keys and 2- or 3-int input refs."""
    node_syms: List[Symbol] = []
    for spec in payload["nodes"]:
        opname = spec["op"]
        raw = spec.get("attrs") or spec.get("attr") or spec.get("param") or {}
        if opname == "null":
            node_syms.append(Variable(spec["name"]))
            continue
        opname = _REF_OP_ALIASES.get(opname, opname)
        try:
            op = _reg.get_op(opname)
        except KeyError:
            raise ValueError(
                f"reference graph op {spec['op']!r} has no counterpart in the "
                f"registry (node {spec['name']!r})") from None
        # attr policy: __dunder__ scope attrs and KNOWN backend noise are
        # dropped; anything else the kernel's signature doesn't name RAISES —
        # silently defaulting a meaningful attr would build a different
        # network than the artifact describes
        sig = inspect.signature(op.fn).parameters
        has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD
                         for p in sig.values())
        attrs = {}
        for k, v in raw.items():
            if k.startswith("__") or k in _REF_NOISE_ATTRS:
                continue
            if not has_var_kw and k not in sig:
                raise ValueError(
                    f"reference graph attr {k}={v!r} on op {opname!r} (node "
                    f"{spec['name']!r}) has no counterpart in the kernel "
                    f"signature — refusing to silently drop it")
            attrs[k] = _parse_attr(str(v))
        ins = []
        for ref in spec.get("inputs", []):
            src, idx = ref[0], (ref[1] if len(ref) > 1 else 0)
            s = node_syms[src]
            ins.append(s if idx == 0 and len(s._heads) == 1
                       else Symbol([s._heads[idx]]))
        node_syms.append(
            make_op_wrapper(opname)(*ins, name=spec["name"], **attrs))
    heads = payload.get("heads") or [[len(payload["nodes"]) - 1, 0]]
    return Symbol([node_syms[h[0]]._heads[h[1] if len(h) > 1 else 0]
                   for h in heads])


def _parse_attr(v: str):
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())
