"""Executor — Symbol binding (mx.executor parity).

Capability parity with ``include/mxnet/executor.h`` Forward/Backward/Bind/SimpleBind
and ``python/mxnet/executor.py``. The reference's GraphExecutor machinery (Gradient
pass, PlaceDevice, PlanMemory, op-executor attach, engine push — graph_executor.cc)
collapses: forward is one topological evaluation of registry ops (XLA compiles and
fuses per op; the hybridized path in jit.py is the whole-graph compile), backward is
ONE ``jax.vjp`` over the same evaluation — loss-fused heads (SoftmaxOutput) keep the
reference's custom backward via their ``jax.custom_vjp`` rules.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..ndarray.ndarray import NDArray
from ..step_cache import cache_stats
from .symbol import Symbol, eval_graph, _req_of

__all__ = ["Executor"]


def _split_resolved(resolved: dict):
    """Partition per-node resolved attrs into static values (flags, floats)
    and array leaves (RNG keys): arrays become traced inputs of the memoized
    backward so a fresh forward's keys replay without retracing."""
    static: Dict[int, dict] = {}
    arr_spec: List[tuple] = []
    arr_vals: List = []
    for nid, attrs in resolved.items():
        stat = {}
        for k, v in attrs.items():
            if isinstance(v, (jax.Array, np.ndarray)):
                arr_spec.append((nid, k))
                arr_vals.append(v)
            else:
                stat[k] = v
        static[nid] = stat
    return static, arr_spec, arr_vals


class Executor:
    def __init__(self, symbol: Symbol, ctx, arg_dict: Dict[str, NDArray],
                 aux_dict: Dict[str, NDArray], grad_dict: Dict[str, NDArray],
                 grad_req="write"):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_dict = {k: v if isinstance(v, NDArray) else NDArray(v)
                         for k, v in arg_dict.items()}
        self.aux_dict = {k: v if isinstance(v, NDArray) else NDArray(v)
                         for k, v in aux_dict.items()}
        self.grad_dict = {k: v if isinstance(v, NDArray) else NDArray(v)
                          for k, v in grad_dict.items()}
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._grad_req = {n: _req_of(grad_req, n, self._arg_names)
                          for n in self._arg_names}
        self.outputs: List[NDArray] = []
        self._is_train = False
        self._resolved: Optional[dict] = None
        # memoized backward programs per (live/fixed/resolved signature):
        # repeated forward/backward on fixed shapes traces jax.vjp ONCE
        self._bwd_cache: Dict[tuple, "jax.stages.Wrapped"] = {}
        self._bwd_stats = cache_stats("symbol_backward")

    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def output_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def forward(self, is_train: bool = False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                self.arg_dict[k] = v if isinstance(v, NDArray) else NDArray(v)
            else:
                self.arg_dict[k]._set_data(
                    v.data if isinstance(v, NDArray) else jnp.asarray(v))
        self._is_train = is_train
        self._resolved = {}  # fresh RNG/flag resolution per step; backward replays it
        feed = {n: a.data for n, a in self.arg_dict.items()}
        feed.update({n: a.data for n, a in self.aux_dict.items()})
        aux_updates: dict = {}
        scope = autograd.train_mode() if is_train else autograd.predict_mode()
        with scope, autograd.pause(train_mode=is_train):
            outs = eval_graph(self._symbol._heads, feed, is_train,
                              aux_updates=aux_updates, resolved=self._resolved)
        for name, new in aux_updates.items():
            self.aux_dict[name]._set_data(new)
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        """One jax.vjp over the whole bound graph, accumulated per grad_req.

        The vjp is wrapped in ``jax.jit`` and memoized per (live-arg
        signature, fixed-arg signature, is_train, resolved-attr structure,
        cotangent signature): repeated forward/backward on fixed shapes
        traces ONCE instead of re-running the whole-graph trace every call.
        Per-forward RNG keys (dropout masks) enter as traced inputs, so the
        compiled backward still replays each forward's exact program.
        """
        live = [n for n in self._arg_names if self._grad_req[n] != "null"]
        if not live:
            return
        if self._resolved is None:
            raise RuntimeError("backward before forward")
        fixed_names = [n for n in self._arg_names if n not in live] \
            + list(self.aux_dict.keys())
        fixed_vals = [self.arg_dict[n].data for n in self._arg_names
                      if n not in live] \
            + [a.data for a in self.aux_dict.values()]
        live_vals = [self.arg_dict[n].data for n in live]
        res_static, arr_spec, arr_vals = _split_resolved(self._resolved)
        if out_grads is None:
            cot_vals = None
        else:
            og = out_grads if isinstance(out_grads, (list, tuple)) \
                else [out_grads]
            cot_vals = [jnp.asarray(g.data if isinstance(g, NDArray) else g)
                        for g in og]

        def asig(v):
            return (tuple(v.shape), str(v.dtype))

        sig = (tuple(live), tuple(asig(v) for v in live_vals),
               tuple(fixed_names), tuple(asig(v) for v in fixed_vals),
               self._is_train,
               tuple((nid, k) + asig(v)
                     for (nid, k), v in zip(arr_spec, arr_vals)),
               tuple((nid, tuple(sorted((k, repr(v)) for k, v in st.items())))
                     for nid, st in sorted(res_static.items())),
               None if cot_vals is None
               else tuple(asig(v) for v in cot_vals))
        fn = self._bwd_cache.get(sig)
        if fn is None:
            self._bwd_stats.miss()
            heads, is_train = self._symbol._heads, self._is_train
            spec = list(arr_spec)
            static = {nid: dict(st) for nid, st in res_static.items()}
            f_names, live_names = list(fixed_names), list(live)
            default_cots = cot_vals is None

            def bwd(lvals, fvals, avals, cvals):
                resolved = {nid: dict(st) for nid, st in static.items()}
                for (nid, k), v in zip(spec, avals):
                    resolved[nid][k] = v
                fixed = dict(zip(f_names, fvals))

                def pure(vals):
                    feed = dict(fixed)
                    feed.update(zip(live_names, vals))
                    return tuple(eval_graph(heads, feed, is_train,
                                            resolved=resolved))

                outs, vjp_fn = jax.vjp(pure, list(lvals))
                if default_cots:
                    cots = tuple(jnp.ones_like(o) for o in outs)
                else:
                    cots = tuple(jnp.asarray(c, dtype=o.dtype)
                                 for c, o in zip(cvals, outs))
                (grads,) = vjp_fn(cots)
                return grads

            fn = self._bwd_cache[sig] = jax.jit(bwd)
        else:
            self._bwd_stats.hit()

        with autograd.pause(train_mode=self._is_train):
            grads = fn(live_vals, fixed_vals, arr_vals, cot_vals)
        for name, g in zip(live, grads):
            req = self._grad_req[name]
            tgt = self.grad_dict.get(name)
            if tgt is None:
                tgt = self.grad_dict[name] = NDArray(jnp.zeros_like(g))
            if req == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g.astype(tgt.dtype))

    def copy_params_from(self, arg_params: Dict, aux_params: Optional[Dict] = None,
                         allow_extra_params: bool = False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v.data if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise ValueError(f"unknown argument {k!r}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_data(
                    v.data if isinstance(v, NDArray) else jnp.asarray(v))
            elif not allow_extra_params:
                raise ValueError(f"unknown aux state {k!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes (executor.py reshape parity): shape
        inference reruns; param arrays are kept."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = dict(self.arg_dict)
        for n, s in zip(self._arg_names, arg_shapes):
            if s is not None and n in kwargs:
                new_args[n] = NDArray(jnp.zeros(s, jnp.float32))
        return Executor(self._symbol, self._ctx, new_args, dict(self.aux_dict),
                        dict(self.grad_dict), self._grad_req)
