"""Neural-network ops — parity with ``src/operator/nn/`` (SURVEY.md §2.2).

Design notes vs the reference:

* Convolution/Pooling lower to ``lax.conv_general_dilated`` / ``lax.reduce_window`` —
  XLA tiles these onto the MXU/VPU directly; there is no im2col, no cuDNN algo
  selection, no autotune cache (that whole subsystem disappears, SURVEY.md §2.7).
* Layout is NCHW by default for API parity with the reference. XLA's layout assignment
  re-tiles internally, so NCHW at the API boundary costs nothing after compilation.
* Loss-fused heads (``SoftmaxOutput``, ``make_loss``) carry the reference's *custom
  backward* semantics via ``jax.custom_vjp`` — their gradient is NOT the vjp of their
  forward (softmax output's grad is ``p - onehot(label)``, src/operator/softmax_output-inl.h).
* Stochastic ops (Dropout) draw keys from ``mxtpu.rng`` (trace-aware, see rng.py).
"""

from __future__ import annotations

import math

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import rng
from .registry import register, alias

# ---------------------------------------------------------------------------
# dense / conv / pooling
# ---------------------------------------------------------------------------

# Low-precision execution hooks (mxtpu.quant.train.quant_scope): when set,
# these replace the fp32 matmul/conv contraction — bias add, flattening and
# layout handling stay here so the quant layer only sees the contraction.
_QUANT_DENSE = None   # (x, weight) -> x @ weight.T in the active quant mode
_QUANT_CONV = None    # (data, weight, **conv_kw) -> conv in the active mode


@register("FullyConnected", aliases=("fully_connected",))
def _fully_connected(data, weight, bias=None, num_hidden: int = 0,
                     no_bias: bool = False, flatten: bool = True):
    """src/operator/nn/fully_connected.cc:231: y = x·Wᵀ + b (weight stored [out,in])."""
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    y = _QUANT_DENSE(x, weight) if _QUANT_DENSE is not None \
        else jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        y = y + bias
    return y


_CONV_LAYOUTS = {
    1: ("NCW", "OIW", "NCW"),
    2: ("NCHW", "OIHW", "NCHW"),
    3: ("NCDHW", "OIDHW", "NCDHW"),
}


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    t = tuple(int(x) for x in v)
    return t if t else (1,) * n


@register("Convolution", aliases=("convolution",))
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                 num_filter: int = 0, num_group: int = 1, no_bias: bool = False,
                 layout: Optional[str] = None):
    """src/operator/nn/convolution.cc — N-D conv with groups/dilation/stride/pad.

    Direct ``lax.conv_general_dilated``; grouped conv via ``feature_group_count``
    (depthwise = num_group == in_channels), which XLA maps to MXU batch tiles without
    the reference's separate depthwise kernel (depthwise_convolution-inl.h).
    """
    n = len(kernel) if kernel else data.ndim - 2
    stride, dilate = _tup(stride, n), _tup(dilate, n)
    pad = _tup(pad, n) if pad else (0,) * n
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_LAYOUTS[n])
    conv_kw = dict(window_strides=stride, padding=[(p, p) for p in pad],
                   rhs_dilation=dilate, dimension_numbers=dn,
                   feature_group_count=num_group)
    out = _QUANT_CONV(data, weight, **conv_kw) if _QUANT_CONV is not None \
        else lax.conv_general_dilated(data, weight, **conv_kw)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@register("Deconvolution", aliases=("deconvolution",))
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                   adj=(), target_shape=(), num_filter: int = 0, num_group: int = 1,
                   no_bias: bool = True, layout: Optional[str] = None):
    """src/operator/nn/deconvolution.cc — transposed conv (gradient of Convolution).

    Implemented as ``lax.conv_transpose``-equivalent via input dilation so the same MXU
    path serves forward and transposed convs. Weight layout matches the reference:
    [in, out/group, *kernel].
    """
    n = len(kernel) if kernel else data.ndim - 2
    stride, dilate = _tup(stride, n), _tup(dilate, n)
    pad = _tup(pad, n) if pad else (0,) * n
    adj = _tup(adj, n) if adj else (0,) * n
    k = tuple(weight.shape[2:])
    # conv_transpose padding: for each dim, (k-1)*d - p on both sides, + adj on high side
    pads = [((k[i] - 1) * dilate[i] - pad[i], (k[i] - 1) * dilate[i] - pad[i] + adj[i])
            for i in range(n)]
    # weight [in, out/g, *k] → flip spatial, swap to [out, in/g, *k] per group
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    if num_group > 1:
        ci, cog = w.shape[0], w.shape[1]
        w = w.reshape((num_group, ci // num_group, cog) + k)
        w = jnp.swapaxes(w, 1, 2).reshape((num_group * cog, ci // num_group) + k)
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _CONV_LAYOUTS[n])
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * n, padding=pads, lhs_dilation=stride,
        dimension_numbers=dn, feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@register("Pooling", aliases=("pooling",))
def _pooling(data, kernel=(), pool_type: str = "max", global_pool: bool = False,
             stride=(), pad=(), pooling_convention: str = "valid",
             p_value: int = 2, count_include_pad: bool = True):
    """src/operator/nn/pooling.cc — max/avg/sum/lp pooling via lax.reduce_window."""
    n = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type in ("avg", "sum"):
            red = jnp.sum(data, axis=axes, keepdims=True)
            return red / jnp.prod(jnp.asarray(data.shape[2:])) if pool_type == "avg" else red
        if pool_type == "lp":
            return jnp.power(jnp.sum(jnp.power(jnp.abs(data), p_value), axis=axes,
                                     keepdims=True), 1.0 / p_value)
    kernel = _tup(kernel, n)
    stride = _tup(stride, n)
    pad = _tup(pad, n) if pad else (0,) * n
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad high edge enough that the last window fits
        extra = []
        for i in range(n):
            size = data.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if size >= kernel[i] else 0)
        pads = [(0, 0), (0, 0)] + [(pad[i], pad[i] + extra[i]) for i in range(n)]
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            # static python product: a jnp op here would stage a tracer
            # under an outer jit, breaking float()
            return s / float(math.prod(kernel))
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        s = lax.reduce_window(jnp.power(jnp.abs(data), p_value), 0.0, lax.add,
                              window, strides, pads)
        return jnp.power(s, 1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type!r}")


@register("UpSampling", aliases=("upsampling",))
def _upsampling(data, scale: int = 1, sample_type: str = "nearest", num_args: int = 1):
    """src/operator/upsampling.cc nearest-neighbour path (bilinear via contrib resize)."""
    n, c, h, w = data.shape
    out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    return out


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@register("BatchNorm", aliases=("batch_norm",))
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps: float = 1e-3,
                momentum: float = 0.9, fix_gamma: bool = True,
                use_global_stats: bool = False, axis: int = 1,
                cudnn_off: bool = False):
    """Inference-mode BatchNorm using running stats (src/operator/nn/batch_norm.cc).

    Training mode (batch stats + moving-stat update) is ``batch_norm_train`` — the
    functional split keeps this op pure; the Gluon layer owns the aux-state update,
    where the reference mutates aux arrays inside the op.
    """
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    mm, mv = moving_mean.reshape(shape), moving_var.reshape(shape)
    return (data - mm) * lax.rsqrt(mv + eps) * g.reshape(shape) + beta.reshape(shape)


@register("batch_norm_train", num_outputs=3)
def _batch_norm_train(data, gamma, beta, eps: float = 1e-3, fix_gamma: bool = True,
                      axis: int = 1):
    """Training-mode BN: returns (out, batch_mean, batch_var) for moving-stat update."""
    axes = tuple(i for i in range(data.ndim) if i != axis)
    mean = jnp.mean(data, axis=axes)
    var = jnp.var(data, axis=axes)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = (data - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + eps)
    out = out * g.reshape(shape) + beta.reshape(shape)
    return out, mean, var


@register("LayerNorm", aliases=("layer_norm",))
def _layer_norm(data, gamma, beta, axis: int = -1, eps: float = 1e-5):
    """src/operator/nn/layer_norm.cc — normalize over one axis, affine per that axis."""
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("InstanceNorm", aliases=("instance_norm",))
def _instance_norm(data, gamma, beta, eps: float = 1e-3):
    """src/operator/instance_norm-inl.h — per-(sample,channel) normalization (NC+)."""
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) + beta.reshape(shape)


@register("LRN", aliases=("lrn",))
def _lrn(data, nsize: int = 5, alpha: float = 1e-4, beta: float = 0.75, knorm: float = 2.0):
    """src/operator/nn/lrn.cc — local response norm across channels (NCHW)."""
    sq = jnp.square(data)
    half = nsize // 2
    pad = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    windows = sum(pad[:, i:i + data.shape[1]] for i in range(nsize))
    return data / jnp.power(knorm + alpha * windows / nsize, beta)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": lambda x: x / (1 + jnp.abs(x)),
}


@register("Activation", aliases=("activation",))
def _activation(data, act_type: str = "relu"):
    return _ACTS[act_type](data)


@register("LeakyReLU", aliases=("leaky_relu",))
def _leaky_relu(data, gamma=None, act_type: str = "leaky", slope: float = 0.25,
                lower_bound: float = 0.125, upper_bound: float = 0.334):
    """src/operator/leaky_relu.cc family: leaky/prelu/elu/selu/gelu/rrelu."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(data > 0, data, a * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        # eval-mode rrelu = mean-slope leaky (training draws uniform slope)
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, mid * data)
    raise ValueError(f"unknown LeakyReLU act_type {act_type!r}")


@register("softmax")
def _softmax(data, axis: int = -1, temperature: Optional[float] = None,
             length=None, use_length: bool = False):
    x = data / temperature if temperature else data
    if use_length and length is not None:
        mask = jnp.arange(data.shape[axis]) < length[..., None]
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def _log_softmax(data, axis: int = -1, temperature: Optional[float] = None):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def _softmin(data, axis: int = -1):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation", aliases=("softmax_activation",))
def _softmax_activation(data, mode: str = "instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


def _dropout_resolve(kwargs):
    """Resolve training flag + RNG key at invoke time so the tape closure replays
    bit-identically under jax.vjp (forward and backward masks must match)."""
    from .. import autograd
    if kwargs.get("_training") is None:
        kwargs["_training"] = autograd.is_training()
    active = kwargs.get("p", 0.5) > 0 and (
        kwargs["_training"] or kwargs.get("mode", "training") == "always")
    if kwargs.get("key") is None and active:
        kwargs["key"] = rng.next_key()
    return kwargs


@register("Dropout", aliases=("dropout",), resolve_kwargs=_dropout_resolve)
def _dropout(data, p: float = 0.5, mode: str = "training", axes=(), key=None,
             _training: Optional[bool] = None):
    """src/operator/nn/dropout.cc — inverted dropout; ``axes`` gives broadcast noise.

    Key sourcing is trace-aware (mxtpu.rng): imperative calls split the global key,
    hybridized traces receive fresh keys per step. ``mode='always'`` applies dropout in
    inference too.
    """
    from .. import autograd
    training = _training if _training is not None else autograd.is_training()
    if p <= 0 or (not training and mode != "always"):
        return data
    if key is None:
        key = rng.next_key()
    shape = list(data.shape)
    for a in axes or ():
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


# ---------------------------------------------------------------------------
# loss-fused heads (custom backward semantics via jax.custom_vjp)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                         multi_output, normalization):
    return jax.nn.softmax(data, axis=-1 if not multi_output else 1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        multi_output, normalization):
    out = _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                               multi_output, normalization)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, multi_output,
                        normalization, res, g):
    out, label = res
    axis = 1 if multi_output else -1
    onehot = jax.nn.one_hot(label.astype(jnp.int32), out.shape[axis],
                            axis=axis, dtype=out.dtype)
    grad = out - onehot
    if use_ignore:
        keep = (label != ignore_label).astype(out.dtype)
        grad = grad * jnp.expand_dims(keep, axis)
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and use_ignore:
        valid = jnp.maximum(jnp.sum(label != ignore_label), 1).astype(out.dtype)
        grad = grad / valid
    grad = grad * scale
    return grad, jnp.zeros_like(label)


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", aliases=("softmax_output", "Softmax"))
def _softmax_output(data, label, grad_scale: float = 1.0, ignore_label: float = -1.0,
                    use_ignore: bool = False, multi_output: bool = False,
                    normalization: str = "null", **_ignored):
    """src/operator/softmax_output-inl.h — forward=softmax, backward=p−onehot(label).

    The defining legacy loss-head: its gradient ignores the incoming cotangent shape
    and injects the cross-entropy gradient directly, which custom_vjp reproduces.
    """
    return _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                                multi_output, normalization)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _make_loss_core(data, grad_scale):
    return data


def _make_loss_fwd(data, grad_scale):
    return data, None


def _make_loss_bwd(grad_scale, res, g):
    return (jnp.full_like(g, grad_scale),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("make_loss", aliases=("MakeLoss",))
def _make_loss(data, grad_scale: float = 1.0, valid_thresh: float = 0.0,
               normalization: str = "null"):
    """src/operator/make_loss-inl.h — identity forward, grad_scale gradient injected
    by the custom vjp (the incoming cotangent is ignored, matching the reference)."""
    return _make_loss_core(data, grad_scale)


@register("LinearRegressionOutput", aliases=("linear_regression_output",))
def _linreg_output(data, label, grad_scale: float = 1.0):
    """src/operator/regression_output-inl.h — forward=identity, backward=(pred−label)/n."""
    return _regression_core(data, label, grad_scale, "linear")


@register("MAERegressionOutput", aliases=("mae_regression_output",))
def _maereg_output(data, label, grad_scale: float = 1.0):
    return _regression_core(data, label, grad_scale, "mae")


@register("LogisticRegressionOutput", aliases=("logistic_regression_output",))
def _logreg_output(data, label, grad_scale: float = 1.0):
    return _regression_core(data, label, grad_scale, "logistic")


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _regression_core(data, label, grad_scale, kind):
    if kind == "logistic":
        return jax.nn.sigmoid(data)
    return data


def _regression_fwd(data, label, grad_scale, kind):
    out = _regression_core(data, label, grad_scale, kind)
    return out, (out, label)


def _regression_bwd(grad_scale, kind, res, g):
    out, label = res
    # reference normalizes by per-sample output size: num_output = Size()/shape[0]
    # (src/operator/regression_output-inl.h)
    n = int(out.size // out.shape[0]) if out.ndim > 1 else 1
    if kind == "mae":
        grad = jnp.sign(out - label)
    else:  # linear & logistic share (pred - label)
        grad = out - label
    return grad * grad_scale / n, jnp.zeros_like(label)


_regression_core.defvjp(_regression_fwd, _regression_bwd)


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    """src/operator/loss_binary_op.cc — scalar summed CE with integer labels."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


# ---------------------------------------------------------------------------
# transformer helpers (contrib parity: src/operator/contrib/transformer.cc)
# ---------------------------------------------------------------------------


@register("div_sqrt_dim", namespace="contrib")
def _div_sqrt_dim(data):
    """contrib._contrib_div_sqrt_dim (transformer.cc:33): x / sqrt(d_last)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], dtype=data.dtype))


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg (src/operator/identity_attach_KL_sparse_reg.cc)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _kl_sparse_reg(data, sparseness_target, penalty):
    return data


def _kl_sparse_reg_fwd(data, sparseness_target, penalty):
    return data, data


def _kl_sparse_reg_bwd(sparseness_target, penalty, data, dy):
    # reference backward (identity_attach_KL_sparse_reg-inl.h:91): the KL
    # penalty gradient vs the mean activation rho_hat is ADDED to the incoming
    # gradient. The reference keeps rho_hat as a momentum-smoothed aux buffer;
    # stateless here, rho_hat is the current batch mean (declared deviation —
    # the momentum kwarg is accepted and ignored at the op layer).
    rho_hat = jnp.mean(data, axis=0, keepdims=True)
    reg = penalty * (-sparseness_target / rho_hat
                     + (1.0 - sparseness_target) / (1.0 - rho_hat))
    return (dy + jnp.broadcast_to(reg, dy.shape),)


_kl_sparse_reg.defvjp(_kl_sparse_reg_fwd, _kl_sparse_reg_bwd)


@register("IdentityAttachKLSparseReg",
          aliases=("identity_attach_kl_sparse_reg",))
def _identity_attach_kl_sparse_reg(data, sparseness_target: float = 0.1,
                                   penalty: float = 0.001,
                                   momentum: float = 0.9):
    """Identity forward; backward attaches the KL sparseness penalty gradient
    for sigmoid activations (src/operator/identity_attach_KL_sparse_reg.cc;
    Hinton's guideTR P11). Pair only with sigmoid outputs (rho in (0,1))."""
    return _kl_sparse_reg(data, float(sparseness_target), float(penalty))


# ---------------------------------------------------------------------------
# SVMOutput (src/operator/svm_output.cc — hinge-loss head)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_output_core(data, label, margin, reg_coef, use_linear):
    return data


def _svm_output_fwd(data, label, margin, reg_coef, use_linear):
    return data, (data, label)


def _svm_output_bwd(margin, reg_coef, use_linear, res, g):
    # svm_output.cc L1_SVM :31 / L2_SVM :50 — the injected hinge gradient
    # (incoming cotangent ignored, like every legacy loss head)
    out, label = res
    k = jax.nn.one_hot(label.astype(jnp.int32), out.shape[-1],
                       dtype=out.dtype)
    if use_linear:   # L1-SVM: ±reg_coef where the margin is violated
        grad_k = -(margin > out).astype(out.dtype) * reg_coef
        grad_o = (margin > -out).astype(out.dtype) * reg_coef
    else:            # L2-SVM: linear in the violation
        grad_k = -jnp.where(margin > out, 2.0 * (margin - out), 0.0) * reg_coef
        grad_o = jnp.where(margin > -out, 2.0 * (margin + out), 0.0) * reg_coef
    grad = k * grad_k + (1.0 - k) * grad_o
    return grad, jnp.zeros_like(label)


_svm_output_core.defvjp(_svm_output_fwd, _svm_output_bwd)


@register("SVMOutput", aliases=("svm_output",))
def _svm_output(data, label, margin: float = 1.0,
                regularization_coefficient: float = 1.0,
                use_linear: bool = False):
    """Hinge-loss head (svm_output-inl.h): forward identity, backward the
    L1/L2-SVM margin gradient per class."""
    return _svm_output_core(data, label, float(margin),
                            float(regularization_coefficient),
                            bool(use_linear))


# v1-legacy / cuDNN op-name aliases (reference registers *_v1 and
# CuDNNBatchNorm as distinct legacy entry points over the same math)
alias("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm")
alias("Convolution", "Convolution_v1")
alias("Pooling", "Pooling_v1")


@register("SyncBatchNorm", namespace="contrib",
          aliases=("_contrib_SyncBatchNorm",))
def _sync_batch_norm_op(data, gamma, beta, moving_mean, moving_var,
                        eps: float = 1e-3, momentum: float = 0.9,
                        fix_gamma: bool = True, use_global_stats: bool = False,
                        ndev: int = 1, key: str = "", axis: int = 1,
                        cudnn_off: bool = False):
    """contrib SyncBatchNorm op name (src/operator/contrib/sync_batch_norm.cc).

    Inference form = plain BatchNorm over running stats; the cross-device
    TRAINING sync lives in ``gluon.contrib.nn.SyncBatchNorm`` (under a
    dp-sharded input XLA computes global-batch statistics, which IS the sync
    semantic — pmean only matters inside explicit shard_map regions). ndev/
    key are the reference's comm-handshake knobs — accepted, nothing to
    coordinate here."""
    return _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                       momentum=momentum, fix_gamma=fix_gamma,
                       use_global_stats=use_global_stats, axis=axis)
