"""Reduction ops — parity with ``src/operator/tensor/broadcast_reduce_op_*`` families.

The reference's reduce kernels (broadcast_reduce-inl.h) take ``axis``/``keepdims``/
``exclude`` attrs; ``exclude=True`` reduces over all axes NOT listed — preserved here.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register, alias


def _norm_axis(axis, ndim, exclude):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _make_reduce(jfn, name, aliases=(), differentiable=True, int_out=False):
    def _fn(data, axis=None, keepdims: bool = False, exclude: bool = False):
        ax = _norm_axis(axis, jnp.ndim(data), exclude)
        return jfn(data, axis=ax, keepdims=keepdims)

    _fn.__name__ = name
    _fn.__doc__ = f"Reduce-{name} over ``axis`` (exclude inverts the axis set)."
    register(name, aliases=aliases, differentiable=differentiable)(_fn)
    return _fn


_make_reduce(jnp.sum, "sum", aliases=("sum_axis",))
_make_reduce(jnp.mean, "mean")
_make_reduce(jnp.prod, "prod")
_make_reduce(jnp.nansum, "nansum")
_make_reduce(jnp.nanprod, "nanprod")
_make_reduce(jnp.max, "max", aliases=("max_axis",))
_make_reduce(jnp.min, "min", aliases=("min_axis",))
_make_reduce(jnp.all, "all", differentiable=False)
_make_reduce(jnp.any, "any", differentiable=False)


@register("argmax", differentiable=False)
def _argmax(data, axis=None, keepdims: bool = False):
    out = jnp.argmax(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)  # reference returns float indices (argmax.cc)


@register("argmin", differentiable=False)
def _argmin(data, axis=None, keepdims: bool = False):
    out = jnp.argmin(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def _argmax_channel(data):
    """argmax over axis 1 (the reference's SoftmaxOutput companion)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register("norm")
def _norm(data, ord: int = 2, axis=None, keepdims: bool = False):
    """L1/L2 norm reduction (reference norm op, tensor/broadcast_reduce_op_value.cc)."""
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))


@register("L2Normalization", aliases=("l2_normalization",))
def _l2_normalization(data, eps: float = 1e-10, mode: str = "instance"):
    """Reference src/operator/l2_normalization-inl.h: normalize by L2 norm.

    mode: 'instance' (per sample over all dims), 'channel' (axis 1), 'spatial'
    (per-channel over trailing spatial dims).
    """
    if mode == "instance":
        axes = tuple(range(1, jnp.ndim(data)))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, jnp.ndim(data)))
    else:
        raise ValueError(f"unknown L2Normalization mode {mode!r}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@register("histogram", num_outputs=2, differentiable=False)
def _histogram(data, bins=None, bin_cnt: int = 10, range=None):
    """src/operator/tensor/histogram.cc: counts + bin edges. ``bins`` may be
    an explicit edges array (then bin_cnt/range are ignored)."""
    flat = data.reshape(-1)
    if bins is not None and not isinstance(bins, int):
        edges = jnp.asarray(bins)
        counts, _ = jnp.histogram(flat, bins=edges)
        return counts.astype(jnp.int32), edges  # x64-disabled dtype floor
    n = bins if isinstance(bins, int) else bin_cnt
    if range is not None:
        lo, hi = range
    elif flat.size == 0:
        lo, hi = 0.0, 1.0          # numpy's empty-input default window
    else:
        lo, hi = jnp.min(flat), jnp.max(flat)
    counts, edges = jnp.histogram(flat, bins=n, range=(lo, hi))
    return counts.astype(jnp.int32), edges
