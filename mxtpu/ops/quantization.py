"""INT8 quantization ops — capability parity with ``src/operator/quantization/``
(quantize.cc, dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc; driven from python/mxnet/contrib/quantization.py).

TPU-native design: int8 matmuls/convs issue ``lax.dot_general`` /
``lax.conv_general_dilated`` with int8 operands and
``preferred_element_type=int32`` — XLA lowers these onto the MXU's int8 path
(2x the bf16 peak on v5e: 394 vs 197 TOPS). The reference's separate
quantize→quantized_op→requantize node chains collapse: the compiled path keeps
activations float at layer boundaries (fake-quant on the way in), which is the
same numerics with fewer HBM round-trips, letting XLA fuse the rescale into the
int32 accumulator readout.

Range convention matches the reference (quantization_utils.h): a float range
[min, max] maps onto the signed int range symmetrically via
``scale = q_max / max(|min|, |max|)``; uint8 (non-negative activations, the
reference's post-ReLU dtype) maps [0, max] onto [0, 255] with
``scale = 255 / max``. uint8 activations ride the SAME MXU int8 path via the
standard zero-point-128 shift: u8·w = (u8-128)·w + 128·Σw, both terms int8/int32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NS = "contrib"

_QMAX = {"int8": 127.0, "uint8": 255.0}


def _scale_of(min_range, max_range, out_type="int8"):
    if out_type == "uint8":
        # unsigned range [0, max] -> [0, 255] (quantization_utils.h
        # FloatToQuantized<uint8_t>: post-ReLU activations are non-negative)
        return 255.0 / jnp.maximum(max_range, 1e-30)
    if out_type not in _QMAX:
        raise ValueError(
            f"unknown quantized out_type {out_type!r}: expected one of "
            f"{sorted(_QMAX)} or 'uint8'")
    absmax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return _QMAX[out_type] / jnp.maximum(absmax, 1e-30)


@register("quantize", namespace=NS, num_outputs=3, differentiable=False)
def _quantize(data, min_range, max_range, out_type: str = "int8"):
    """quantize.cc parity: float -> int8/uint8 given a calibrated range.

    Returns (quantized, out_min, out_max) like the reference (3 outputs so the
    range travels with the tensor through a quantized graph). int8 is
    symmetric over ±max(|min|,|max|); uint8 maps [0, max] affinely (values
    below 0 clamp — the reference reserves uint8 for non-negative tensors)."""
    scale = _scale_of(min_range, max_range, out_type)
    if out_type == "uint8":
        q = jnp.clip(jnp.round(data * scale), 0.0, 255.0)
        return q.astype(jnp.uint8), jnp.zeros_like(scale), 255.0 / scale
    q = jnp.clip(jnp.round(data * scale), -_QMAX[out_type], _QMAX[out_type])
    absmax = _QMAX[out_type] / scale
    return q.astype(jnp.int8), -absmax, absmax


@register("dequantize", namespace=NS, differentiable=False)
def _dequantize(data, min_range, max_range, out_type: str = "float32"):
    """dequantize.cc parity: int8/uint8 -> float given the tensor's range."""
    if data.dtype == jnp.uint8:
        return data.astype(out_type) * (jnp.maximum(max_range, 1e-30) / 255.0)
    absmax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(out_type) * (absmax / _QMAX["int8"])


@register("requantize", namespace=NS, num_outputs=3, differentiable=False)
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """requantize.cc parity: int32 accumulator -> int8 with a (calibrated or
    on-the-fly) output range."""
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / 2147483647.0)
    if min_calib_range is None:
        max_calib_range = jnp.max(jnp.abs(real))
        min_calib_range = -max_calib_range
    scale = _scale_of(min_calib_range, max_calib_range, "int8")
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    return q, min_calib_range, max_calib_range


def _quantize_act(x, x_scale, unsigned: bool):
    """Quantize a float activation at the layer boundary. Signed: int8 in
    [-127, 127]. Unsigned: uint8 in [0, 255], returned zero-point-shifted to
    int8 (q - 128) so the MXU's int8 path applies; the caller adds the
    128·Σw correction to the accumulator."""
    if unsigned:
        q = jnp.clip(jnp.round(x * x_scale), 0.0, 255.0)
        return (q - 128.0).astype(jnp.int8)
    return jnp.clip(jnp.round(x * x_scale), -127, 127).astype(jnp.int8)


def zero_point_corr_dense(w_q):
    """Per-output-channel zero-point correction 128·Σᵢ W[:, i] (int32) — a
    per-layer constant; compute once at quantization time."""
    return 128 * jnp.sum(w_q.astype(jnp.int32), axis=1)


def zero_point_corr_conv(x_shape, w_q, stride=(1, 1), pad=(0, 0),
                         dilate=(1, 1), groups: int = 1):
    """Zero-point correction for a uint8 conv: 128·conv(1, w). Depends only on
    (input shape, weights, geometry) — compute once per input shape and cache
    on the layer; XLA constant-folds it under jit."""
    dn = lax.conv_dimension_numbers(x_shape, w_q.shape, ("NCHW", "OIHW", "NCHW"))
    ones = jnp.ones(x_shape, jnp.int8)
    return 128 * lax.conv_general_dilated(
        ones, w_q, window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.int32)


def int8_dense(x, w_q, w_scale, x_scale, bias=None, x_unsigned: bool = False,
               zp_corr=None):
    """int8/uint8 x int8 -> int32 matmul on the MXU, rescaled to float.

    ``x`` is float; it is quantized with the calibrated ``x_scale`` on the way
    in (fake-quant boundary). ``w_q`` is pre-quantized int8 [out, in];
    ``w_scale`` is per-output-channel [out]. With ``x_unsigned`` the
    activation uses the uint8 range via a zero-point-128 shift:
    u8·Wᵀ = (u8-128)·Wᵀ + 128·Σᵢ W[:, i]. Parity target:
    quantized_fully_connected.cc (uint8 is its primary dtype)."""
    x_q = _quantize_act(x, x_scale, x_unsigned)
    acc = lax.dot_general(x_q, w_q, (((x_q.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    if x_unsigned:
        acc = acc + (zp_corr if zp_corr is not None
                     else zero_point_corr_dense(w_q))
    out = acc.astype(jnp.float32) / (x_scale * w_scale)
    if bias is not None:
        out = out + bias
    return out


def int8_conv(x, w_q, w_scale, x_scale, bias=None, stride=(1, 1), pad=(0, 0),
              dilate=(1, 1), groups: int = 1, x_unsigned: bool = False,
              zp_corr=None):
    """int8/uint8 x int8 -> int32 NCHW convolution on the MXU, rescaled to float.

    ``w_q`` int8 [O, I/g, KH, KW]; ``w_scale`` per-output-channel [O]. The
    uint8 activation path shifts by zero-point 128; the correction term
    128·conv(1, w) is a per-(shape, layer) constant — callers should pass the
    cached ``zp_corr`` (``zero_point_corr_conv``) so eager forwards don't pay
    a second conv. Parity target: quantized_conv.cc."""
    x_q = _quantize_act(x, x_scale, x_unsigned)
    dn = lax.conv_dimension_numbers(x.shape, w_q.shape, ("NCHW", "OIHW", "NCHW"))
    conv_kw = dict(window_strides=tuple(stride),
                   padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
                   dimension_numbers=dn, feature_group_count=groups)
    acc = lax.conv_general_dilated(x_q, w_q,
                                   preferred_element_type=jnp.int32, **conv_kw)
    if x_unsigned:
        # 128·conv(1, w): a per-(shape, layer) constant — pass the cached
        # zp_corr from the layer to avoid paying a second conv per forward
        # in eager mode (under jit XLA constant-folds it either way)
        acc = acc + (zp_corr if zp_corr is not None else zero_point_corr_conv(
            x.shape, w_q, stride, pad, dilate, groups))
    out = acc.astype(jnp.float32) / (x_scale * w_scale.reshape(1, -1, 1, 1))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def quantize_weight(w, per_channel_axis=0):
    """Symmetric per-output-channel int8 weight quantization.

    Returns (w_q int8, scale) with ``w ~= w_q / scale`` (scale shaped for the
    channel axis). The reference quantizes weights per-tensor
    (quantize_graph_pass); per-channel is strictly more accurate and free on
    TPU since the rescale fuses into the accumulator readout."""
    red = tuple(i for i in range(w.ndim) if i != per_channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=red, keepdims=True)
    scale = 127.0 / jnp.maximum(absmax, 1e-30)
    w_q = jnp.clip(jnp.round(w * scale), -127, 127).astype(jnp.int8)
    return w_q, scale.reshape(-1)


# ---------------------------------------------------------------------------
# quantized graph ops (src/operator/quantization/quantized_conv.cc,
# quantized_fully_connected.cc, quantized_pooling.cc, quantized_flatten.cc):
# int8/uint8 in, int32 accumulator + its float range out — composes with
# contrib.requantize exactly like the reference's quantize->op->requantize
# chains. quantize_net's fused path remains the production route; these ops
# exist for graph-level parity and manual pipelines.
# ---------------------------------------------------------------------------


def _in_scale(q, min_r, max_r):
    """quantization scale implied by a tensor's dtype + travelling range
    (one formula — _scale_of — keyed on the carried dtype)."""
    return _scale_of(min_r, max_r,
                     "uint8" if q.dtype == jnp.uint8 else "int8")


def _acc_range(scale_d, scale_w):
    """Range descriptor for the int32 accumulator: real = acc * absmax/2^31-1
    (the contract contrib.requantize consumes)."""
    absmax = 2147483647.0 / (scale_d * scale_w)
    return -absmax, absmax


@register("quantized_flatten", namespace=NS, num_outputs=3,
          differentiable=False)
def _quantized_flatten(data, min_data, max_data):
    return data.reshape(data.shape[0], -1), min_data, max_data


@register("quantized_pooling", namespace=NS, num_outputs=3,
          differentiable=False)
def _quantized_pooling(data, min_data, max_data, kernel=(2, 2),
                       pool_type: str = "max", stride=(2, 2), pad=(0, 0)):
    """Pooling straight on the quantized ints; the range travels unchanged
    (max pool) / exactly (avg divides the int32 sum)."""
    kh, kw = kernel
    sh, sw = stride
    x = data.astype(jnp.int32)
    pads = ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1]))
    if pool_type == "max":
        init = jnp.iinfo(jnp.int32).min
        out = lax.reduce_window(jnp.pad(x, pads, constant_values=init), init,
                                lax.max, (1, 1, kh, kw), (1, 1, sh, sw),
                                "VALID")
        return out.astype(data.dtype), min_data, max_data
    summed = lax.reduce_window(jnp.pad(x, pads), 0, lax.add,
                               (1, 1, kh, kw), (1, 1, sh, sw), "VALID")
    # round, don't truncate: floor() would bias every avg activation -0.5 LSB
    out = jnp.round(summed / (kh * kw)).astype(data.dtype)
    return out, min_data, max_data


@register("quantized_fully_connected", namespace=NS, num_outputs=3,
          differentiable=False)
def _quantized_fully_connected(data, weight, min_data, max_data, min_weight,
                               max_weight, num_hidden: int = 0,
                               no_bias: bool = True):
    if not no_bias:
        raise NotImplementedError(
            "quantized_fully_connected: bias inputs are not bound — fold the "
            "bias after requantize/dequantize (quantize_net's fused path "
            "does this), or call with no_bias=True")
    sd = _in_scale(data, min_data, max_data)
    sw = _in_scale(weight, min_weight, max_weight)
    x = data.astype(jnp.int32)
    if data.dtype == jnp.uint8:
        x = x - 128
    acc = lax.dot_general(x.astype(jnp.int8), weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    if data.dtype == jnp.uint8:
        acc = acc + zero_point_corr_dense(weight)
    lo, hi = _acc_range(sd, sw)
    return acc, lo, hi


@register("quantized_conv", namespace=NS, num_outputs=3, differentiable=False)
def _quantized_conv(data, weight, min_data, max_data, min_weight, max_weight,
                    kernel=(1, 1), stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                    num_filter: int = 0, num_group: int = 1,
                    no_bias: bool = True, layout: str = "NCHW"):
    if not no_bias:
        raise NotImplementedError(
            "quantized_conv: bias inputs are not bound — fold the bias after "
            "requantize/dequantize, or call with no_bias=True")
    if layout != "NCHW":
        raise NotImplementedError(f"quantized_conv: layout {layout!r} "
                                  f"(NCHW only)")
    sd = _in_scale(data, min_data, max_data)
    sw = _in_scale(weight, min_weight, max_weight)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    conv_kw = dict(window_strides=tuple(stride),
                   padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
                   dimension_numbers=dn, feature_group_count=num_group)
    x = data
    if data.dtype == jnp.uint8:
        x = (data.astype(jnp.int32) - 128).astype(jnp.int8)
    acc = lax.conv_general_dilated(x, weight,
                                   preferred_element_type=jnp.int32, **conv_kw)
    if data.dtype == jnp.uint8:
        acc = acc + zero_point_corr_conv(x.shape, weight, stride, pad, dilate,
                                         num_group)
    lo, hi = _acc_range(sd, sw)
    return acc, lo, hi
