"""INT8 quantization ops — capability parity with ``src/operator/quantization/``
(quantize.cc, dequantize.cc, requantize.cc, quantized_conv.cc,
quantized_fully_connected.cc; driven from python/mxnet/contrib/quantization.py).

TPU-native design: int8 matmuls/convs issue ``lax.dot_general`` /
``lax.conv_general_dilated`` with int8 operands and
``preferred_element_type=int32`` — XLA lowers these onto the MXU's int8 path
(2x the bf16 peak on v5e: 394 vs 197 TOPS). The reference's separate
quantize→quantized_op→requantize node chains collapse: the compiled path keeps
activations float at layer boundaries (fake-quant on the way in), which is the
same numerics with fewer HBM round-trips, letting XLA fuse the rescale into the
int32 accumulator readout.

Range convention matches the reference (quantization_utils.h): a float range
[min, max] maps onto the signed int range symmetrically via
``scale = q_max / max(|min|, |max|)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NS = "contrib"

_QMAX = {"int8": 127.0, "uint8": 255.0}


def _scale_of(min_range, max_range, out_type="int8"):
    absmax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return _QMAX[out_type] / jnp.maximum(absmax, 1e-30)


@register("quantize", namespace=NS, num_outputs=3, differentiable=False)
def _quantize(data, min_range, max_range, out_type: str = "int8"):
    """quantize.cc parity: float -> int8/uint8 given a calibrated range.

    Returns (quantized, out_min, out_max) like the reference (3 outputs so the
    range travels with the tensor through a quantized graph)."""
    scale = _scale_of(min_range, max_range, out_type)
    q = jnp.clip(jnp.round(data * scale), -_QMAX[out_type], _QMAX[out_type])
    dt = jnp.int8 if out_type == "int8" else jnp.uint8
    absmax = _QMAX[out_type] / scale
    return q.astype(dt), -absmax, absmax


@register("dequantize", namespace=NS, differentiable=False)
def _dequantize(data, min_range, max_range, out_type: str = "float32"):
    """dequantize.cc parity: int8/uint8 -> float given the tensor's range."""
    qmax = _QMAX["uint8" if data.dtype == jnp.uint8 else "int8"]
    absmax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return data.astype(out_type) * (absmax / qmax)


@register("requantize", namespace=NS, num_outputs=3, differentiable=False)
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """requantize.cc parity: int32 accumulator -> int8 with a (calibrated or
    on-the-fly) output range."""
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / 2147483647.0)
    if min_calib_range is None:
        max_calib_range = jnp.max(jnp.abs(real))
        min_calib_range = -max_calib_range
    scale = _scale_of(min_calib_range, max_calib_range, "int8")
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    return q, min_calib_range, max_calib_range


def int8_dense(x, w_q, w_scale, x_scale, bias=None):
    """int8 x int8 -> int32 matmul on the MXU, rescaled to float.

    ``x`` is float; it is quantized with the calibrated ``x_scale`` on the way
    in (fake-quant boundary). ``w_q`` is pre-quantized int8 [out, in];
    ``w_scale`` is per-output-channel [out]. Parity target:
    quantized_fully_connected.cc."""
    x_q = jnp.clip(jnp.round(x * x_scale), -127, 127).astype(jnp.int8)
    acc = lax.dot_general(x_q, w_q, (((x_q.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) / (x_scale * w_scale)
    if bias is not None:
        out = out + bias
    return out


def int8_conv(x, w_q, w_scale, x_scale, bias=None, stride=(1, 1), pad=(0, 0),
              dilate=(1, 1), groups: int = 1):
    """int8 x int8 -> int32 NCHW convolution on the MXU, rescaled to float.

    ``w_q`` int8 [O, I/g, KH, KW]; ``w_scale`` per-output-channel [O]. Parity
    target: quantized_conv.cc."""
    x_q = jnp.clip(jnp.round(x * x_scale), -127, 127).astype(jnp.int8)
    dn = lax.conv_dimension_numbers(x.shape, w_q.shape, ("NCHW", "OIHW", "NCHW"))
    acc = lax.conv_general_dilated(
        x_q, w_q, window_strides=tuple(stride), padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate), dimension_numbers=dn,
        feature_group_count=groups, preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) / (x_scale * w_scale.reshape(1, -1, 1, 1))
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def quantize_weight(w, per_channel_axis=0):
    """Symmetric per-output-channel int8 weight quantization.

    Returns (w_q int8, scale) with ``w ~= w_q / scale`` (scale shaped for the
    channel axis). The reference quantizes weights per-tensor
    (quantize_graph_pass); per-channel is strictly more accurate and free on
    TPU since the rescale fuses into the accumulator readout."""
    red = tuple(i for i in range(w.ndim) if i != per_channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=red, keepdims=True)
    scale = 127.0 / jnp.maximum(absmax, 1e-30)
    w_q = jnp.clip(jnp.round(w * scale), -127, 127).astype(jnp.int8)
    return w_q, scale.reshape(-1)
