"""Creation ops — parity with ``src/operator/tensor/init_op.cc`` (zeros/ones/arange/eye…).

These take no array inputs; ``ctx`` placement is applied by the NDArray wrapper layer
(creation lands on the current default device; explicit ``ctx=`` triggers a device_put).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..base import dtype_np
from .registry import register


@register("zeros", differentiable=False)
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(tuple(shape) if not isinstance(shape, int) else (shape,), dtype_np(dtype))


@register("ones", differentiable=False)
def _ones(shape=(), dtype="float32"):
    return jnp.ones(tuple(shape) if not isinstance(shape, int) else (shape,), dtype_np(dtype))


@register("full", differentiable=False)
def _full(shape=(), val: float = 0.0, dtype="float32"):
    return jnp.full(tuple(shape) if not isinstance(shape, int) else (shape,), val, dtype_np(dtype))


@register("zeros_like", differentiable=False)
def _zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", differentiable=False)
def _ones_like(data):
    return jnp.ones_like(data)


@register("full_like", differentiable=False)
def _full_like(data, fill_value: float = 0.0):
    return jnp.full_like(data, fill_value)


@register("arange", differentiable=False)
def _arange(start=0, stop=None, step: float = 1.0, repeat: int = 1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("linspace", differentiable=False)
def _linspace(start=0.0, stop=1.0, num: int = 50, endpoint: bool = True, dtype="float32"):
    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=dtype_np(dtype))


@register("eye", differentiable=False)
def _eye(N: int, M: int = 0, k: int = 0, dtype="float32"):
    return jnp.eye(N, M if M else None, k=k, dtype=dtype_np(dtype))
