"""Matrix / shape-manipulation / indexing ops.

Parity targets: ``src/operator/tensor/matrix_op-inl.h`` (reshape/transpose/slice/concat/
tile/repeat/pad/flip/depth-space), ``dot-inl.h`` (dot/batch_dot with transpose flags),
``indexing_op.h`` (take/batch_take/one_hot/gather_nd/scatter_nd/pick/Embedding-gather).
The MXU note: ``dot``/``batch_dot`` lower to ``lax.dot_general``, which is exactly what
the systolic array wants — keep operands large and let callers pick bf16.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# dot family
# ---------------------------------------------------------------------------


@register("dot")
def _dot(lhs, rhs, transpose_a: bool = False, transpose_b: bool = False):
    """Reference ``dot`` (dot-inl.h): contract lhs's last axis with rhs's first.

    For 2-D this is matmul with optional operand transposes; for >2-D it reduces the
    last axis of lhs against the first of rhs (tensordot semantics), matching
    mx.nd.dot.
    """
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2) if lhs.ndim >= 2 else lhs
    if transpose_b:
        rhs = jnp.swapaxes(rhs, 0, 1) if rhs.ndim >= 2 else rhs
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot")
def _batch_dot(lhs, rhs, transpose_a: bool = False, transpose_b: bool = False):
    """Batched matmul over leading batch dims (dot-inl.h batch_dot) → lax.dot_general."""
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("khatri_rao")
def _khatri_rao(*mats):
    """Column-wise Khatri-Rao product (reference contrib/krprod.cc)."""
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, out.shape[1])
    return out


# ---------------------------------------------------------------------------
# reshape & friends
# ---------------------------------------------------------------------------


def _mx_reshape_shape(data_shape: Tuple[int, ...], spec) -> Tuple[int, ...]:
    """Implement the reference's reshape special codes (matrix_op-inl.h ReshapeParam):

    0 = copy this dim; -1 = infer; -2 = copy all remaining dims; -3 = merge two
    consecutive input dims; -4 = split one input dim into the next two spec values.
    """
    out = []
    src = list(data_shape)
    i = 0  # index into src
    j = 0  # index into spec
    spec = list(spec)
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            d1, d2 = spec[j + 1], spec[j + 2]
            cur = src[i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(int(s)); i += 1
        j += 1
    # resolve single -1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(data_shape)) if data_shape else 1
        out[out.index(-1)] = total // known
    return tuple(out)


@register("reshape", aliases=("Reshape",))
def _reshape(data, shape=None, reverse: bool = False):
    tgt = _mx_reshape_shape(tuple(data.shape)[::-1] if reverse else tuple(data.shape),
                            tuple(shape)[::-1] if reverse else tuple(shape))
    if reverse:
        tgt = tgt[::-1]
    return jnp.reshape(data, tgt)


@register("reshape_like")
def _reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("flatten", aliases=("Flatten",))
def _flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def _transpose(data, axes=None):
    return jnp.transpose(data, axes if axes else None)


@register("swapaxes", aliases=("SwapAxis",))
def _swapaxes(data, dim1: int = 0, dim2: int = 0):
    return jnp.swapaxes(data, dim1, dim2)


@register("expand_dims")
def _expand_dims(data, axis: int = 0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def _squeeze(data, axis=None):
    return jnp.squeeze(data, axis)


@register("broadcast_to")
def _broadcast_to(data, shape):
    # reference: 0 in target shape means keep source dim
    tgt = tuple(s if t == 0 else t for s, t in zip(data.shape, shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def _broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(data, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("cast", aliases=("Cast",), differentiable=False)
def _cast(data, dtype="float32"):
    from ..base import dtype_np
    return data.astype(dtype_np(dtype))


@register("stop_gradient", aliases=("BlockGrad",), differentiable=False)
def _stop_gradient(data):
    return lax.stop_gradient(data)


@register("identity", aliases=("_copy",))
def _identity(data):
    return jnp.asarray(data)


@register("shape_array", differentiable=False)
def _shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int32)


@register("size_array", differentiable=False)
def _size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int32)


# ---------------------------------------------------------------------------
# concat / split / stack / slice
# ---------------------------------------------------------------------------


@register("concat", aliases=("Concat", "concatenate"))
def _concat(*data, dim: int = 1):
    """NB: reference default axis is 1 (Concat op), not 0."""
    return jnp.concatenate(data, axis=dim)


@register("stack")
def _stack(*data, axis: int = 0):
    return jnp.stack(data, axis=axis)


@register("split", aliases=("SliceChannel",), num_outputs=-1)
def _split(data, num_outputs: int = 1, axis: int = 1, squeeze_axis: bool = False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("slice", aliases=("crop",))
def _slice(data, begin=(), end=(), step=()):
    """Reference slice op (matrix_op-inl.h SliceParam): None-able begin/end per axis."""
    nd = data.ndim
    begin = tuple(begin) + (None,) * (nd - len(begin))
    end = tuple(end) + (None,) * (nd - len(end))
    step = tuple(step) + (None,) * (nd - len(step)) if step else (None,) * nd
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis")
def _slice_axis(data, axis: int = 0, begin: int = 0, end: Optional[int] = None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def _slice_like(data, shape_like, axes=()):
    axes = axes or tuple(range(shape_like.ndim))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("reverse", aliases=("flip",))
def _reverse(data, axis=0):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axis)


@register("tile")
def _tile(data, reps=()):
    return jnp.tile(data, tuple(reps))


@register("repeat")
def _repeat(data, repeats: int = 1, axis: Optional[int] = None):
    return jnp.repeat(data, repeats, axis=axis)


@register("pad", aliases=("Pad",))
def _pad(data, mode: str = "constant", pad_width=(), constant_value: float = 0.0):
    """Reference Pad op (pad.cc): pad_width is a flat (before,after) list per axis."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    while len(pw) < data.ndim:
        pw.append((0, 0))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@register("depth_to_space")
def _depth_to_space(data, block_size: int):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def _space_to_depth(data, block_size: int):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


@register("take")
def _take(a, indices, axis: int = 0, mode: str = "clip"):
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=jmode)


@register("batch_take")
def _batch_take(a, indices):
    return jnp.take_along_axis(a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("pick")
def _pick(data, index, axis: int = -1, keepdims: bool = False, mode: str = "clip"):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis)


@register("one_hot", differentiable=False)
def _one_hot(indices, depth: int, on_value: float = 1.0, off_value: float = 0.0,
             dtype="float32"):
    from ..base import dtype_np
    eye = jnp.equal(indices.astype(jnp.int32)[..., None],
                    jnp.arange(depth, dtype=jnp.int32))
    return jnp.where(eye, on_value, off_value).astype(dtype_np(dtype))


@register("gather_nd")
def _gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def _scatter_nd(data, indices, shape):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[idx].add(data)


@register("where")
def _where(condition, x, y):
    return jnp.where(condition.astype(bool) if hasattr(condition, "astype") else condition, x, y)


@register("Embedding", aliases=("embedding",))
def _embedding(data, weight, input_dim: int = 0, output_dim: int = 0, dtype="float32",
               sparse_grad: bool = False):
    """Embedding lookup (src/operator/tensor/indexing_op.cc Embedding): a gather.

    On TPU the MXU-friendly formulation for small vocabularies would be one-hot matmul,
    but XLA lowers gather efficiently; sparse_grad is accepted for API parity (gradients
    are dense — the row-sparse path is a kvstore concern, SURVEY.md §7 hard-parts).
    """
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("diag")
def _diag(data, k: int = 0):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("ravel_multi_index", differentiable=False)
def _ravel_multi_index(data, shape):
    idx = tuple(data.astype(jnp.int32))
    return jnp.asarray(jnp.ravel_multi_index(idx, tuple(shape), mode="clip"),
                       dtype=jnp.float32)


@register("unravel_index", differentiable=False)
def _unravel_index(data, shape):
    out = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(out).astype(jnp.float32)


# reference contrib name for the sparse-grad embedding (indexing_op.cc
# _contrib_SparseEmbedding): same forward gather; the row-sparse gradient
# behavior lives in gluon.nn.Embedding(sparse_grad=True)'s recorded backward
from .registry import alias as _alias  # noqa: E402
_alias("Embedding", "SparseEmbedding", "_contrib_SparseEmbedding")
_alias("Embedding", "SparseEmbedding", namespace="contrib")


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs; rhs only donates graph attrs/storage kind (the
    reference's sparse-grad plumbing helper, elemwise_unary_op_basic.cc)."""
    return lhs


@register("_slice_assign", aliases=("_crop_assign",))
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """lhs with lhs[begin:end:step] = rhs (matrix_op.cc _slice_assign — the
    graph form of __setitem__; imperative setitem uses .at[] directly)."""
    idx = tuple(slice(b, e, s if s else None)
                for b, e, s in zip(begin, end, step or (None,) * len(begin)))
    return lhs.at[idx].set(rhs)


@register("_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(lhs, scalar: float = 0.0, begin=(), end=(), step=()):
    idx = tuple(slice(b, e, s if s else None)
                for b, e, s in zip(begin, end, step or (None,) * len(begin)))
    return lhs.at[idx].set(scalar)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=None):
    """lhs with lhs[indices] = rhs (indexing_op.cc _scatter_set_nd; the
    scatter-write twin of gather_nd)."""
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


# the _scatter_*_scalar / _scatter_elemwise_div family exists in the
# reference solely to keep SPARSE storage sparse under scalar/broadcast math
# (elemwise_binary_scalar_op_extended.cc); dense math is identical, and the
# sparse path here applies ops to stored values via the sparse module
_alias("_plus_scalar", "_scatter_plus_scalar")
_alias("_minus_scalar", "_scatter_minus_scalar")
_alias("elemwise_div", "_scatter_elemwise_div")
