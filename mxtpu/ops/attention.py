"""Attention ops — flash attention as a Pallas TPU kernel with an XLA fallback.

The reference predates fused attention (its transformer support is just
``_contrib_div_sqrt_dim``, contrib/transformer.cc:33); for a TPU-native framework
attention IS the hot op, so it gets the Pallas treatment per the long-context mandate
(SURVEY.md §5): blockwise online-softmax (flash) keeps the T×T score matrix out of
HBM — the kernel streams K/V tiles through VMEM and accumulates (m, l, o) running
stats, so memory is O(T·d) instead of O(T²).

``attention(q, k, v)`` dispatches: Pallas kernel on TPU backends (block sizes tuned to
the MXU 128-lane layout), pure-XLA reference elsewhere (CPU tests, odd shapes).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["attention_reference", "flash_attention"]

_NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = False, scale: Optional[float] = None,
                        bias=None):
    """Pure-XLA softmax attention. q,k,v: (B, H, T, D)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    if bias is not None:
        logits = logits + bias
    if causal:
        # top-left alignment (row i attends keys 0..i), matching torch is_causal
        # and the Pallas kernel's rows>=cols convention
        tq, tk = logits.shape[-2], logits.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        logits = jnp.where(rows >= cols, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    """One (batch·head, q-block) program: stream K/V tiles, online softmax."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    block_q = q.shape[0]
    kv_len = k_ref.shape[1]
    num_kb = kv_len // block_k
    qi = pl.program_id(1)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    q_start = qi * block_q

    def body(kb, carry):
        m, l, o = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            rows = q_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        o_new = corr * o + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    if causal:
        # only key blocks up to the diagonal contribute
        last_kb = (q_start + block_q - 1) // block_k + 1
        num_iter = jnp.minimum(num_kb, last_kb)
    else:
        num_iter = num_kb
    m, l, o = lax.fori_loop(0, num_iter, body, (m0, l0, o0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_attention_pallas(q, k, v, causal: bool, scale: float,
                            block_q: int = 128, block_k: int = 128,
                            interpret: bool = False):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    qq = q.reshape(B * H, T, D)
    kk = k.reshape(B * H, Tk, D)
    vv = v.reshape(B * H, Tk, D)
    grid = (B * H, T // block_q)

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qq, kk, vv)
    return out.reshape(B, H, T, D)


def _use_pallas(q) -> bool:
    if jax.default_backend() not in ("tpu",):
        return False
    T, D = q.shape[2], q.shape[3]
    return T % 128 == 0 and D % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal, scale):
    if _use_pallas(q) and q.shape[2] == k.shape[2]:
        return _flash_attention_pallas(q, k, v, causal, scale)
    return attention_reference(q, k, v, causal=causal, scale=scale)


def _flash_fwd(q, k, v, causal, scale):
    return _flash_core(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, g):
    # backward recomputes through the XLA reference formulation (a fused flash
    # backward kernel is a later optimization; memory is still O(T²) only inside
    # this bwd — acceptable until the Pallas bwd lands)
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: attention_reference(
        q_, k_, v_, causal=causal, scale=scale), q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


@register("flash_attention", namespace="contrib", aliases=("attention",))
def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Fused scaled-dot-product attention; q,k,v: (B, H, T, D).

    Pallas forward on TPU when tile-aligned (T, D multiples of 128), XLA reference
    otherwise; backward via custom_vjp recompute — numerically equivalent paths.
    """
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_core(q, k, v, causal, s)
