"""Attention ops — flash attention as Pallas TPU kernels (fwd + bwd) with an
XLA fallback.

The reference predates fused attention (its transformer support is just
``_contrib_div_sqrt_dim``, contrib/transformer.cc:33); for a TPU-native
framework attention IS the hot op, so it gets the Pallas treatment per the
long-context mandate (SURVEY.md §5): blockwise online-softmax (flash) keeps the
T×T score matrix out of HBM — kernels stream K/V tiles through VMEM.

Production shapes engage the kernel: head dims 64/96/128/... (any D ≤ 512) are
zero-padded to the 128-lane width inside the wrapper (padding columns
contribute nothing to q·kᵀ and produce zero output columns, sliced off
afterwards). Sequence lengths engage when T % 128 == 0 on real hardware
(sub-128 whole-axis blocks pass in interpret mode but real Mosaic rejects
their vector loads — observed on v5e); anything else falls back to the XLA
reference, which is equally fast at those sizes. The backward pass is the standard flash
backward — forward saves the per-row log-sum-exp; two kernels recompute the
probabilities per tile and accumulate dq (grid over q blocks) and dk/dv (grid
over k blocks) without materializing T×T.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["attention_reference", "flash_attention", "flash_chunk"]

_NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = False, scale: Optional[float] = None,
                        bias=None):
    """Pure-XLA softmax attention. q,k,v: (B, H, T, D). The bias-free path is
    the single shared implementation (``_chunk_reference_lse``)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    if bias is None:
        return _chunk_reference_lse(q, k, v, causal, s)[0]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s + bias
    if causal:
        # top-left alignment (row i attends keys 0..i), matching torch is_causal
        # and the Pallas kernel's rows>=cols convention
        tq, tk = logits.shape[-2], logits.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        logits = jnp.where(rows >= cols, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash kernels
# ---------------------------------------------------------------------------


def _pick_block(t: int, cap: int = 512) -> int:
    """Largest legal q/k block: Mosaic requires the lse/delta row blocks'
    last dim to be 128-divisible or equal to the full axis, so blocks are
    multiples of 128 dividing t, or the whole axis (t <= 128, t % 8 == 0).

    Cap 512 measured fastest on v5e at production shapes (B4 H16 T2048 D64
    fwd+bwd: 15.1 ms @128 → 6.7 ms @512, vs 20.7 ms XLA reference); 1024
    exceeds VMEM and fails to compile. Launch sites scale the cap down with
    the padded head dim (`_block_cap`) so large-D shapes stay inside VMEM.

    Raises :exc:`ValueError` when no Mosaic-legal block exists — launch
    sites gate on ``_use_pallas``/``_legal_bucket`` first, so hitting this
    means a kernel was invoked directly at an unsupported length; the error
    names the constraint instead of surfacing as an opaque Mosaic lowering
    failure deep inside ``pallas_call``."""
    if cap < 128:
        # below 128 only a whole-axis block is Mosaic-legal (the lse/delta
        # row block must be 128-divisible or the full axis)
        if t <= cap and t % 8 == 0:
            return t
        raise ValueError(
            f"no Mosaic-legal flash block for axis length {t} under cap "
            f"{cap}: sub-128 caps admit only a whole-axis block, needing "
            f"t <= {cap} and t % 8 == 0 (Mosaic sublane tiling)")
    if t % 128 == 0:
        b = min(cap - cap % 128, t)
        while b > 128 and t % b != 0:
            b -= 128
        return b
    if t <= 128 and t % 8 == 0:
        return t
    raise ValueError(
        f"no Mosaic-legal flash block for axis length {t}: the lse/delta "
        f"row block's last dim must be a multiple of 128 or the whole "
        f"axis, so t must be a multiple of 128, or t <= 128 with "
        f"t % 8 == 0. Pad the sequence (e.g. to {-(-t // 128) * 128}) or "
        f"take the XLA reference path")


def _block_cap(dp: int) -> int:
    """VMEM-aware block cap: 512 validated at Dp=128; scale down linearly in
    the padded head dim so the per-program tiles stay in the same budget
    (Dp=256 → 256, Dp≥512 → 128, the previously-validated floor)."""
    return max(128, 512 * 128 // max(dp, 128))


def _bwd_mode() -> str:
    """Flash-backward launch shape: ``'split'`` (default — the validated
    two-kernel dq then dk/dv pair) or ``'fused'`` (``MXTPU_FLASH_BWD=fused``
    — one kernel per (batch·head, tile) computing dq for its q-tile AND
    dk/dv for its k-tile, halving launches and re-streaming each opposing
    tile once instead of twice across kernels). Long-context retune knob
    (PR16 tentpole c); read at trace time, so flipping it retraces."""
    return "fused" if os.environ.get(
        "MXTPU_FLASH_BWD", "").strip().lower() == "fused" else "split"


def _lse_store_dtype():
    """Storage dtype for the sublane-broadcast lse/delta rows the backward
    kernels stream: f32 (default, exact) or bf16 (``MXTPU_FLASH_LSE=bf16``)
    which halves that HBM traffic at long T. Kernels accumulate in f32
    either way — only the stored rows round. Softmax weights are exp(s-lse),
    so a bf16 lse (rel err ~2^-8) perturbs weights ~0.4% — fine for
    training steps, not for bit-exactness guards, hence opt-in."""
    return jnp.bfloat16 if os.environ.get(
        "MXTPU_FLASH_LSE", "").strip().lower() == "bf16" else jnp.float32


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                      causal: bool, scale: float):
    """One (batch·head, q-block) program: stream K/V tiles, online softmax.
    Also writes the per-row log-sum-exp needed by the backward kernels."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    block_q = q.shape[0]
    kv_len = k_ref.shape[1]
    num_kb = kv_len // block_k
    qi = pl.program_id(1)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    q_start = qi * block_q

    def body(kb, carry):
        m, l, o = carry
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            rows = q_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        o_new = corr * o + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    if causal:
        # only key blocks up to the diagonal contribute
        last_kb = (q_start + block_q - 1) // block_k + 1
        num_iter = jnp.minimum(num_kb, last_kb)
    else:
        num_iter = num_kb
    m, l, o = lax.fori_loop(0, num_iter, body, (m0, l0, o0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    # lse travels broadcast over 8 sublanes — Mosaic requires the block's
    # second-to-last dim to be 8-divisible (a bare (1, block_q) is illegal)
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, 0][None, :],
                                  (8, block_q))


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool, scale: float):
    """dq for one q block: loop K/V tiles, recompute P from the saved lse."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)[:, None]
    delta = delta_ref[0, 0].astype(jnp.float32)[:, None]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    q_start = qi * block_q
    kv_len = k_ref.shape[1]
    num_kb = kv_len // block_k

    def body(kb, dq):
        k_blk = k_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            rows = q_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                       # masked entries underflow to 0
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        last_kb = (q_start + block_q - 1) // block_k + 1
        num_iter = jnp.minimum(num_kb, last_kb)
    else:
        num_iter = num_kb
    dq0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    dq = lax.fori_loop(0, num_iter, body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float):
    """dk/dv for one k block: loop q tiles, recompute P from the saved lse."""
    from jax.experimental import pallas as pl

    k_blk = k_ref[0].astype(jnp.float32)           # (block_k, d)
    v_blk = v_ref[0].astype(jnp.float32)
    block_k = k_blk.shape[0]
    kb = pl.program_id(1)
    k_start = kb * block_k
    t = q_ref.shape[1]
    num_qb = t // block_q

    def body(qb, carry):
        dk, dv = carry
        qs = qb * block_q
        q = q_ref[0, pl.dslice(qs, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.dslice(qs, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(qs, block_q)].astype(jnp.float32)[:, None]
        delta = delta_ref[0, 0, pl.dslice(qs, block_q)].astype(
            jnp.float32)[:, None]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            rows = qs + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_new = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    start_qb = (k_start // block_q) if causal else 0
    z = jnp.zeros((block_k, k_blk.shape[1]), jnp.float32)
    dk, dv = lax.fori_loop(start_qb, num_qb, body, (z, z))
    # dk absorbed one factor of scale through q; no extra factor needed
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dk_ref, dv_ref, *, block: int,
                            causal: bool, scale: float):
    """One (batch·head, tile i) program producing dq for q-tile i AND dk/dv
    for k-tile i (``MXTPU_FLASH_BWD=fused``). Requires self-attention
    tiling (T == Tk, shared block). The two inner loops walk complementary
    causal wedges — key tiles j <= i for dq, query tiles j >= i for dk/dv —
    so together each program touches one full stripe of the T×T square and
    the grid covers it exactly once, in half the kernel launches of the
    split pair."""
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    t = q_ref.shape[1]
    num_b = t // block
    i_start = i * block

    q_i = q_ref[0, pl.dslice(i_start, block), :].astype(jnp.float32) * scale
    do_i = do_ref[0, pl.dslice(i_start, block), :].astype(jnp.float32)
    lse_i = lse_ref[0, 0, pl.dslice(i_start, block)].astype(
        jnp.float32)[:, None]
    delta_i = delta_ref[0, 0, pl.dslice(i_start, block)].astype(
        jnp.float32)[:, None]
    k_i = k_ref[0, pl.dslice(i_start, block), :].astype(jnp.float32)
    v_i = v_ref[0, pl.dslice(i_start, block), :].astype(jnp.float32)

    # -- dq for q-tile i: stream key tiles j (j <= i when causal) ----------
    def dq_body(j, dq):
        ks = j * block
        k_blk = k_ref[0, pl.dslice(ks, block), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(ks, block), :].astype(jnp.float32)
        s = jnp.dot(q_i, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            rows = i_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ks + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_i)
        dp = jnp.dot(do_i, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block, q_i.shape[1]), jnp.float32)
    dq = lax.fori_loop(0, jnp.minimum(num_b, i + 1) if causal else num_b,
                       dq_body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)

    # -- dk/dv for k-tile i: stream query tiles j (j >= i when causal) -----
    def dkv_body(j, carry):
        dk, dv = carry
        qs = j * block
        q_blk = q_ref[0, pl.dslice(qs, block), :].astype(jnp.float32) * scale
        do_blk = do_ref[0, pl.dslice(qs, block), :].astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.dslice(qs, block)].astype(
            jnp.float32)[:, None]
        delta_blk = delta_ref[0, 0, pl.dslice(qs, block)].astype(
            jnp.float32)[:, None]
        s = jnp.dot(q_blk, k_i.T, preferred_element_type=jnp.float32)
        if causal:
            rows = qs + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = i_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_blk)
        dv_new = dv + jnp.dot(p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v_i.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk)
        dk_new = dk + jnp.dot(ds.T, q_blk, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((block, k_i.shape[1]), jnp.float32)
    dk, dv = lax.fori_loop(i if causal else 0, num_b, dkv_body, (z, z))
    # dk absorbed one factor of scale through q_blk; no extra factor needed
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_d(x):
    d = x.shape[-1]
    dp = -(-d // 128) * 128
    if dp == d:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, dp - d)))


def _flash_attention_pallas(q, k, v, causal: bool, scale: float,
                            block_q: int = 512, block_k: int = 512,
                            interpret: bool = False):
    """Forward kernel launch; returns (out, lse). q,k,v: (B, H, T, D)."""
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    Tk = k.shape[2]
    qq = _pad_d(q.reshape(B * H, T, D))
    kk = _pad_d(k.reshape(B * H, Tk, D))
    vv = _pad_d(v.reshape(B * H, Tk, D))
    Dp = qq.shape[-1]
    block_q = _pick_block(T, min(block_q, _block_cap(Dp)))
    block_k = _pick_block(Tk, min(block_k, _block_cap(Dp)))
    grid = (B * H, T // block_q)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk, Dp), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
            jax.ShapeDtypeStruct((B * H, 8, T), jnp.float32),
        ],
        interpret=interpret,
    )(qq, kk, vv)
    return out[..., :D].reshape(B, H, T, D), lse[:, 0, :]


def _flash_backward_pallas(q, k, v, o, lse, g, causal: bool, scale: float,
                           block_q: int = 512, block_k: int = 512,
                           interpret: bool = False, lse_cot=None):
    """Flash backward: dq via q-block grid, dk/dv via k-block grid (the
    default 'split' launch), or one fused grid doing both per tile when
    ``MXTPU_FLASH_BWD=fused`` and the shape is self-attention tiling.

    ``lse_cot`` (B,H,T): optional cotangent of the log-sum-exp output (ring
    merges differentiate through lse); it folds into the delta term exactly —
    dS = P∘(dP - (Δ - dlse)) since ∂lse/∂S = P."""
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    Tk = k.shape[2]
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if lse_cot is not None:
        delta = delta - lse_cot.astype(jnp.float32)
    # lse/delta ride (BH, 8, T): sublane-broadcast to satisfy Mosaic tiling;
    # MXTPU_FLASH_LSE=bf16 halves this streamed traffic (kernels re-widen)
    row_dt = _lse_store_dtype()
    delta = jnp.broadcast_to(
        delta.astype(row_dt).reshape(B * H, 1, T), (B * H, 8, T))
    lse = jnp.broadcast_to(
        lse.astype(row_dt).reshape(B * H, 1, T), (B * H, 8, T))
    qq = _pad_d(q.reshape(B * H, T, D))
    kk = _pad_d(k.reshape(B * H, Tk, D))
    vv = _pad_d(v.reshape(B * H, Tk, D))
    gg = _pad_d(g.reshape(B * H, T, D))
    Dp = qq.shape[-1]
    # same padded-D cap as the forward (blocks must match its VMEM budget)
    block_q = _pick_block(T, min(block_q, _block_cap(Dp)))
    block_k = _pick_block(Tk, min(block_k, _block_cap(Dp)))

    if _bwd_mode() == "fused" and T == Tk and block_q == block_k:
        fused = functools.partial(_flash_bwd_fused_kernel, block=block_q,
                                  causal=causal, scale=scale)
        dq, dk, dv = pl.pallas_call(
            fused,
            grid=(B * H, T // block_q),
            in_specs=[
                pl.BlockSpec((1, T, Dp), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, Tk, Dp), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, Tk, Dp), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, T, Dp), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, 8, T), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, 8, T), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
                jax.ShapeDtypeStruct((B * H, Tk, Dp), k.dtype),
                jax.ShapeDtypeStruct((B * H, Tk, Dp), v.dtype),
            ],
            interpret=interpret,
        )(qq, kk, vv, gg, lse, delta)
        return (dq[..., :D].reshape(B, H, T, D),
                dk[..., :D].reshape(B, H, Tk, D),
                dv[..., :D].reshape(B, H, Tk, D))

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                                  causal=causal, scale=scale)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tk, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tk, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        interpret=interpret,
    )(qq, kk, vv, gg, lse, delta)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                                   causal=causal, scale=scale)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Tk // block_k),
        in_specs=[
            pl.BlockSpec((1, T, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 8, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 8, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, Dp), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, Dp), v.dtype),
        ],
        interpret=interpret,
    )(qq, kk, vv, gg, lse, delta)

    return (dq[..., :D].reshape(B, H, T, D),
            dk[..., :D].reshape(B, H, Tk, D),
            dv[..., :D].reshape(B, H, Tk, D))


def _use_pallas(q, k) -> bool:
    if jax.default_backend() not in ("tpu",):
        return False
    T, D = q.shape[2], q.shape[3]
    Tk = k.shape[2]
    # hardware gate: 128-multiple sequence only. The T<=128 whole-axis block
    # is legal to *interpret* but real Mosaic rejects its sub-128 vector
    # loads ("index in dimension 2 is a multiple of 128", observed on v5e
    # with T=16, Dp=128) — and at those sizes the XLA path is just as fast.
    return T == Tk and D <= 512 and T % 128 == 0


def _chunk_reference_lse(q, k, v, causal, scale):
    """(normalized out, lse) via plain XLA — the flash_chunk fallback. Rows
    with every key masked produce a very negative lse, which zeroes their
    weight in any downstream lse-merge."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        logits = jnp.where(rows >= cols, logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhqk,bhkd->bhqd", p / l, v)
    lse = (m + jnp.log(l))[..., 0]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_chunk(q, k, v, causal, scale):
    """One self-attention chunk returning (normalized out, lse (B,H,T)) —
    the composable unit ring attention merges across devices. Pallas on TPU
    at eligible shapes, XLA fallback elsewhere; the custom vjp handles BOTH
    cotangents (out and lse), so lse-merges differentiate exactly."""
    if _use_pallas(q, k):
        out, lse = _flash_attention_pallas(q, k, v, causal, scale)
        return out, lse.reshape(q.shape[0], q.shape[1], q.shape[2])
    return _chunk_reference_lse(q, k, v, causal, scale)


def _flash_chunk_fwd(q, k, v, causal, scale):
    out, lse = flash_chunk(q, k, v, causal, scale)
    return (out, lse), (q, k, v, out, lse)


def _flash_chunk_bwd(causal, scale, res, cots):
    q, k, v, out, lse = res
    g_o, g_lse = cots
    if _use_pallas(q, k):
        B, H, T, _ = q.shape
        lse2d = lse.reshape(B * H, T)
        return _flash_backward_pallas(q, k, v, out, lse2d, g_o, causal, scale,
                                      lse_cot=g_lse)
    _, vjp = jax.vjp(lambda q_, k_, v_: _chunk_reference_lse(
        q_, k_, v_, causal, scale), q, k, v)
    return vjp((g_o, g_lse))


flash_chunk.defvjp(_flash_chunk_fwd, _flash_chunk_bwd)


@register("flash_attention", namespace="contrib", aliases=("attention",))
def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Fused scaled-dot-product attention; q,k,v: (B, H, T, D).

    Pallas fwd+bwd on TPU at production shapes (any head dim ≤512 via lane
    padding; T % 128 == 0), XLA reference otherwise — numerically equivalent
    paths. Thin wrapper over ``flash_chunk`` (the lse output's zero cotangent
    folds away in bwd).
    """
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return flash_chunk(q, k, v, causal, s)[0]
