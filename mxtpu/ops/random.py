"""Random sampling ops — parity with ``src/operator/random/`` (SURVEY.md §2.2).

The reference's samplers run on a per-device counter-based PRNG resource
(kParallelRandom); JAX's threefry keys ARE that design, so each op draws a key from
``mxtpu.rng`` (trace-aware — see rng.py). Registered in the ``random`` namespace and
also exposed as ``nd.random_*`` aliases for reference-name parity.
"""

from __future__ import annotations

import math

from typing import Optional

import jax
import jax.numpy as jnp

from ..base import dtype_np
from .. import rng
from .registry import register

NS = "random"


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


@register("uniform", namespace=NS, differentiable=False, aliases=("random_uniform",))
def _uniform(low: float = 0.0, high: float = 1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    return jax.random.uniform(k, _shape(shape), dtype_np(dtype), low, high)


@register("normal", namespace=NS, differentiable=False,
          aliases=("random_normal", "randn"))
def _normal(loc: float = 0.0, scale: float = 1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    return loc + scale * jax.random.normal(k, _shape(shape), dtype_np(dtype))


@register("gamma", namespace=NS, differentiable=False, aliases=("random_gamma",))
def _gamma(alpha: float = 1.0, beta: float = 1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    return beta * jax.random.gamma(k, alpha, _shape(shape), dtype_np(dtype))


@register("exponential", namespace=NS, differentiable=False,
          aliases=("random_exponential",))
def _exponential(lam: float = 1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    return jax.random.exponential(k, _shape(shape), dtype_np(dtype)) / lam


@register("poisson", namespace=NS, differentiable=False, aliases=("random_poisson",))
def _poisson(lam: float = 1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    return jax.random.poisson(k, lam, _shape(shape)).astype(dtype_np(dtype))


@register("negative_binomial", namespace=NS, differentiable=False,
          aliases=("random_negative_binomial",))
def _negative_binomial(k: int = 1, p: float = 1.0, shape=None, dtype="float32", key=None):
    kk = key if key is not None else rng.next_key()
    k1, k2 = jax.random.split(kk)
    # NB(k,p) = Poisson(Gamma(k, (1-p)/p))
    lam = jax.random.gamma(k1, k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, _shape(shape)).astype(dtype_np(dtype))


@register("generalized_negative_binomial", namespace=NS, differentiable=False,
          aliases=("random_generalized_negative_binomial",))
def _gen_negative_binomial(mu: float = 1.0, alpha: float = 1.0, shape=None,
                           dtype="float32", key=None):
    kk = key if key is not None else rng.next_key()
    k1, k2 = jax.random.split(kk)
    if alpha == 0:
        return jax.random.poisson(k1, mu, _shape(shape)).astype(dtype_np(dtype))
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, _shape(shape)).astype(dtype_np(dtype))


@register("randint", namespace=NS, differentiable=False, aliases=("random_randint",))
def _randint(low: int = 0, high: int = 1, shape=None, dtype="int32", key=None):
    k = key if key is not None else rng.next_key()
    return jax.random.randint(k, _shape(shape), low, high, dtype_np(dtype))


@register("multinomial", namespace=NS, differentiable=False,
          aliases=("sample_multinomial",))
def _multinomial(data, shape=None, get_prob: bool = False, dtype="int32", key=None):
    """Sample indices from (batched) probability rows (sample_multinomial_op.h)."""
    k = key if key is not None else rng.next_key()
    # static python product (a jnp op would stage a tracer under an outer jit)
    n = math.prod(map(int, _shape(shape)))
    logits = jnp.log(jnp.maximum(data, 1e-37))
    if data.ndim == 1:
        out = jax.random.categorical(k, logits, shape=(n,))
        out = out if shape is not None else out[0]
    else:
        out = jax.random.categorical(k, logits[:, None, :].repeat(n, 1), axis=-1)
        out = out if shape is not None else out[:, 0]
    out = out.astype(dtype_np(dtype))
    if get_prob:
        logp = jnp.log(jnp.take_along_axis(
            data if data.ndim > 1 else data[None, :],
            jnp.atleast_2d(out).astype(jnp.int32), axis=-1)).reshape(jnp.shape(out))
        return out, logp
    return out


@register("shuffle", namespace=NS, differentiable=False, aliases=("_shuffle",))
def _random_shuffle(data, key=None):
    k = key if key is not None else rng.next_key()
    return jax.random.permutation(k, data, axis=0)


@register("bernoulli", namespace=NS, differentiable=False)
def _bernoulli(p: float = 0.5, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    return jax.random.bernoulli(k, p, _shape(shape)).astype(dtype_np(dtype))


# sample_* variants: per-element distribution parameters given as arrays
# (src/operator/random/sample_op.cc sample_uniform etc.)

@register("sample_uniform", namespace=NS, differentiable=False)
def _sample_uniform(low, high, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    s = _shape(shape)
    u = jax.random.uniform(k, jnp.shape(low) + s, dtype_np(dtype))
    return low.reshape(low.shape + (1,) * len(s)) + u * (high - low).reshape(
        high.shape + (1,) * len(s))


@register("sample_normal", namespace=NS, differentiable=False)
def _sample_normal(mu, sigma, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    s = _shape(shape)
    z = jax.random.normal(k, jnp.shape(mu) + s, dtype_np(dtype))
    return mu.reshape(mu.shape + (1,) * len(s)) + z * sigma.reshape(sigma.shape + (1,) * len(s))


@register("sample_gamma", namespace=NS, differentiable=False)
def _sample_gamma(alpha, beta, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    s = _shape(shape)
    g = jax.random.gamma(k, alpha.reshape(alpha.shape + (1,) * len(s)),
                         jnp.shape(alpha) + s, dtype_np(dtype))
    return g * beta.reshape(beta.shape + (1,) * len(s))


@register("sample_exponential", namespace=NS, differentiable=False)
def _sample_exponential(lam, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    s = _shape(shape)
    e = jax.random.exponential(k, jnp.shape(lam) + s, dtype_np(dtype))
    return e / lam.reshape(lam.shape + (1,) * len(s))


@register("sample_poisson", namespace=NS, differentiable=False)
def _sample_poisson(lam, shape=None, dtype="float32", key=None):
    k = key if key is not None else rng.next_key()
    s = _shape(shape)
    return jax.random.poisson(
        k, lam.reshape(lam.shape + (1,) * len(s)),
        jnp.shape(lam) + s).astype(dtype_np(dtype))


@register("sample_negative_binomial", namespace=NS, differentiable=False)
def _sample_negative_binomial(k, p, shape=None, dtype="float32", key=None):
    kk = key if key is not None else rng.next_key()
    k1, k2 = jax.random.split(kk)
    s = _shape(shape)
    kr = k.reshape(k.shape + (1,) * len(s))
    pr = p.reshape(p.shape + (1,) * len(s))
    lam = jax.random.gamma(k1, kr, jnp.shape(k) + s) * ((1 - pr) / pr)
    return jax.random.poisson(k2, lam, jnp.shape(k) + s).astype(dtype_np(dtype))


@register("sample_generalized_negative_binomial", namespace=NS,
          differentiable=False)
def _sample_gen_negative_binomial(mu, alpha, shape=None, dtype="float32",
                                  key=None):
    kk = key if key is not None else rng.next_key()
    k1, k2 = jax.random.split(kk)
    s = _shape(shape)
    mur = mu.reshape(mu.shape + (1,) * len(s))
    ar = alpha.reshape(alpha.shape + (1,) * len(s))
    r = 1.0 / jnp.maximum(ar, 1e-12)
    p = r / (r + mur)
    lam = jax.random.gamma(k1, r, jnp.shape(mu) + s) * ((1 - p) / p)
    lam = jnp.where(ar == 0, jnp.broadcast_to(mur, lam.shape), lam)
    return jax.random.poisson(k2, lam, jnp.shape(mu) + s).astype(dtype_np(dtype))
