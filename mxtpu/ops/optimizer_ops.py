"""Fused optimizer-update ops — the public ``mx.nd.sgd_update`` family.

Reference: ``src/operator/optimizer_op.cc:317`` et seq. registers these as
first-class ops (used by custom training loops and the kvstore server's
updater); each is one fused elementwise kernel over (weight, grad, states).
Here each op is a pure JAX function mirroring the reference kernel's exact
math (``optimizer_op-inl.h``: SGDKernel :84, SGDMomKernel :305, MP_SGDKernel
:361, FTMLKernel :752, AdamUpdate :850, RMSPropAlexUpdate :1130, RMSPropUpdate
:1235, FtrlUpdate :1330, SignSGDKernel :1525, SignumKernel :1595) — XLA fuses
the whole update into one HBM-bandwidth-bound pass, the TPU analogue of the
reference's single CUDA kernel launch.

Pure-function contract: every op returns ``(new_weight, *new_states)``; the
``mx.nd`` layer (``ndarray/fused_optimizer.py``) restores the reference's
in-place convention (states mutated, weight written through ``out=``).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _rescaled(grad, rescale_grad, clip_gradient):
    """grad * rescale, clipped iff clip_gradient >= 0 (reference convention:
    negative clip disables)."""
    g = rescale_grad * grad
    if clip_gradient >= 0.0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update", num_outputs=1, differentiable=False)
def sgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """w = (1 - lr*wd)*w - lr*clip(rescale*g) (SGDKernel, optimizer_op-inl.h:84)."""
    g = _rescaled(grad, rescale_grad, clip_gradient)
    return (1.0 - lr * wd) * weight - lr * g


@register("sgd_mom_update", num_outputs=2, differentiable=False)
def sgd_mom_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """mom = momentum*mom - lr*wd*w - lr*clip(rescale*g); w += mom
    (SGDMomKernel, optimizer_op-inl.h:305)."""
    g = _rescaled(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * wd * weight - lr * g
    return weight + mom, mom


@register("mp_sgd_update", num_outputs=2, differentiable=False)
def mp_sgd_update(weight, grad, weight32, *, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """Multi-precision SGD: the update runs on the fp32 master copy, the
    low-precision weight output is a cast of it (MP_SGDKernel :361)."""
    g = _rescaled(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = (1.0 - lr * wd) * weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3, differentiable=False)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    """Multi-precision momentum SGD (MP_SGDMomKernel, optimizer_op-inl.h:409):
    mom and master weight are fp32; output weight is the cast master."""
    g = _rescaled(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom = momentum * mom - lr * wd * weight32 - lr * g
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register("signsgd_update", num_outputs=1, differentiable=False)
def signsgd_update(weight, grad, *, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """w = (1 - lr*wd)*w - lr*sign(g) — clip has no effect on a sign
    (SignSGDKernel, optimizer_op-inl.h:1525)."""
    return (1.0 - lr * wd) * weight - lr * jnp.sign(grad)


@register("signum_update", num_outputs=2, differentiable=False)
def signum_update(weight, grad, mom, *, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """mom = momentum*mom - (1-momentum)*(wd*w + clip(rescale*g));
    w = (1 - lr*wd_lh)*w + lr*sign(mom) (SignumKernel, optimizer_op-inl.h:1595)."""
    g = _rescaled(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - (1.0 - momentum) * wd * weight - (1.0 - momentum) * g
    return (1.0 - lr * wd_lh) * weight + lr * jnp.sign(mom), mom


@register("adam_update", num_outputs=3, differentiable=False)
def adam_update(weight, grad, mean, var, *, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """Fused Adam WITHOUT bias correction — the reference kernel leaves the
    sqrt(1-b2^t)/(1-b1^t) factor to the caller's lr (AdamUpdate,
    optimizer_op-inl.h:850; python optimizer.Adam folds it into lr)."""
    g = rescale_grad * grad + wd * weight
    if clip_gradient >= 0.0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * g
    var = beta2 * var + (1.0 - beta2) * g * g
    return weight - lr * mean / (jnp.sqrt(var) + epsilon), mean, var


@register("ftml_update", num_outputs=4, differentiable=False)
def ftml_update(weight, grad, d, v, z, *, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    """Follow-the-Moving-Leader (FTMLKernel, optimizer_op-inl.h:752)."""
    g = rescale_grad * grad + wd * weight
    if clip_grad >= 0.0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    v = beta2 * v + (1.0 - beta2) * g * g
    d_t = (1.0 - beta1 ** t) / lr * (jnp.sqrt(v / (1.0 - beta2 ** t)) + epsilon)
    z = beta1 * z + (1.0 - beta1) * g - (d_t - beta1 * d) * weight
    return -z / d_t, d_t, v, z


@register("rmsprop_update", num_outputs=2, differentiable=False)
def rmsprop_update(weight, grad, n, *, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    """Tieleman & Hinton RMSProp (RMSPropUpdate, optimizer_op-inl.h:1235)."""
    g = rescale_grad * grad + wd * weight
    if clip_gradient >= 0.0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n = (1.0 - gamma1) * g * g + gamma1 * n
    w = weight - lr * g / jnp.sqrt(n + epsilon)
    if clip_weights >= 0.0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n


@register("rmspropalex_update", num_outputs=4, differentiable=False)
def rmspropalex_update(weight, grad, n, g, delta, *, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves' centered RMSProp (RMSPropAlexUpdate, optimizer_op-inl.h:1130).
    State ``g`` is the running mean gradient; ``delta`` the running step."""
    gr = rescale_grad * grad + wd * weight
    if clip_gradient >= 0.0:
        gr = jnp.clip(gr, -clip_gradient, clip_gradient)
    n = (1.0 - gamma1) * gr * gr + gamma1 * n
    g = (1.0 - gamma1) * gr + gamma1 * g
    delta = gamma2 * delta - lr * gr / jnp.sqrt(n - g * g + epsilon)
    w = weight + delta
    if clip_weights >= 0.0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n, g, delta


@register("ftrl_update", num_outputs=3, differentiable=False)
def ftrl_update(weight, grad, z, n, *, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """FTRL-proximal (FtrlUpdate, optimizer_op-inl.h:1330). Note the reference
    does NOT fold wd into the gradient here — wd enters the denominator."""
    g = _rescaled(grad, rescale_grad, clip_gradient)
    z = z + g - (jnp.sqrt(n + g * g) - jnp.sqrt(n)) * weight / lr
    n = n + g * g
    w = ((jnp.sign(z) * lamda1 - z) / ((beta + jnp.sqrt(n)) / lr + wd)
         * (jnp.abs(z) > lamda1))
    return w.astype(weight.dtype), z, n


@register("_sparse_adagrad_update", num_outputs=2, differentiable=False,
          aliases=("adagrad_update",))
def sparse_adagrad_update(weight, grad, history, *, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0):
    """AdaGrad update (optimizer_op-inl.h:1635 AdagradParam / AdagradUpdate;
    the reference registers only the row-sparse form — the nd wrapper's lazy
    path delivers that, this kernel is the row-slab math)."""
    g = _rescaled(grad, rescale_grad, clip_gradient) + wd * weight
    history = history + g * g
    return weight - lr * g / (jnp.sqrt(history) + epsilon), history
