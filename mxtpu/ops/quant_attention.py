"""Fused dequant-attention decode — the quantized-KV hot path (ISSUE 16).

PR 14's int8/fp8 paged KV cache shrinks residency 3.56x but the original
attention read ran ``dequantize_rows`` over the WHOLE per-layer cache as
plain XLA ops before the score einsum — a full-precision KV materialization
per layer per decode step, which is exactly why ``quant_decode_speedup``
ratcheted at 0.78 (quantization paid in bytes and charged in time). This
module makes the dequantize happen *inside* the attention read on both
execution paths:

* **pallas** — a Pallas TPU kernel streams int8/fp8 KV tiles through VMEM
  and dequantizes in-register inside the online-softmax body (same flash
  structure as ``attention.py``'s forward, specialised to the one-query
  decode shape). The per-row f32 scales ride as an 8-sublane broadcast
  (Mosaic's row-block tiling rule, see ``_flash_fwd_kernel``'s lse); block
  legality reuses ``_pick_block``/``_block_cap``. The full-precision KV
  view never exists anywhere — not in HBM, not in VMEM.
* **xla** — the A/B + CPU/interpret fallback. No Pallas, but the scales
  fold into the einsums as per-row scalars (``q . (data*s) == (q . data)*s``
  and ``att @ (data*s) == (att*s) @ data``), so this path ALSO never
  materializes a dequantized ``(S, H, TOT, D)`` cache — the int8 cache
  feeds the score dot directly.

Selection is ``MXTPU_DECODE_KERNEL=pallas|xla`` (engine kwarg > env; unset
= auto: pallas on TPU, xla elsewhere), resolved ONCE per compiled program
at build time — flipping the env between dispatches can never retrace a
live engine program. A forced ``pallas`` at a Mosaic-illegal bucket (TOT
not a 128-multiple on hardware) degrades to the xla path for that program
rather than failing the engine mid-serve; off-TPU the kernel runs in
interpret mode so the parity suite exercises the real kernel body on CPU.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .attention import _NEG_INF, _block_cap, _pick_block

__all__ = ["decode_kernel_mode", "resolve_decode_kernel",
           "dequant_attention_decode"]

DECODE_KERNELS = ("pallas", "xla")
_AUTO = ("", "auto")


def decode_kernel_mode(value=None) -> Optional[str]:
    """Resolve the decode-kernel selector: ``value`` if given, else
    ``MXTPU_DECODE_KERNEL``. Returns None (auto), 'pallas', or 'xla';
    anything else raises ``ValueError`` (never a silent fallback)."""
    raw = os.environ.get("MXTPU_DECODE_KERNEL", "") if value is None else value
    raw = str(raw).strip().lower()
    if raw in _AUTO:
        return None
    if raw not in DECODE_KERNELS:
        raise ValueError(
            f"MXTPU_DECODE_KERNEL={raw!r} (choose from {list(DECODE_KERNELS)}, "
            "or unset for auto: pallas on TPU, xla elsewhere)")
    return raw


def _legal_bucket(TOT: int) -> bool:
    """Block legality of the KV bucket under the Mosaic tiling rule
    ``_pick_block`` enforces: 128-multiples tile; sub-128 buckets are only
    legal as the whole axis (engine buckets are 32-multiples, so 32/64/96
    qualify in interpret mode; real Mosaic needs the 128-multiple)."""
    return TOT % 128 == 0 or (TOT <= 128 and TOT % 8 == 0)


def resolve_decode_kernel(mode=None, TOT: Optional[int] = None,
                          D: Optional[int] = None) -> str:
    """Concrete kernel for one compiled decode program, decided at BUILD
    time (the engine resolves its mode once per lifetime, so program-cache
    keys stay (slots, bucket, chunk) and env flips never retrace). Auto is
    pallas on TPU, xla elsewhere; a pallas request at a shape the kernel
    can't tile (bucket legality per ``_legal_bucket``, head dim > 512)
    degrades to xla for that program."""
    mode = decode_kernel_mode(mode)
    on_tpu = jax.default_backend() == "tpu"
    if mode is None:
        mode = "pallas" if on_tpu else "xla"
    if mode == "pallas" and TOT is not None:
        legal = (TOT % 128 == 0) if on_tpu else _legal_bucket(TOT)
        if not legal or (D is not None and D > 512):
            return "xla"
    return mode


# ---------------------------------------------------------------------------
# Pallas kernel: in-register dequant inside the online-softmax decode body
# ---------------------------------------------------------------------------


def _dequant_decode_kernel(lim_ref, q_ref, kd_ref, ks_ref, vd_ref, vs_ref,
                           o_ref, *, block_t: int, scale: float):
    """One (slot*head) program: stream quantized K/V tiles, dequantize
    in-register, online softmax over positions ``0..lim``. The query rides
    broadcast over 8 sublanes (a bare (1, D) row block is Mosaic-illegal,
    same trick as the flash lse), so every row of the (8, Dp) tiles
    computes the identical result and the wrapper keeps row 0."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale           # (8, Dp)
    lim = lim_ref[0, 0, 0]                             # this slot's position
    tot = kd_ref.shape[1]
    num_tb = tot // block_t

    m0 = jnp.full((8, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((8, 1), jnp.float32)
    o0 = jnp.zeros((8, q.shape[1]), jnp.float32)

    def body(tb, carry):
        m, l, o = carry
        t0 = tb * block_t
        # int8/fp8 tile + per-row f32 scale -> f32 tile, in-register only
        k_blk = kd_ref[0, pl.dslice(t0, block_t), :].astype(jnp.float32) \
            * ks_ref[0, 0, pl.dslice(t0, block_t)][:, None]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)  # (8, bt)
        cols = t0 + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= lim, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        v_blk = vd_ref[0, pl.dslice(t0, block_t), :].astype(jnp.float32) \
            * vs_ref[0, 0, pl.dslice(t0, block_t)][:, None]
        o_new = corr * o + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    # only tiles at or below the slot's position hold written rows
    num_iter = jnp.minimum(lim // block_t + 1, num_tb)
    m, l, o = lax.fori_loop(0, num_iter, body, (m0, l0, o0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _pad_last(x, dp: int):
    d = x.shape[-1]
    if dp == d:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, dp - d)))


def _decode_pallas(q, kd, ks, vd, vs, pc, scale: float, interpret: bool):
    """Kernel launch for the decode shape: q (S,H,D); kd/vd (S,H,TOT,D)
    quantized storage; ks/vs (S,H,TOT) f32 row scales; pc (S,) positions."""
    from jax.experimental import pallas as pl

    S, H, TOT, D = kd.shape
    BH = S * H
    dp = -(-D // 128) * 128
    block_t = _pick_block(TOT, _block_cap(dp))
    q8 = _pad_last(jnp.broadcast_to(q.reshape(BH, 1, D), (BH, 8, D)), dp)
    kd2 = _pad_last(kd.reshape(BH, TOT, D), dp)
    vd2 = _pad_last(vd.reshape(BH, TOT, D), dp)
    # per-row scales ride 8-sublane broadcast (Mosaic row-block tiling)
    ks2 = jnp.broadcast_to(ks.reshape(BH, 1, TOT), (BH, 8, TOT)) \
        .astype(jnp.float32)
    vs2 = jnp.broadcast_to(vs.reshape(BH, 1, TOT), (BH, 8, TOT)) \
        .astype(jnp.float32)
    lim = jnp.broadcast_to(
        jnp.repeat(pc.astype(jnp.int32), H).reshape(BH, 1, 1), (BH, 8, 128))

    kernel = functools.partial(_dequant_decode_kernel, block_t=block_t,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(BH,),
        in_specs=[
            pl.BlockSpec((1, 8, 128), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 8, dp), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, TOT, dp), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 8, TOT), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, TOT, dp), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 8, TOT), lambda b: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, dp), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, 8, dp), q.dtype),
        interpret=interpret,
    )(lim, q8, kd2, ks2, vd2, vs2)
    return out[:, 0, :D].reshape(S, H, D)


def _decode_xla(q, kd, ks, vd, vs, pc, scale: float):
    """XLA path over the quantized storage — no Pallas, but both attention
    dots run int8 x int8 -> int32 ``dot_general`` when the cache is int8
    (the same dynamic per-row activation quantization as
    ``quant.serve._int8_matmul``): the query rows quantize against the int8
    K cache for the scores, and the ``att * vscale`` rows quantize against
    the int8 V cache for the context, with the (activation x row) scales
    folded into the int32 accumulator readout. On TPU that is the MXU's
    2x-peak int8 path; on CPU it reads a quarter of the bytes — either
    way the dequantized (S, H, TOT, D) view is never materialized, which
    was the whole 0.78x regression. An fp8 cache (no int8 accumulator)
    keeps f32 dots with the scales folded in as per-row scalars
    (``q . (data*s) == (q . data)*s`` and ``att @ (data*s) == (att*s) @
    data``)."""
    TOT = kd.shape[2]
    mask = jnp.arange(TOT)[None, None, :] <= pc[:, None, None]
    if kd.dtype == jnp.int8:
        from ..quant import kv_quant
        q_q, q_s = kv_quant.quantize_rows(q, "int8")
        acc = lax.dot_general(q_q, kd, (((2,), (3,)), ((0, 1), (0, 1))),
                              preferred_element_type=jnp.int32)
        s = acc.astype(jnp.float32) * q_s[..., None] * ks * scale
        att = jax.nn.softmax(jnp.where(mask, s, _NEG_INF), axis=-1)
        # masked positions are exactly 0 in att, so they quantize to the
        # exact 0 code — the int8 context read never leaks an unwritten row
        w_q, w_s = kv_quant.quantize_rows(att * vs, "int8")
        acc2 = lax.dot_general(w_q, vd, (((2,), (2,)), ((0, 1), (0, 1))),
                               preferred_element_type=jnp.int32)
        return acc2.astype(jnp.float32) * w_s[..., None]
    s = jnp.einsum("bhd,bhtd->bht", q, kd.astype(jnp.float32)) * ks * scale
    att = jax.nn.softmax(jnp.where(mask, s, _NEG_INF), axis=-1)
    return jnp.einsum("bht,bhtd->bhd", att * vs, vd.astype(jnp.float32))


def dequant_attention_decode(q, kd, ks, vd, vs, pc, *, scale: float,
                             kernel=None, interpret: Optional[bool] = None):
    """One decode-step attention read over a quantized paged KV cache.

    ``q`` (S, H, D) working-precision queries; ``kd``/``vd`` (S, H, TOT, D)
    quantized storage (int8 or fp8); ``ks``/``vs`` (S, H, TOT) per-row f32
    scales; ``pc`` (S,) int32 per-slot positions (position ``t`` attends
    iff ``t <= pc[slot]``). Returns the (S, H, D) context in ``q``'s dtype.

    ``kernel`` picks the path ('pallas' / 'xla' / None = resolve from
    ``MXTPU_DECODE_KERNEL`` + backend); off-TPU the Pallas path runs in
    interpret mode unless ``interpret`` overrides. Both paths compute the
    identical masked softmax over the identical dequantized values — they
    differ only in float reassociation, bounded well inside the
    quantization ``roundtrip_error_bound`` (the parity suite pins this)."""
    kernel = resolve_decode_kernel(kernel, TOT=kd.shape[2], D=kd.shape[3])
    if kernel == "pallas":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return _decode_pallas(q, kd, ks, vd, vs, pc, scale, interpret)
    return _decode_xla(q, kd, ks, vd, vs, pc, scale)
