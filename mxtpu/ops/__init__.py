"""Operator library: importing this package registers every op (SURVEY.md §2.2 surface)."""

from . import registry
from .registry import OpDef, get_op, invoke, list_ops, register

# registration side effects
from . import elementwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import init_ops  # noqa: F401
from . import order  # noqa: F401
from . import linalg  # noqa: F401
from . import sequence  # noqa: F401
from . import nn  # noqa: F401
from . import random  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import quantization  # noqa: F401
from . import detection  # noqa: F401
from . import spatial  # noqa: F401
from . import rnn  # noqa: F401
from . import attention  # noqa: F401
from . import image_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
