"""Control-flow operators — foreach / while_loop / cond.

Capability parity with ``src/operator/control_flow.cc:477-536`` (the `_foreach`,
`_while_loop`, `_cond` stateful subgraph ops) and the Python surface
``python/mxnet/ndarray/contrib.py:101,196``.

Re-design: the reference captures the body as a CachedOp subgraph and hand-manages
its state/gradient plumbing (1,104 LoC). Here the body is traced straight into
``lax.scan`` / ``lax.cond`` — XLA-compilable control flow with gradients from the
scan's own vjp (no subgraph machinery):

* ``foreach``  → ``lax.scan`` over axis 0 (one compiled loop, MXU-friendly body).
* ``while_loop`` → a **bounded masked scan**: mxnet requires ``max_iterations``
  anyway, and a masked scan (inactive steps pass state through and emit zeros) is
  reverse-differentiable where ``lax.while_loop`` is not — outputs are zero-padded
  to ``max_iterations`` (the reference leaves padding undefined).
* ``cond``     → eager branch selection (gradient flows through the taken branch);
  under a jit trace the predicate is a tracer and it lowers to ``lax.cond``.

All three record ONE tape node whose replay closure re-runs the compiled loop, so
``backward()`` through an imperative foreach-RNN works like any other op.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["foreach", "while_loop", "cond"]


def _nd():
    from ..ndarray.ndarray import NDArray
    return NDArray


def _as_list(x) -> Tuple[List, bool]:
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _run_and_record(inner, explicit_handles, n_explicit_out_hint=None):
    """Execute ``inner`` once eagerly (capturing closed-over marked NDArrays the
    body reads — RNN weights etc.), then record ONE tape node whose replay swaps
    the captured handles' buffers for the vjp's tracer inputs (the same
    handle-swap discipline DataParallelTrainer uses)."""
    from .. import autograd
    from ..ndarray import ndarray as nd_core
    NDArray = _nd()
    cap: list = []
    nd_core._push_capture(cap)
    try:
        res = inner(*[h.data for h in explicit_handles])
    finally:
        nd_core._pop_capture()
    outs_nd = [NDArray(r) for r in res]
    if autograd.is_recording():
        explicit_ids = {id(h) for h in explicit_handles}
        seen: dict = {}
        for h in cap:
            if h._grad_entry is not None and id(h) not in explicit_ids:
                seen.setdefault(id(h), h)
        captured = list(seen.values())
        n_explicit = len(explicit_handles)

        def pure_fn(*raws):
            cap_raws = raws[n_explicit:]
            saved = [(h._data, h._version) for h in captured]
            try:
                for h, r in zip(captured, cap_raws):
                    h._data = r
                    h._version += 1
                return inner(*raws[:n_explicit])
            finally:
                for h, (d, v) in zip(captured, saved):
                    h._data = d
                    h._version += 1

        autograd.record_custom_node(pure_fn, list(explicit_handles) + captured,
                                    outs_nd)
    return outs_nd


def foreach(body, data, init_states, name: str = "foreach"):
    """Run ``body`` over axis-0 slices of ``data``, carrying ``states``
    (contrib.py:101). ``body(data_i, states) -> (out, new_states)``. Returns
    (stacked outputs, final states)."""
    from .. import autograd
    NDArray = _nd()
    datas, single_data = _as_list(data)
    states, single_state = _as_list(init_states)
    n_data, n_state = len(datas), len(states)
    struct: dict = {}

    def pure_fn(*raws):
        rd, rs = list(raws[:n_data]), list(raws[n_data:])

        def step(carry, xs):
            s_nd = [NDArray(c) for c in carry]
            x_nd = [NDArray(x) for x in xs]
            # keep the ambient training mode: the reference runs the subgraph in
            # the caller's train/predict mode (control_flow.cc subgraph exec)
            with autograd.pause(train_mode=autograd.is_training()):
                out, new_states = body(x_nd[0] if single_data else x_nd,
                                       s_nd[0] if single_state else s_nd)
            outs, struct["single_out"] = _as_list(out)
            ns, _ = _as_list(new_states)
            return [s.data for s in ns], [o.data for o in outs]

        final, stacked = lax.scan(step, rs, rd)
        return tuple(stacked) + tuple(final)

    outs_nd = _run_and_record(pure_fn, datas + states)
    n_out = len(outs_nd) - n_state
    out_list, state_list = outs_nd[:n_out], outs_nd[n_out:]
    outputs = out_list[0] if struct["single_out"] else out_list
    final_states = state_list[0] if single_state else state_list
    return outputs, final_states


def while_loop(cond, func, loop_vars, max_iterations: int = None):
    """Bounded while loop (contrib.py:196). ``cond(*loop_vars) -> scalar``,
    ``func(*loop_vars) -> (step_output, new_loop_vars)``. Returns
    (outputs zero-padded to max_iterations rows, final loop_vars)."""
    from .. import autograd
    NDArray = _nd()
    if max_iterations is None:
        raise ValueError("while_loop: max_iterations is required "
                         "(reference parity — outputs are statically shaped)")
    max_iterations = int(max_iterations)
    lvars, single_var = _as_list(loop_vars)
    n_vars = len(lvars)
    struct: dict = {}

    def pure_fn(*raws):
        def step(carry, _):
            vals, active = carry
            v_nd = [NDArray(v) for v in vals]
            with autograd.pause(train_mode=autograd.is_training()):
                c = cond(*v_nd)
                out, new_vars = func(*v_nd)
            c_raw = jnp.reshape(
                c.data if isinstance(c, NDArray) else jnp.asarray(c),
                ()).astype(bool) & active
            outs, struct["single_out"] = _as_list(out)
            nv, _ = _as_list(new_vars)
            new_vals = [jnp.where(c_raw, n.data.astype(v.dtype).reshape(v.shape), v)
                        for n, v in zip(nv, vals)]
            masked = [jnp.where(c_raw, o.data, jnp.zeros_like(o.data))
                      for o in outs]
            return (new_vals, c_raw), masked

        (final_vals, _), stacked = lax.scan(
            step, (list(raws), jnp.asarray(True)), None, length=max_iterations)
        return tuple(stacked) + tuple(final_vals)

    outs_nd = _run_and_record(pure_fn, lvars)
    n_out = len(outs_nd) - n_vars
    outputs = outs_nd[:n_out]
    final_states = outs_nd[n_out:]
    return list(outputs), list(final_states)


def cond(pred, then_func, else_func):
    """Conditional execution: ``pred`` is a thunk (or scalar NDArray); the chosen
    branch's thunk runs (``_cond`` op parity, control_flow.cc).

    Eager: Python branch selection (recorded ops flow normally). Inside a jit
    trace the predicate is a tracer → lowers to ``lax.cond``."""
    NDArray = _nd()
    p = pred() if callable(pred) else pred
    praw = p.data if isinstance(p, NDArray) else jnp.asarray(p)
    if isinstance(praw, jax.core.Tracer):
        struct: dict = {}

        def _branch(f):
            def run(_):
                out = f()
                outs, struct["single_out"] = _as_list(out)
                return tuple(o.data if isinstance(o, NDArray) else jnp.asarray(o)
                             for o in outs)
            return run

        res = lax.cond(jnp.reshape(praw, ()).astype(bool),
                       _branch(then_func), _branch(else_func), None)
        outs = [NDArray(r) for r in res]
        return outs[0] if struct["single_out"] else list(outs)
    take_then = bool(np.asarray(jax.device_get(praw)).reshape(()))
    return then_func() if take_then else else_func()
