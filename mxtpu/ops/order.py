"""Ordering ops — parity with ``src/operator/tensor/ordering_op-inl.h`` (topk/sort/argsort).

TPU note: XLA's sort is a bitonic network on the VPU; top-k uses ``lax.top_k`` which is
substantially cheaper than a full sort for small k.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("sort")
def _sort(data, axis: Optional[int] = -1, is_ascend: bool = True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis if axis is not None else 0)
    return out


@register("argsort", differentiable=False)
def _argsort(data, axis: Optional[int] = -1, is_ascend: bool = True, dtype="float32"):
    from ..base import dtype_np
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis if axis is not None else 0)
    return out.astype(dtype_np(dtype))


@register("topk",
          differentiable=lambda kw: kw.get("ret_typ", "indices")
          in ("value", "both"))
def _topk(data, axis: Optional[int] = -1, k: int = 1, ret_typ: str = "indices",
          is_ascend: bool = False, dtype="float32"):
    """Reference topk (ordering_op-inl.h): ret_typ ∈ {value, indices, mask, both}."""
    from ..base import dtype_np
    ax = axis if axis is not None else data.ndim - 1
    moved = jnp.moveaxis(data, ax, -1)
    src = -moved if is_ascend else moved
    vals, idx = lax.top_k(src, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idxf = jnp.moveaxis(idx, -1, ax).astype(dtype_np(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxf
    if ret_typ == "mask":
        mask = jnp.zeros_like(moved).at[
            tuple(jnp.indices(idx.shape))[:-1] + (idx,)].set(1)
        return jnp.moveaxis(mask, -1, ax)
    if ret_typ == "both":
        return vals, idxf
    raise ValueError(f"unknown ret_typ {ret_typ!r}")
