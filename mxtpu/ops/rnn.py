"""Fused RNN ops — parity with ``src/operator/rnn-inl.h`` (mode ∈ {rnn_relu, rnn_tanh,
lstm, gru}) and the cuDNN fused path (cudnn_rnn-inl.h).

One layer+direction per op call, fused over time with ``lax.scan`` — the TPU-correct
formulation: the per-step matmuls batch onto the MXU and XLA pipelines the scan; the
reference needed a hand-fused CPU kernel (rnn_impl.h) and cuDNN for the same effect.
Gate orders match the reference: LSTM [i, f, c, o]; GRU [r, z, n].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _step_rnn(act):
    def step(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b):
        (h,) = carry
        new_h = act(x_t @ i2h_w.T + i2h_b + h @ h2h_w.T + h2h_b)
        return (new_h,), new_h
    return step


def _step_lstm(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b):
    h, c = carry
    gates = x_t @ i2h_w.T + i2h_b + h @ h2h_w.T + h2h_b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    new_c = f * c + i * g
    new_h = o * jnp.tanh(new_c)
    return (new_h, new_c), new_h


def _step_gru(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b):
    (h,) = carry
    ix = x_t @ i2h_w.T + i2h_b
    ih = h @ h2h_w.T + h2h_b
    ir, iz, inn = jnp.split(ix, 3, axis=-1)
    hr, hz, hn = jnp.split(ih, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(inn + r * hn)
    new_h = (1 - z) * n + z * h
    return (new_h,), new_h


_STEPS = {
    "rnn_relu": _step_rnn(lambda x: jnp.maximum(x, 0)),
    "rnn_tanh": _step_rnn(jnp.tanh),
    "lstm": _step_lstm,
    "gru": _step_gru,
}


@register("rnn_scan", num_outputs=-1)
def _rnn_scan(data, h0, c0_or_w, *rest, mode: str = "lstm", reverse: bool = False):
    """Scan one RNN layer over time. data (T,B,I); h0 (B,H); lstm also takes c0.

    args after data,h0[,c0]: i2h_w, i2h_b, h2h_w, h2h_b.
    Returns (out(T,B,H), hT) or (out, hT, cT) for lstm.
    """
    if mode == "lstm":
        c0 = c0_or_w
        i2h_w, i2h_b, h2h_w, h2h_b = rest
        carry0 = (h0, c0)
    else:
        i2h_w, i2h_b, h2h_w, h2h_b = (c0_or_w,) + rest
        carry0 = (h0,)
    stepfn = _STEPS[mode]
    xs = jnp.flip(data, axis=0) if reverse else data

    def body(carry, x_t):
        return stepfn(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b)

    carry, outs = lax.scan(body, carry0, xs)
    if reverse:
        outs = jnp.flip(outs, axis=0)
    if mode == "lstm":
        return outs, carry[0], carry[1]
    return outs, carry[0]
