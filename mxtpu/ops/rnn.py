"""Fused RNN ops — parity with ``src/operator/rnn-inl.h`` (mode ∈ {rnn_relu, rnn_tanh,
lstm, gru}) and the cuDNN fused path (cudnn_rnn-inl.h).

One layer+direction per op call, fused over time with ``lax.scan`` — the TPU-correct
formulation: the per-step matmuls batch onto the MXU and XLA pipelines the scan; the
reference needed a hand-fused CPU kernel (rnn_impl.h) and cuDNN for the same effect.
Gate orders match the reference: LSTM [i, f, c, o]; GRU [r, z, n].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _step_rnn(act):
    def step(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b):
        (h,) = carry
        new_h = act(x_t @ i2h_w.T + i2h_b + h @ h2h_w.T + h2h_b)
        return (new_h,), new_h
    return step


def _step_lstm(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b):
    h, c = carry
    gates = x_t @ i2h_w.T + i2h_b + h @ h2h_w.T + h2h_b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    new_c = f * c + i * g
    new_h = o * jnp.tanh(new_c)
    return (new_h, new_c), new_h


def _step_gru(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b):
    (h,) = carry
    ix = x_t @ i2h_w.T + i2h_b
    ih = h @ h2h_w.T + h2h_b
    ir, iz, inn = jnp.split(ix, 3, axis=-1)
    hr, hz, hn = jnp.split(ih, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(inn + r * hn)
    new_h = (1 - z) * n + z * h
    return (new_h,), new_h


_STEPS = {
    "rnn_relu": _step_rnn(lambda x: jnp.maximum(x, 0)),
    "rnn_tanh": _step_rnn(jnp.tanh),
    "lstm": _step_lstm,
    "gru": _step_gru,
}


@register("rnn_scan", num_outputs=-1)
def _rnn_scan(data, h0, c0_or_w, *rest, mode: str = "lstm", reverse: bool = False):
    """Scan one RNN layer over time. data (T,B,I); h0 (B,H); lstm also takes c0.

    args after data,h0[,c0]: i2h_w, i2h_b, h2h_w, h2h_b.
    Returns (out(T,B,H), hT) or (out, hT, cT) for lstm.
    """
    if mode == "lstm":
        c0 = c0_or_w
        i2h_w, i2h_b, h2h_w, h2h_b = rest
        carry0 = (h0, c0)
    else:
        i2h_w, i2h_b, h2h_w, h2h_b = (c0_or_w,) + rest
        carry0 = (h0,)
    stepfn = _STEPS[mode]
    xs = jnp.flip(data, axis=0) if reverse else data

    def body(carry, x_t):
        return stepfn(carry, x_t, i2h_w, i2h_b, h2h_w, h2h_b)

    carry, outs = lax.scan(body, carry0, xs)
    if reverse:
        outs = jnp.flip(outs, axis=0)
    if mode == "lstm":
        return outs, carry[0], carry[1]
    return outs, carry[0]


# ---------------------------------------------------------------------------
# fused RNN op (src/operator/rnn.cc "RNN": cuDNN-packed parameter vector)
# ---------------------------------------------------------------------------

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _rnn_resolve(kwargs):
    from .. import autograd
    from .. import rng as rng_mod
    if kwargs.get("_training") is None:
        kwargs["_training"] = autograd.is_training()
    if kwargs.get("key") is None and kwargs.get("p", 0.0) > 0 \
            and kwargs["_training"]:
        kwargs["key"] = rng_mod.next_key()
    return kwargs


def _slice_packed(params, num_layers, input_size, h, gates, dirs):
    """Walk the reference's packed layout (python/mxnet/rnn/rnn_cell.py:600
    FusedRNNCell._slice_weights): per layer, per direction — G i2h gate
    weights then G h2h gate weights; then all biases in the same order.
    Returns weights[layer][dir] = (i2h_w (G*h, in_l), i2h_b, h2h_w, h2h_b)."""
    out = []
    p = 0

    def take(n, shape):
        nonlocal p
        seg = lax.dynamic_slice_in_dim(params, p, n).reshape(shape)
        p += n
        return seg

    for layer in range(num_layers):
        in_l = input_size if layer == 0 else dirs * h
        row = []
        for _ in range(dirs):
            i2h = take(gates * h * in_l, (gates * h, in_l))
            h2h = take(gates * h * h, (gates * h, h))
            row.append([i2h, None, h2h, None])
        out.append(row)
    for layer in range(num_layers):
        for d in range(dirs):
            out[layer][d][1] = take(gates * h, (gates * h,))
            out[layer][d][3] = take(gates * h, (gates * h,))
    return out


@register("RNN", num_outputs=-1, resolve_kwargs=_rnn_resolve)
def _rnn_fused(data, parameters, state, state_cell=None, *,
               state_size: int, num_layers: int, mode: str = "lstm",
               bidirectional: bool = False, p: float = 0.0,
               state_outputs: bool = False, key=None,
               _training: Optional[bool] = None):
    """The reference's fused multi-layer RNN op (rnn-inl.h; parameter vector
    packed in the FusedRNNCell/cuDNN layout, rnn_cell.py:600). data (T,N,I);
    state (layers*dirs, N, H); lstm also takes state_cell. Dropout ``p``
    applies BETWEEN layers in training, like cuDNN. Returns output
    (T, N, H*dirs) (+ hT[, cT] when state_outputs).

    TPU formulation: the packed vector is sliced into per-layer/direction
    gate blocks once at trace time, then each layer runs the same lax.scan
    kernel as ``rnn_scan`` — no workspace management, no cuDNN descriptor
    zoo (GetRNNWorkspaceSize et al. collapse)."""
    h = state_size
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    T, N, input_size = data.shape
    weights = _slice_packed(parameters, num_layers, input_size, h, gates, dirs)

    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        dir_outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            i2h_w, i2h_b, h2h_w, h2h_b = weights[layer][d]
            # one layer+direction = one rnn_scan call (the registered
            # single-layer kernel owns the scan/flip/carry logic)
            if mode == "lstm":
                outs, hT, cT = _rnn_scan(x, state[idx], state_cell[idx],
                                         i2h_w, i2h_b, h2h_w, h2h_b,
                                         mode=mode, reverse=d == 1)
                c_outs.append(cT)
            else:
                outs, hT = _rnn_scan(x, state[idx], i2h_w, i2h_b, h2h_w,
                                     h2h_b, mode=mode, reverse=d == 1)
            dir_outs.append(outs)
            h_outs.append(hT)
        x = dir_outs[0] if dirs == 1 else jnp.concatenate(dir_outs, axis=-1)
        if p > 0.0 and _training and key is not None and \
                layer < num_layers - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), 0.0).astype(x.dtype)
    if not state_outputs:
        return x
    hT = jnp.stack(h_outs)
    if mode == "lstm":
        return x, hT, jnp.stack(c_outs)
    return x, hT
