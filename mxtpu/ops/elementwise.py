"""Elementwise unary/binary/scalar/broadcast op families.

Parity target: ``src/operator/tensor/`` elemwise families (mshadow_op.h functors,
elemwise_unary_op_basic.cc, elemwise_binary_broadcast_op_*.cc — SURVEY.md §2.2). The
reference generates ~100 registrations from C++ functor templates plus hand-written
``_backward_*`` twins; here each op is one jnp/lax expression and gradients come from
``jax.vjp``. Broadcast semantics: the reference distinguishes ``elemwise_add`` (same
shape) from ``broadcast_add`` (numpy broadcasting); jnp broadcasts everywhere, so the
``broadcast_*``/``_scalar`` names are registered as aliases of one implementation —
behavior is a strict superset.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# unary math
# ---------------------------------------------------------------------------

_UNARY = {
    # name: (fn, extra aliases)
    "abs": (jnp.abs, ()),
    "sign": (jnp.sign, ()),
    "ceil": (jnp.ceil, ()),
    "floor": (jnp.floor, ()),
    "round": (jnp.round, ()),
    "rint": (jnp.rint, ()),
    "trunc": (jnp.trunc, ()),
    "fix": (jnp.trunc, ()),
    "exp": (jnp.exp, ()),
    "expm1": (jnp.expm1, ()),
    "log": (jnp.log, ()),
    "log1p": (jnp.log1p, ()),
    "log2": (jnp.log2, ()),
    "log10": (jnp.log10, ()),
    "sqrt": (jnp.sqrt, ()),
    "rsqrt": (lax.rsqrt, ()),
    "cbrt": (jnp.cbrt, ()),
    "square": (jnp.square, ()),
    "reciprocal": (jnp.reciprocal, ()),
    "negative": (jnp.negative, ("neg",)),
    "sin": (jnp.sin, ()),
    "cos": (jnp.cos, ()),
    "tan": (jnp.tan, ()),
    "arcsin": (jnp.arcsin, ()),
    "arccos": (jnp.arccos, ()),
    "arctan": (jnp.arctan, ()),
    "sinh": (jnp.sinh, ()),
    "cosh": (jnp.cosh, ()),
    "tanh": (jnp.tanh, ()),
    "arcsinh": (jnp.arcsinh, ()),
    "arccosh": (jnp.arccosh, ()),
    "arctanh": (jnp.arctanh, ()),
    "degrees": (jnp.degrees, ()),
    "radians": (jnp.radians, ()),
    "erf": (jax.scipy.special.erf, ()),
    "erfinv": (jax.scipy.special.erfinv, ()),
    "gammaln": (jax.scipy.special.gammaln, ()),
    "logical_not": (jnp.logical_not, ()),
    "isnan": (jnp.isnan, ()),
    "isinf": (jnp.isinf, ()),
    "isfinite": (jnp.isfinite, ()),
}

for _name, (_fn, _aliases) in _UNARY.items():
    register(_name, aliases=_aliases, differentiable=_name not in
             ("sign", "ceil", "floor", "round", "rint", "trunc", "fix",
              "logical_not", "isnan", "isinf", "isfinite"))(
        (lambda f: lambda data: f(data))(_fn))


@register("gamma")
def _gamma(data):
    """Γ(x) — reference op ``gamma`` (mshadow_op.h)."""
    return jnp.exp(jax.scipy.special.gammaln(data)) * jnp.sign(
        jnp.where(jnp.floor(data) == data, 1.0, _gamma_sign(data)))


def _gamma_sign(x):
    # reflection sign for negative non-integer arguments
    return jnp.where(x > 0, 1.0, jnp.sign(jnp.sin(jnp.pi * x)))


@register("rcbrt")
def _rcbrt(data):
    return 1.0 / jnp.cbrt(data)


@register("relu", aliases=("ReLU",))
def _relu(data):
    return jnp.maximum(data, 0)


@register("sigmoid")
def _sigmoid(data):
    return jax.nn.sigmoid(data)


@register("hard_sigmoid")
def _hard_sigmoid(data, alpha: float = 0.2, beta: float = 0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("softsign")
def _softsign(data):
    return data / (1 + jnp.abs(data))


@register("softrelu")
def _softrelu(data):
    """softplus — reference ``softrelu`` (mshadow_op.h)."""
    return jax.nn.softplus(data)


@register("clip")
def _clip(data, a_min: float = None, a_max: float = None):
    return jnp.clip(data, a_min, a_max)


# ---------------------------------------------------------------------------
# binary (broadcasting) + scalar variants
# ---------------------------------------------------------------------------

_BINARY = {
    "add": (jnp.add, ("elemwise_add", "broadcast_add", "broadcast_plus", "plus")),
    "subtract": (jnp.subtract, ("elemwise_sub", "broadcast_sub", "broadcast_minus", "minus")),
    "multiply": (jnp.multiply, ("elemwise_mul", "broadcast_mul", "mul")),
    "divide": (jnp.divide, ("elemwise_div", "broadcast_div", "div")),
    "mod": (jnp.mod, ("broadcast_mod",)),
    "power": (jnp.power, ("broadcast_power", "pow")),
    "maximum": (jnp.maximum, ("broadcast_maximum",)),
    "minimum": (jnp.minimum, ("broadcast_minimum",)),
    "hypot": (jnp.hypot, ("broadcast_hypot",)),
    "arctan2": (jnp.arctan2, ("broadcast_arctan2",)),
}

for _name, (_fn, _aliases) in _BINARY.items():
    register(_name, aliases=_aliases)((lambda f: lambda lhs, rhs: f(lhs, rhs))(_fn))

_COMPARE = {
    "equal": (jnp.equal, ("broadcast_equal",)),
    "not_equal": (jnp.not_equal, ("broadcast_not_equal",)),
    "greater": (jnp.greater, ("broadcast_greater",)),
    "greater_equal": (jnp.greater_equal, ("broadcast_greater_equal",)),
    "lesser": (jnp.less, ("broadcast_lesser", "less")),
    "lesser_equal": (jnp.less_equal, ("broadcast_lesser_equal", "less_equal")),
    "logical_and": (jnp.logical_and, ("broadcast_logical_and",)),
    "logical_or": (jnp.logical_or, ("broadcast_logical_or",)),
    "logical_xor": (jnp.logical_xor, ("broadcast_logical_xor",)),
}

for _name, (_fn, _aliases) in _COMPARE.items():
    # comparisons produce same-dtype 0/1 in the reference, not bool
    register(_name, aliases=_aliases, differentiable=False)(
        (lambda f: lambda lhs, rhs: f(lhs, rhs).astype(jnp.result_type(lhs, rhs)))(_fn))


@register("rsubtract", aliases=("rminus",))
def _rsub(lhs, rhs):
    return jnp.subtract(rhs, lhs)


@register("rdivide", aliases=("rdiv",))
def _rdiv(lhs, rhs):
    return jnp.divide(rhs, lhs)


@register("rpower", aliases=("rpow",))
def _rpow(lhs, rhs):
    return jnp.power(rhs, lhs)


@register("rmod")
def _rmod(lhs, rhs):
    return jnp.mod(rhs, lhs)


@register("smooth_l1")
def _smooth_l1(data, scalar: float = 1.0):
    """Huber-style loss kernel (reference smooth_l1, used by detection heads)."""
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * data * data, a - 0.5 / s2)


# scalar-operand internal ops (reference _plus_scalar family, elemwise_binary_scalar_op*
# — the symbolic frontend encodes the scalar as an attr, so these must be real ops)
@register("_plus_scalar")
def _plus_scalar(data, scalar: float = 0.0):
    return data + scalar


@register("_minus_scalar")
def _minus_scalar(data, scalar: float = 0.0):
    return data - scalar


@register("_rminus_scalar")
def _rminus_scalar(data, scalar: float = 0.0):
    return scalar - data


@register("_mul_scalar")
def _mul_scalar(data, scalar: float = 1.0):
    return data * scalar


@register("_div_scalar")
def _div_scalar(data, scalar: float = 1.0):
    return data / scalar


@register("_rdiv_scalar")
def _rdiv_scalar(data, scalar: float = 1.0):
    return scalar / data


@register("_power_scalar")
def _power_scalar(data, scalar: float = 1.0):
    return jnp.power(data, scalar)


@register("_rpower_scalar")
def _rpower_scalar(data, scalar: float = 1.0):
    return jnp.power(scalar, data)


# scalar comparisons (reference _greater_scalar family; 0/1 floats like the
# binary comparison ops) — the symbolic frontend lowers `sym > c` to these
def _cmp_scalar(name, fn):
    @register(name, differentiable=False)
    def op(data, scalar: float = 0.0):
        return fn(data, scalar).astype(data.dtype)
    op.__name__ = name
    return op


_equal_scalar = _cmp_scalar("_equal_scalar", jnp.equal)
_not_equal_scalar = _cmp_scalar("_not_equal_scalar", jnp.not_equal)
_greater_scalar = _cmp_scalar("_greater_scalar", jnp.greater)
_greater_equal_scalar = _cmp_scalar("_greater_equal_scalar", jnp.greater_equal)
_lesser_scalar = _cmp_scalar("_lesser_scalar", jnp.less)
_lesser_equal_scalar = _cmp_scalar("_lesser_equal_scalar", jnp.less_equal)


# scalar-overload variants the reference registers as internal ops
# (elemwise_binary_scalar_op*.cc; the nd frontend lowers `x % 2` etc. here)
@register("_maximum_scalar", aliases=("_MaximumScalar",))
def _maximum_scalar(data, scalar: float = 0.0):
    return jnp.maximum(data, scalar)


@register("_minimum_scalar", aliases=("_MinimumScalar",))
def _minimum_scalar(data, scalar: float = 0.0):
    return jnp.minimum(data, scalar)


@register("_mod_scalar", aliases=("_ModScalar",))
def _mod_scalar(data, scalar: float = 1.0):
    return jnp.mod(data, scalar)


@register("_rmod_scalar", aliases=("_RModScalar",))
def _rmod_scalar(data, scalar: float = 1.0):
    return jnp.mod(scalar, data)


@register("_hypot_scalar", aliases=("_HypotScalar",))
def _hypot_scalar(data, scalar: float = 0.0):
    return jnp.hypot(data, scalar)


@register("_logical_and_scalar", differentiable=False)
def _logical_and_scalar(data, scalar: float = 0.0):
    return jnp.logical_and(data != 0, bool(scalar)).astype(data.dtype)


@register("_logical_or_scalar", differentiable=False)
def _logical_or_scalar(data, scalar: float = 0.0):
    return jnp.logical_or(data != 0, bool(scalar)).astype(data.dtype)


@register("_logical_xor_scalar", differentiable=False)
def _logical_xor_scalar(data, scalar: float = 0.0):
    return jnp.logical_xor(data != 0, bool(scalar)).astype(data.dtype)


@register("_grad_add")
def _grad_add(lhs, rhs):
    """Gradient-accumulation add (elemwise_op_common; identical math to
    elemwise_add — a separate name so grad_req='add' graphs serialize)."""
    return lhs + rhs


@register("add_n", aliases=("ElementWiseSum", "_sum"))
def _add_n_op(*args):
    """Sum of N arrays in one op (src/operator/tensor/elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("_square_sum", differentiable=True)
def _square_sum(data, axis=None, keepdims: bool = False):
    """Fused square+sum (src/operator/tensor/square_sum.cc — the rsp-grad
    norm helper); one fusion either way under XLA."""
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    return jnp.sum(data * data, axis=ax, keepdims=keepdims)
