"""Linear-algebra ops — parity with ``src/operator/tensor/la_op.{h,cc}`` (LAPACK wrappers).

The reference wraps LAPACK/cuSOLVER behind ``linalg_*`` ops; here they are
jax.numpy.linalg / lax.linalg calls, which XLA lowers to MXU-friendly blocked kernels on
TPU. Registered under the ``linalg`` namespace (``mx.nd.linalg.*``).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

NS = "linalg"


@register("gemm", namespace=NS)
def _gemm(A, B, C, transpose_a: bool = False, transpose_b: bool = False,
          alpha: float = 1.0, beta: float = 1.0, axis: int = -2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("gemm2", namespace=NS)
def _gemm2(A, B, transpose_a: bool = False, transpose_b: bool = False, alpha: float = 1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("potrf", namespace=NS)
def _potrf(A):
    """Cholesky factor L with A = L Lᵀ (la_op.cc linalg_potrf)."""
    return jnp.linalg.cholesky(A)


@register("potri", namespace=NS)
def _potri(A):
    """Inverse from Cholesky factor: given L, compute (L Lᵀ)⁻¹."""
    ident = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = lax.linalg.triangular_solve(A, ident, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("trsm", namespace=NS)
def _trsm(A, B, transpose: bool = False, rightside: bool = False, lower: bool = True,
          alpha: float = 1.0):
    out = lax.linalg.triangular_solve(A, alpha * B, left_side=not rightside,
                                      lower=lower, transpose_a=transpose)
    return out


@register("trmm", namespace=NS)
def _trmm(A, B, transpose: bool = False, rightside: bool = False, lower: bool = True,
          alpha: float = 1.0):
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register("syrk", namespace=NS)
def _syrk(A, transpose: bool = False, alpha: float = 1.0):
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("sumlogdiag", namespace=NS)
def _sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("extractdiag", namespace=NS)
def _extractdiag(A, offset: int = 0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("makediag", namespace=NS)
def _makediag(A, offset: int = 0):
    if offset == 0:
        return jnp.apply_along_axis(jnp.diag, -1, A) if A.ndim > 1 else jnp.diag(A)
    n = A.shape[-1] + abs(offset)
    base = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
    return base.at[..., r, c].set(A)


@register("extracttrian", namespace=NS)
def _extracttrian(A, offset: int = 0, lower: bool = True):
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register("maketrian", namespace=NS)
def _maketrian(A, offset: int = 0, lower: bool = True):
    m = A.shape[-1]
    # solve n(n+1)/2 (+ offset corrections) ≈ m for n
    import math
    n = int((math.isqrt(8 * m + 1) - 1) // 2) + abs(offset)
    rows, cols = (jnp.tril_indices(n, k=offset) if lower else jnp.triu_indices(n, k=offset))
    base = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    return base.at[..., rows, cols].set(A)


@register("inverse", namespace=NS)
def _inverse(A):
    return jnp.linalg.inv(A)


@register("det", namespace=NS)
def _det(A):
    return jnp.linalg.det(A)


@register("slogdet", namespace=NS, num_outputs=2)
def _slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register("svd", namespace=NS, num_outputs=3)
def _svd(A):
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return u, s, vt


@register("eigh", namespace=NS, num_outputs=2)
def _eigh(A):
    w, v = jnp.linalg.eigh(A)
    return w, v


@register("qr", namespace=NS, num_outputs=2)
def _qr(A):
    q, r = jnp.linalg.qr(A)
    return q, r


@register("gelqf", namespace=NS, num_outputs=2)
def _gelqf(A):
    """LQ factorization A = L·Q with row-orthonormal Q (x, y) and lower-
    triangular L (x, x); outputs (Q, L) (reference la_op.cc:506 _linalg_gelqf).
    Computed as the transpose of QR on Aᵀ — one MXU-friendly factorization."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


@register("syevd", namespace=NS, num_outputs=2)
def _syevd(A):
    """Symmetric eigendecomposition A = Uᵀ·diag(L)·U — ROWS of U are the
    eigenvectors (reference la_op.cc _linalg_syevd convention; jnp.linalg.eigh
    returns column eigenvectors, so U is its transpose). Outputs (U, L)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


# reference root-level names (la_op.cc add_alias "linalg_gelqf" etc.)
from .registry import alias as _alias  # noqa: E402
for _n in ("gelqf", "syevd", "gemm", "gemm2", "potrf", "potri", "trsm", "trmm",
           "syrk", "sumlogdiag", "extractdiag", "makediag", "extracttrian",
           "maketrian", "inverse", "det", "slogdet"):
    _alias(f"linalg.{_n}", f"linalg_{_n}")
