"""Contrib ops — parity targets from ``src/operator/contrib/`` (SURVEY.md §2.2):
ctc_loss, bilinear resize, adaptive avg pooling, ROIAlign, box ops/NMS, count_sketch.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NS = "contrib"
NEG = -1e10


@register("ctc_loss", namespace=NS, aliases=("CTCLoss",))
def _ctc_loss(pred, label, pred_lengths, label_lengths):
    """CTC negative log-likelihood (contrib ctc_loss.cc parity).

    pred: (T, N, C) activations (softmax applied internally, matching the reference);
    label: (N, L) int labels with blank=0 reserved; lengths: (N,) ints.
    Standard log-alpha recursion over ``lax.scan`` — static shapes, TPU-friendly
    (the reference binds warp-ctc / a hand-written DP kernel, ctc_include/).
    """
    T, N, C = pred.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(pred, axis=-1)
    lab = label.astype(jnp.int32)
    ext = jnp.zeros((N, 2 * L + 1), dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    lab_len = label_lengths.astype(jnp.int32)
    seq_len = pred_lengths.astype(jnp.int32)
    ext_len = 2 * lab_len + 1
    S = 2 * L + 1
    pos = jnp.arange(S)[None, :]

    emit0 = jnp.take_along_axis(logp[0], ext, axis=1)
    alpha0 = jnp.where(pos < 2, emit0, NEG)

    def step(alpha, t):
        emit = jnp.take_along_axis(logp[t], ext, axis=1)  # (N, S)
        a1 = alpha
        a2 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=NEG)
        a3 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=NEG)
        same = jnp.pad(ext[:, :-2] == ext[:, 2:], ((0, 0), (2, 0)),
                       constant_values=True)
        a3 = jnp.where((ext == 0) | same, NEG, a3)
        m = jnp.maximum(jnp.maximum(a1, a2), a3)
        new = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m) + jnp.exp(a3 - m)) + emit
        new = jnp.where(t < seq_len[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    last1 = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    m = jnp.maximum(last1, last2)
    return -(m + jnp.log(jnp.exp(last1 - m) + jnp.exp(last2 - m)))


@register("BilinearResize2D", namespace=NS, aliases=("bilinear_resize_2d",))
def _bilinear_resize(data, height: int = 1, width: int = 1):
    """contrib bilinear_resize.cc — NCHW bilinear interpolation via jax.image."""
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, height, width), method="linear")


@register("AdaptiveAvgPooling2D", namespace=NS, aliases=("adaptive_avg_pooling",))
def _adaptive_avg_pool(data, output_size=(1, 1)):
    """contrib adaptive_avg_pooling.cc — pool to a fixed output grid."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    n, c, h, w = data.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        return data.reshape(n, c, oh, h // oh, ow, w // ow).mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


@register("ROIAlign", namespace=NS, aliases=("roi_align",))
def _roi_align(data, rois, pooled_size=(7, 7), spatial_scale: float = 1.0,
               sample_ratio: int = 2):
    """contrib roi_align.cc — bilinear-sampled ROI pooling (NCHW, rois [K,5])."""
    if isinstance(pooled_size, int):
        pooled_size = (pooled_size, pooled_size)
    ph, pw = pooled_size
    n, c, h, w = data.shape
    sr = max(sample_ratio, 1)

    def one_roi(roi):
        batch = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        # sample sr×sr points per bin, bilinear each
        iy = jnp.arange(ph)[:, None, None, None]
        ix = jnp.arange(pw)[None, :, None, None]
        sy = jnp.arange(sr)[None, None, :, None]
        sx = jnp.arange(sr)[None, None, None, :]
        y = y1 + (iy + (sy + 0.5) / sr) * bin_h
        x = x1 + (ix + (sx + 0.5) / sr) * bin_w
        y = jnp.clip(y, 0, h - 1)
        x = jnp.clip(x, 0, w - 1)
        y0, x0 = jnp.floor(y).astype(jnp.int32), jnp.floor(x).astype(jnp.int32)
        y1i, x1i = jnp.minimum(y0 + 1, h - 1), jnp.minimum(x0 + 1, w - 1)
        wy, wx = y - y0, x - x0
        img = data[batch]  # (C, H, W)
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1i]
        v10 = img[:, y1i, x0]
        v11 = img[:, y1i, x1i]
        val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx)
        return val.mean(axis=(-1, -2))  # average samples → (C, ph, pw)

    return jax.vmap(one_roi)(rois)


@register("box_iou", namespace=NS)
def _box_iou(lhs, rhs, format: str = "corner"):
    """contrib bounding_box.cc box_iou: pairwise IoU, corner format (x1,y1,x2,y2)."""
    if format == "center":
        def corner(b):
            cx, cy, bw, bh = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
            return jnp.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2], -1)
        lhs, rhs = corner(lhs), corner(rhs)
    a = lhs[..., :, None, :]
    b = rhs[..., None, :, :]
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    inter = jnp.prod(jnp.maximum(br - tl, 0), axis=-1)
    area_a = jnp.prod(a[..., 2:] - a[..., :2], axis=-1)
    area_b = jnp.prod(b[..., 2:] - b[..., :2], axis=-1)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("box_nms", namespace=NS, differentiable=False)
def _box_nms(data, overlap_thresh: float = 0.5, valid_thresh: float = 0.0,
             topk: int = -1, coord_start: int = 2, score_index: int = 1,
             id_index: int = -1, force_suppress: bool = False,
             in_format: str = "corner", out_format: str = "corner"):
    """contrib bounding_box.cc box_nms — greedy NMS, static-shape (TPU) formulation.

    Suppressed entries get score -1 (reference convention); output order = by score.
    """
    boxes = data[..., coord_start:coord_start + 4]
    scores = data[..., score_index]
    ids = data[..., id_index] if id_index >= 0 else None

    def nms_one(boxes, scores, ids):
        n = boxes.shape[0]
        order = jnp.argsort(-scores)
        boxes_s = boxes[order]
        scores_s = scores[order]
        iou = _box_iou(boxes_s, boxes_s, format=in_format)
        if ids is not None and not force_suppress:
            same_cls = ids[order][:, None] == ids[order][None, :]
            iou = jnp.where(same_cls, iou, 0.0)
        valid = scores_s > valid_thresh

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) & keep[i]
            return keep & ~sup

        keep = lax.fori_loop(0, n, body, valid)
        new_scores = jnp.where(keep, scores_s, -1.0)
        out = data[order] if ids is None else data[order]
        out = out.at[..., score_index].set(new_scores)
        return out

    if data.ndim == 2:
        return nms_one(boxes, scores, ids)
    return jax.vmap(nms_one)(boxes, scores, ids)


@register("count_sketch", namespace=NS)
def _count_sketch(data, h, s, out_dim: int = 0):
    """contrib count_sketch.cc — random projection sketch."""
    idx = h.astype(jnp.int32)
    signed = data * s
    out = jnp.zeros(data.shape[:-1] + (out_dim,), dtype=data.dtype)
    return out.at[..., idx].add(signed)


@register("getnnz", namespace=NS, differentiable=False)
def _getnnz(data, axis=None):
    return jnp.sum((data != 0).astype(jnp.int32), axis=axis)


@register("quadratic", namespace=NS)
def _quadratic(data, a: float = 0.0, b: float = 0.0, c: float = 0.0):
    """contrib quadratic_op (the reference's custom-op tutorial op,
    src/operator/contrib/quadratic_op-inl.h): a*x^2 + b*x + c."""
    return a * data * data + b * data + c


@register("bipartite_matching", namespace=NS, num_outputs=2,
          differentiable=False, aliases=("_contrib_bipartite_matching",))
def _bipartite_matching(data, threshold: float = 0.0, is_ascend: bool = False,
                        topk: int = -1):
    """Greedy bipartite matching on a score matrix (..., N, M)
    (src/operator/contrib/bounding_box.cc:147 _contrib_bipartite_matching).

    Walks (row, col) pairs in score order, assigning each pair whose row and
    column are both unmatched; stops at the first below-threshold score with
    free slots, or past ``topk`` matches (the reference kernel's exact stop
    conditions, bounding_box-inl.h:721). Returns (row_match, col_match):
    matched column index per row / row index per column, -1 when unmatched.
    The sequential greedy scan runs as one ``lax.fori_loop`` per batch item
    (vmapped) — static shapes, no host sync.
    """
    shape = data.shape
    N, M = shape[-2], shape[-1]
    flat = data.reshape((-1, N * M))

    def one(scores):
        order = jnp.argsort(jnp.where(is_ascend, scores, -scores),
                            stable=True)
        sorted_scores = scores[order]

        def body(j, st):
            rmark, cmark, count, active = st
            idx = order[j]
            r, c = idx // M, idx % M
            sc = sorted_scores[j]
            free = (rmark[r] < 0) & (cmark[c] < 0) & active
            ok = jnp.where(is_ascend, sc < threshold, sc > threshold)
            do = free & ok
            rmark = rmark.at[r].set(jnp.where(do, c, rmark[r]))
            cmark = cmark.at[c].set(jnp.where(do, r, cmark[c]))
            count = count + do.astype(jnp.int32)
            active = active & ~(free & ~ok)          # bad score on free pair
            if topk > 0:
                # strict topk (documented contract; the reference kernel's
                # assign-then-check allows topk+1 — an upstream off-by-one we
                # do not reproduce)
                active = active & (count < topk)
            return rmark, cmark, count, active

        rmark = jnp.full((N,), -1, jnp.int32)
        cmark = jnp.full((M,), -1, jnp.int32)
        rmark, cmark, _, _ = jax.lax.fori_loop(
            0, N * M, body, (rmark, cmark, jnp.int32(0), jnp.bool_(True)))
        return rmark.astype(data.dtype), cmark.astype(data.dtype)

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(shape[:-2] + (N,)),
            cols.reshape(shape[:-2] + (M,)))
