"""Operator registry — the NNVM-registry equivalent, collapsed to its useful core.

The reference registers ~205 ops via ``NNVM_REGISTER_OP`` with attr functors
(``FCompute``/``FInferShape``/``FGradient``/…, ``include/mxnet/op_attr_types.h``) because its
executor needs shape/type inference, storage dispatch, and hand-written gradients as separate
graph passes. On this stack a registered op is just a **pure JAX-traceable function**:

* shape/dtype inference  → free from jax tracing (``jax.eval_shape``),
* gradients              → free from ``jax.vjp`` (no ``FGradient``/``_backward_*`` twins),
* kernel dispatch        → XLA (with Pallas overrides for hot ops),
* async scheduling       → JAX's dispatch (no dependency engine).

What we keep from the registry idea: a **name → op table** (drives ``mx.nd.*`` wrapper
generation and alias parity with the reference op names), per-op metadata (number of
outputs, differentiability), and an imperative ``invoke`` entry point that unwraps
``NDArray`` handles, runs the function, wraps results, and notifies the autograd tape —
the collapsed analogue of ``MXImperativeInvokeEx → Imperative::Invoke``
(src/c_api/c_api_ndarray.cc:81-143, src/imperative/imperative.cc:87).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["OpDef", "register", "get_op", "list_ops", "invoke", "alias"]


class OpDef:
    __slots__ = ("name", "fn", "num_outputs", "differentiable", "aliases", "doc",
                 "namespace", "resolve_kwargs")

    def __init__(self, name: str, fn: Callable, num_outputs: int = 1,
                 differentiable: bool = True, aliases: Sequence[str] = (),
                 namespace: str = "", resolve_kwargs: Optional[Callable] = None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.aliases = tuple(aliases)
        self.doc = fn.__doc__
        self.namespace = namespace  # "" (nd root), "linalg", "random", "contrib", "image"
        # Ops with implicit state (RNG keys, training flag) resolve it to concrete
        # kwargs at invoke time so the recorded tape closure replays identically
        # under jax.vjp (the reference has no replay — its backward kernels read
        # saved state; here determinism must be captured in the closure).
        self.resolve_kwargs = resolve_kwargs

    def __repr__(self):
        return f"OpDef({self.name})"


_OPS: Dict[str, OpDef] = {}

# the op sub-namespaces both frontends (mx.nd.* and mx.sym.*) expose — one
# list so the two surfaces cannot drift
OP_NAMESPACES = ("linalg", "random", "contrib", "image")


def register(name: Optional[str] = None, *, num_outputs: int = 1,
             differentiable: bool = True, aliases: Sequence[str] = (),
             namespace: str = "", resolve_kwargs: Optional[Callable] = None):
    """Register a pure JAX function as a framework op.

    The function receives raw ``jax.Array``/scalar positional inputs plus keyword attrs
    and must be jit-traceable (static attrs only in kwargs). ``num_outputs`` may be -1
    for ops whose output count depends on attrs (e.g. ``split``). ``differentiable``
    may be a callable ``kwargs -> bool`` for ops whose output kind depends on attrs
    (topk's value/both outputs carry a gradient, its indices/mask outputs don't —
    reference ``_backward_topk`` covers kReturnValue and kReturnBoth,
    ordering_op.cc:74).
    """

    def _wrap(fn: Callable):
        opname = name or fn.__name__
        op = OpDef(opname, fn, num_outputs, differentiable, aliases, namespace,
                   resolve_kwargs)
        key = f"{namespace}.{opname}" if namespace else opname
        if key in _OPS:
            raise ValueError(f"duplicate op registration: {key}")
        _OPS[key] = op
        for a in aliases:
            akey = f"{namespace}.{a}" if namespace else a
            _OPS.setdefault(akey, op)
        return fn

    return _wrap


def alias(existing: str, *names: str, namespace: str = ""):
    """Register extra reference-parity names for an already-registered op."""
    op = get_op(existing)
    for n in names:
        key = f"{namespace}.{n}" if namespace else n
        _OPS.setdefault(key, op)


def get_op(name: str) -> OpDef:
    if name not in _OPS:
        raise KeyError(f"op {name!r} not registered")
    return _OPS[name]


def describe(name: str) -> dict:
    """Typed op-config reflection — the dmlc::Parameter equivalent
    (reference: every op's Param struct self-describes fields/defaults for
    doc generation, parameter.h DMLC_DECLARE_FIELD). Returns
    ``{name, doc, inputs, attrs: [{name, default, annotation}]}``
    introspected from the registered function's signature."""
    import inspect
    op = get_op(name)
    sig = inspect.signature(op.fn)
    inputs, attrs = [], []
    for pname, p in sig.parameters.items():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            inputs.append({"name": f"*{pname}", "variadic": True})
        elif p.default is inspect.Parameter.empty and \
                p.kind != inspect.Parameter.VAR_KEYWORD:
            inputs.append({"name": pname, "variadic": False})
        elif p.kind != inspect.Parameter.VAR_KEYWORD:
            ann = None if p.annotation is inspect.Parameter.empty else (
                getattr(p.annotation, "__name__", None) or str(p.annotation))
            attrs.append({"name": pname, "default": p.default,
                          "annotation": ann})
    return {"name": op.name, "doc": op.doc, "num_outputs": op.num_outputs,
            "inputs": inputs, "attrs": attrs, "aliases": list(op.aliases)}


def op_doc(name: str) -> str:
    """Auto-generated docstring (MXSymbolGetAtomicSymbolInfo-style doc
    rendering): summary + a Parameters section from the signature."""
    info = describe(name)
    lines = [info["doc"].strip() if info["doc"] else f"{info['name']} op.", ""]
    if info["inputs"]:
        lines += ["Inputs: " + ", ".join(i["name"] for i in info["inputs"]), ""]
    if info["attrs"]:
        lines += ["Parameters", "----------"]
        for a in info["attrs"]:
            t = a["annotation"] or type(a["default"]).__name__
            lines.append(f"{a['name']} : {t}, default {a['default']!r}")
    return "\n".join(lines)


def list_ops(namespace: Optional[str] = None) -> List[str]:
    if namespace is None:
        return sorted(_OPS)
    prefix = f"{namespace}." if namespace else ""
    out = []
    for k in _OPS:
        if namespace == "" and "." not in k:
            out.append(k)
        elif prefix and k.startswith(prefix):
            out.append(k[len(prefix):])
    return sorted(out)


# ---------------------------------------------------------------------------
# imperative invoke
# ---------------------------------------------------------------------------

def invoke(op: OpDef, *args, out=None, **kwargs):
    """Run an op imperatively on NDArray/scalar inputs.

    Collapsed version of the reference call stack (SURVEY.md §3.1): no SetShapeType /
    DispatchMode / engine push — JAX traces, compiles (op-by-op eager → XLA), and
    schedules asynchronously. Autograd recording mirrors ``Imperative::RecordOp``
    (src/imperative/imperative.cc:183): if the thread-local tape is live, the op, its
    NDArray inputs, and the produced outputs are appended so ``backward()`` can replay
    VJPs.
    """
    from ..ndarray.ndarray import NDArray, _wrap_out

    if op.resolve_kwargs is not None:
        kwargs = op.resolve_kwargs(dict(kwargs))

    raw = [a.data if isinstance(a, NDArray) else a for a in args]
    raw_kwargs = {k: (v.data if isinstance(v, NDArray) else v)
                  for k, v in kwargs.items()}
    result = op.fn(*raw, **raw_kwargs)

    multi = isinstance(result, (tuple, list))
    outs = [_wrap_out(r) for r in result] if multi else [_wrap_out(result)]

    if out is not None:
        # reference in-place `out=` convention (mx.nd op out= kwarg): overwrite the
        # destination handles' buffers; the destinations become the op outputs so a
        # live tape records onto the handles the caller keeps.
        targets = out if isinstance(out, (tuple, list)) else [out]
        for t, o in zip(targets, outs):
            t._set_data(o._data)
        outs = list(targets)

    from .. import autograd
    differentiable = (op.differentiable(kwargs) if callable(op.differentiable)
                      else op.differentiable)
    if autograd.is_recording() and differentiable:
        # positional NDArrays by index, kwarg NDArrays by name — both become tape
        # inputs so gradients flow to (e.g.) `length=` tensors as well
        nd_in = [(i, a) for i, a in enumerate(args) if isinstance(a, NDArray)]
        nd_in += [(k, v) for k, v in kwargs.items() if isinstance(v, NDArray)]
        if nd_in:
            autograd._record(op, args, kwargs, nd_in, outs)

    if out is not None:
        return out
    return tuple(outs) if multi else outs[0]
