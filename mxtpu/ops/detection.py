"""Detection ops — capability parity with the reference's SSD/RCNN operator set:
``src/operator/contrib/multibox_prior.cc``, ``multibox_target.cc``,
``multibox_detection.cc``, ``contrib/proposal.cc``, ``src/operator/roi_pooling.cc``,
``contrib/psroi_pooling.cc``, ``contrib/deformable_convolution.cc``.

TPU-native formulations: every op is a static-shape, jittable XLA program —
the reference's sequential CPU loops (greedy bipartite matching, greedy NMS)
become bounded ``lax.fori_loop``s over vectorized mask updates, so the whole
detection head can live inside one compiled step. Suppressed/invalid rows use
the reference's -1 convention instead of dynamic output shapes.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NS = "contrib"


def _corner_to_center(b):
    return ((b[..., 0] + b[..., 2]) * 0.5, (b[..., 1] + b[..., 3]) * 0.5,
            b[..., 2] - b[..., 0], b[..., 3] - b[..., 1])


def _pair_iou(anchors, gts):
    """IoU matrix (A, G), corner format."""
    tl = jnp.maximum(anchors[:, None, :2], gts[None, :, :2])
    br = jnp.minimum(anchors[:, None, 2:4], gts[None, :, 2:4])
    inter = jnp.prod(jnp.maximum(br - tl, 0.0), axis=-1)
    area_a = jnp.prod(jnp.maximum(anchors[:, 2:4] - anchors[:, :2], 0.0), -1)
    area_g = jnp.prod(jnp.maximum(gts[:, 2:4] - gts[:, :2], 0.0), -1)
    return inter / jnp.maximum(area_a[:, None] + area_g[None, :] - inter, 1e-12)


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------


@register("MultiBoxPrior", namespace=NS, differentiable=False,
          aliases=("multibox_prior",))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip: bool = False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """multibox_prior.cc: SSD anchor generation over an (N,C,H,W) feature map.

    Per location: ``len(sizes)`` boxes at ratio 1 then ``len(ratios)-1`` boxes
    at sizes[0] — widths carry the reference's in_h/in_w aspect correction
    (multibox_prior.cc:50-66). Output (1, H*W*num_anchors, 4), corner format."""
    in_h, in_w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w
    r = jnp.arange(in_h, dtype=jnp.float32)
    c = jnp.arange(in_w, dtype=jnp.float32)
    cy = (r + offsets[0]) * step_y                      # (H,)
    cx = (c + offsets[1]) * step_x                      # (W,)
    # half-extents per anchor kind
    ws, hs = [], []
    for s in sizes:
        ws.append(s * in_h / in_w / 2.0)
        hs.append(s / 2.0)
    for ratio in ratios[1:]:
        sq = float(np.sqrt(ratio))
        ws.append(sizes[0] * in_h / in_w * sq / 2.0)
        hs.append(sizes[0] / sq / 2.0)
    w = jnp.asarray(ws, jnp.float32)                    # (A,)
    h = jnp.asarray(hs, jnp.float32)
    cxg = jnp.broadcast_to(cx[None, :, None], (in_h, in_w, w.size))
    cyg = jnp.broadcast_to(cy[:, None, None], (in_h, in_w, w.size))
    out = jnp.stack([cxg - w, cyg - h, cxg + w, cyg + h], axis=-1)
    out = out.reshape(1, in_h * in_w * w.size, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------


def _encode_loc(anchors, gt_boxes, variances):
    """multibox_target.cc:32 AssignLocTargets."""
    ax, ay, aw, ah = _corner_to_center(anchors)
    gx, gy, gw, gh = _corner_to_center(gt_boxes)
    vx, vy, vw, vh = variances
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, 1e-12) / vx,
        (gy - ay) / jnp.maximum(ah, 1e-12) / vy,
        jnp.log(jnp.maximum(gw, 1e-12) / jnp.maximum(aw, 1e-12)) / vw,
        jnp.log(jnp.maximum(gh, 1e-12) / jnp.maximum(ah, 1e-12)) / vh,
    ], axis=-1)


@register("MultiBoxTarget", namespace=NS, num_outputs=3, differentiable=False,
          aliases=("multibox_target",))
def _multibox_target(anchors, labels, cls_preds, overlap_threshold: float = 0.5,
                     ignore_label: float = -1.0,
                     negative_mining_ratio: float = -1.0,
                     negative_mining_thresh: float = 0.5,
                     minimum_negative_samples: int = 0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """multibox_target.cc: anchor↔gt matching producing (loc_target (N,4A),
    loc_mask (N,4A), cls_target (N,A)).

    anchors (1,A,4); labels (N,G,5+) rows [cls,x1,y1,x2,y2] padded with -1;
    cls_preds (N,num_cls,A). The reference's sequential greedy bipartite stage
    runs as a G-iteration fori_loop over vectorized argmax; the threshold stage
    and hard-negative mining are fully vectorized."""
    anchors = anchors.reshape(-1, 4)
    A = anchors.shape[0]
    G = labels.shape[1]

    def one_batch(label, cls_pred):
        gt_valid = label[:, 0] != -1.0                        # (G,)
        iou = _pair_iou(anchors, label[:, 1:5])               # (A, G)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)

        # stage 1: greedy bipartite matching (multibox_target.cc:110-148)
        def bip_body(_, carry):
            match_gt, match_iou, a_free, g_free = carry
            m = iou * a_free[:, None] * g_free[None, :]
            flat = jnp.argmax(m)
            aj, gk = flat // G, flat % G
            ok = m[aj, gk] > 1e-6
            match_gt = jnp.where(ok, match_gt.at[aj].set(gk), match_gt)
            match_iou = jnp.where(ok, match_iou.at[aj].set(m[aj, gk]), match_iou)
            a_free = jnp.where(ok, a_free.at[aj].set(0.0), a_free)
            g_free = jnp.where(ok, g_free.at[gk].set(0.0), g_free)
            return match_gt, match_iou, a_free, g_free

        match_gt0 = jnp.full((A,), -1, jnp.int32)
        match_iou0 = jnp.full((A,), -1.0, jnp.float32)
        match_gt, match_iou, a_free, _ = lax.fori_loop(
            0, G, bip_body,
            (match_gt0, match_iou0, jnp.ones((A,)), gt_valid.astype(jnp.float32)))

        # stage 2: threshold matching for still-unmatched anchors (:151-180)
        row_best = jnp.argmax(iou, axis=1).astype(jnp.int32)
        row_iou = jnp.max(iou, axis=1)
        unmatched = a_free > 0.5
        if overlap_threshold > 0:
            thr_pos = unmatched & (row_iou > overlap_threshold)
        else:
            thr_pos = jnp.zeros((A,), bool)
        positive = (~unmatched) | thr_pos
        match_gt = jnp.where(unmatched, row_best, match_gt)
        match_iou = jnp.where(unmatched, row_iou, match_iou)

        # stage 3: negatives — mining (:182-243) or all
        if negative_mining_ratio > 0:
            num_pos = jnp.sum(positive)
            num_neg = jnp.minimum(
                jnp.maximum((num_pos * negative_mining_ratio).astype(jnp.int32),
                            minimum_negative_samples),
                A - num_pos)
            logits = cls_pred.T                               # (A, num_cls)
            prob_bg = jax.nn.softmax(logits, axis=-1)[:, 0]
            cand = (~positive) & (match_iou < negative_mining_thresh)
            score = jnp.where(cand, prob_bg, jnp.inf)         # hardest = lowest
            order = jnp.argsort(score)
            rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A))
            negative = cand & (rank < num_neg)
        else:
            negative = ~positive

        valid_any = jnp.any(gt_valid)
        cls_target = jnp.where(
            positive, label[match_gt, 0] + 1.0,
            jnp.where(negative, 0.0, ignore_label))
        loc = _encode_loc(anchors, label[match_gt, 1:5], variances)
        mask4 = jnp.broadcast_to(positive[:, None], (A, 4)).astype(jnp.float32)
        loc_target = jnp.where(mask4 > 0, loc, 0.0)
        # no valid gt → everything stays background/zero (reference skips batch)
        cls_target = jnp.where(valid_any, cls_target, 0.0)
        loc_target = jnp.where(valid_any, loc_target, 0.0)
        mask4 = jnp.where(valid_any, mask4, 0.0)
        return loc_target.reshape(-1), mask4.reshape(-1), cls_target

    loc_t, loc_m, cls_t = jax.vmap(one_batch)(labels, cls_preds)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------


def _decode_loc(anchors, loc_pred, variances, clip):
    """multibox_detection.cc:46 TransformLocations."""
    ax, ay, aw, ah = _corner_to_center(anchors)
    vx, vy, vw, vh = variances
    ox = loc_pred[..., 0] * vx * aw + ax
    oy = loc_pred[..., 1] * vy * ah + ay
    ow = jnp.exp(loc_pred[..., 2] * vw) * aw * 0.5
    oh = jnp.exp(loc_pred[..., 3] * vh) * ah * 0.5
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


@register("MultiBoxDetection", namespace=NS, differentiable=False,
          aliases=("multibox_detection",))
def _multibox_detection(cls_prob, loc_pred, anchors, clip: bool = True,
                        threshold: float = 0.01, background_id: int = 0,
                        nms_threshold: float = 0.5,
                        force_suppress: bool = False, keep_topk: int = -1,
                        nms_topk: int = -1, variances=(0.1, 0.1, 0.2, 0.2)):
    """multibox_detection.cc: decode + per-class greedy NMS.

    cls_prob (N,num_cls,A), loc_pred (N,4A), anchors (1,A,4) →
    (N, A, 6) rows [cls_id, score, x1,y1,x2,y2]; invalid rows cls_id=-1."""
    anchors = anchors.reshape(-1, 4)
    A = anchors.shape[0]

    def one_batch(probs, locs):
        locs = locs.reshape(A, 4)
        # drop background row, pick best foreground class per anchor
        fg = jnp.concatenate([probs[:background_id], probs[background_id + 1:]],
                             axis=0)                       # (C-1, A)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        valid = score > threshold
        cls_id = jnp.where(valid, cls_id, -1.0)
        score = jnp.where(valid, score, -1.0)
        boxes = _decode_loc(anchors, locs, variances, clip)

        order = jnp.argsort(-score)
        if nms_topk > 0:
            keep_rank = jnp.arange(A) < nms_topk
        else:
            keep_rank = jnp.ones((A,), bool)
        cls_s, score_s, boxes_s = cls_id[order], score[order], boxes[order]
        score_s = jnp.where(keep_rank, score_s, -1.0)
        iou = _pair_iou(boxes_s, boxes_s)
        if not force_suppress:
            same = cls_s[:, None] == cls_s[None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            sup = (iou[i] > nms_threshold) & (jnp.arange(A) > i) & keep[i]
            return keep & ~sup

        keep = lax.fori_loop(0, A, body, score_s > -1.0)
        cls_out = jnp.where(keep, cls_s, -1.0)
        score_out = jnp.where(keep, score_s, -1.0)
        return jnp.concatenate([cls_out[:, None], score_out[:, None], boxes_s],
                               axis=1)

    return jax.vmap(one_batch)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# Proposal (RPN)
# ---------------------------------------------------------------------------


def _rpn_anchors(h, w, stride, scales, ratios):
    """proposal.cc GenerateAnchors: base anchors at stride grid (image coords)."""
    base = float(stride)
    px, py = (base - 1) * 0.5, (base - 1) * 0.5
    boxes = []
    for r in ratios:
        size = base * base / r
        ws = round(float(np.sqrt(size)))
        hs = round(float(ws * r))
        for s in scales:
            w2, h2 = ws * s * 0.5, hs * s * 0.5
            boxes.append([px - w2 + 0.5, py - h2 + 0.5, px + w2 - 0.5,
                          py + h2 - 0.5])
    base_a = jnp.asarray(boxes, jnp.float32)                 # (A, 4)
    sx = jnp.arange(w, dtype=jnp.float32) * stride
    sy = jnp.arange(h, dtype=jnp.float32) * stride
    shift = jnp.stack([
        jnp.broadcast_to(sx[None, :], (h, w)),
        jnp.broadcast_to(sy[:, None], (h, w)),
        jnp.broadcast_to(sx[None, :], (h, w)),
        jnp.broadcast_to(sy[:, None], (h, w))], axis=-1)     # (h, w, 4)
    return (shift[:, :, None, :] + base_a[None, None, :, :]).reshape(-1, 4)


@register("Proposal", namespace=NS, differentiable=False,
          aliases=("proposal",))
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n: int = 6000,
              rpn_post_nms_top_n: int = 300, threshold: float = 0.7,
              rpn_min_size: int = 16, scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride: int = 16, output_score: bool = False,
              iou_loss: bool = False):
    """contrib/proposal.cc: RPN proposal generation.

    cls_prob (N, 2A, h, w) (bg/fg per anchor), bbox_pred (N, 4A, h, w),
    im_info (N, 3) [height, width, scale]. Output (N*post_nms, 5) rois
    [batch_idx, x1,y1,x2,y2] (+ optional scores (N*post_nms, 1))."""
    N, _, h, w = cls_prob.shape
    A = len(scales) * len(ratios)
    anchors = _rpn_anchors(h, w, feature_stride, scales, ratios)   # (hwA, 4)
    K = anchors.shape[0]
    pre_n = min(rpn_pre_nms_top_n, K) if rpn_pre_nms_top_n > 0 else K
    post_n = rpn_post_nms_top_n

    def one_batch(probs, deltas, info):
        fg = probs[A:].transpose(1, 2, 0).reshape(-1)              # (hwA,)
        d = deltas.transpose(1, 2, 0).reshape(-1, 4)
        ax, ay, aw, ah = _corner_to_center(anchors)
        aw, ah = aw + 1.0, ah + 1.0                                # pixel conv.
        cx = d[:, 0] * aw + ax
        cy = d[:, 1] * ah + ay
        pw = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        ph = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - 0.5 * (pw - 1), cy - 0.5 * (ph - 1),
                           cx + 0.5 * (pw - 1), cy + 0.5 * (ph - 1)], -1)
        boxes = jnp.clip(boxes, 0.0,
                         jnp.stack([info[1] - 1, info[0] - 1,
                                    info[1] - 1, info[0] - 1]))
        min_size = rpn_min_size * info[2]
        keep_size = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
                    ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        scores = jnp.where(keep_size, fg, -1.0)
        top_scores, top_idx = lax.top_k(scores, pre_n)
        top_boxes = boxes[top_idx]
        iou = _pair_iou(top_boxes, top_boxes)

        def body(i, keep):
            sup = (iou[i] > threshold) & (jnp.arange(pre_n) > i) & keep[i]
            return keep & ~sup

        keep = lax.fori_loop(0, pre_n, body, top_scores > -1.0)
        nms_score = jnp.where(keep, top_scores, -1.0)
        sel_scores, sel = lax.top_k(nms_score, min(post_n, pre_n))
        rois = top_boxes[sel]
        if post_n > pre_n:
            pad = post_n - pre_n
            rois = jnp.concatenate([rois, jnp.tile(rois[:1], (pad, 1))], 0)
            sel_scores = jnp.concatenate([sel_scores,
                                          jnp.tile(sel_scores[:1], (pad,))], 0)
        return rois, sel_scores

    rois, scores = jax.vmap(one_batch)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=jnp.float32), post_n)[:, None]
    out = jnp.concatenate([batch_idx, rois.reshape(-1, 4)], axis=1)
    if output_score:
        return out, scores.reshape(-1, 1)
    return out


def _multi_proposal(*args, **kwargs):
    return _proposal(*args, **kwargs)


# ---------------------------------------------------------------------------
# ROIPooling / PSROIPooling
# ---------------------------------------------------------------------------


@register("ROIPooling", aliases=("roi_pooling",))
def _roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale: float = 1.0):
    """src/operator/roi_pooling.cc: max pooling over ROI bins.

    data (N,C,H,W); rois (R,5) [batch_idx, x1,y1,x2,y2] in image coords.
    Masked-max formulation (static shapes; bins never materialize a gather)."""
    N, C, H, W = data.shape
    ph, pw = pooled_size
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        img = data[b]                                       # (C, H, W)

        def bin_val(iy, ix):
            hs = jnp.floor(y1 + iy * bin_h)
            he = jnp.ceil(y1 + (iy + 1) * bin_h)
            ws_ = jnp.floor(x1 + ix * bin_w)
            we = jnp.ceil(x1 + (ix + 1) * bin_w)
            mask = ((ys >= hs) & (ys < he))[:, None] & \
                   ((xs >= ws_) & (xs < we))[None, :]
            empty = ~jnp.any(mask)
            v = jnp.where(mask[None], img, -jnp.inf).max(axis=(1, 2))
            return jnp.where(empty, 0.0, v)

        iy = jnp.arange(ph)
        ix = jnp.arange(pw)
        vals = jax.vmap(lambda y: jax.vmap(lambda x: bin_val(y, x))(ix))(iy)
        return vals.transpose(2, 0, 1)                      # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


@register("PSROIPooling", namespace=NS, aliases=("psroi_pooling",))
def _psroi_pooling(data, rois, spatial_scale: float = 1.0, output_dim: int = 0,
                   pooled_size: int = 7, group_size: int = 0):
    """contrib/psroi_pooling.cc: position-sensitive ROI average pooling.

    data (N, output_dim*k*k, H, W); each (iy,ix) bin averages its own channel
    group (position sensitivity, the R-FCN trick)."""
    k = pooled_size
    group = group_size if group_size > 0 else k
    N, Ck, H, W = data.shape
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / k, rw / k
        img = data[b].reshape(output_dim, group * group, H, W)

        def bin_val(iy, ix):
            hs = jnp.floor(y1 + iy * bin_h)
            he = jnp.ceil(y1 + (iy + 1) * bin_h)
            ws_ = jnp.floor(x1 + ix * bin_w)
            we = jnp.ceil(x1 + (ix + 1) * bin_w)
            mask = ((ys >= hs) & (ys < he))[:, None] & \
                   ((xs >= ws_) & (xs < we))[None, :]
            gidx = (iy * group // k) * group + (ix * group // k)
            chan = img[:, gidx]                             # (output_dim, H, W)
            cnt = jnp.maximum(jnp.sum(mask), 1)
            return jnp.where(mask[None], chan, 0.0).sum((1, 2)) / cnt

        iy = jnp.arange(k)
        ix = jnp.arange(k)
        vals = jax.vmap(lambda y: jax.vmap(lambda x: bin_val(y, x))(ix))(iy)
        return vals.transpose(2, 0, 1)                      # (output_dim, k, k)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------


def _bilinear_gather(img, y, x):
    """Sample img (C,H,W) at float coords y,x (...,): bilinear, zero outside."""
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1, wx1 = y - y0, x - x0
    out = 0.0
    for dy, wy in ((0, 1 - wy1), (1, wy1)):
        for dx, wx in ((0, 1 - wx1), (1, wx1)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            inside = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            v = img[:, jnp.clip(yy, 0, H - 1), jnp.clip(xx, 0, W - 1)]
            out = out + v * (wy * wx * inside)[None]
    return out


@register("DeformableConvolution", namespace=NS,
          aliases=("deformable_convolution",))
def _deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                            stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                            num_filter: int = 0, num_group: int = 1,
                            num_deformable_group: int = 1,
                            no_bias: bool = False):
    """contrib/deformable_convolution.cc (DCNv1): each kernel tap samples at
    its regular grid position plus a learned offset, bilinearly.

    data (N,C,H,W); offset (N, 2*dg*kh*kw, OH, OW) ordered [dy,dx] per tap.
    Implementation: gather the deformed im2col patches with a vectorized
    bilinear sampler, then contract with the weight — the contraction is a
    plain dot_general on the MXU."""
    N, C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph_, pw_ = pad
    OH = (H + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
    OW = (W + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
    dg = num_deformable_group

    oy = jnp.arange(OH, dtype=jnp.float32) * sh - ph_
    ox = jnp.arange(OW, dtype=jnp.float32) * sw - pw_

    def one_image(img, off):
        off = off.reshape(dg, kh * kw, 2, OH, OW)

        def tap(t):
            ky, kx = t // kw, t % kw
            base_y = oy[:, None] + ky * dh                  # (OH, 1)
            base_x = ox[None, :] + kx * dw                  # (1, OW)

            def group_sample(g):
                dy = off[g, t, 0]
                dx = off[g, t, 1]
                y = base_y + dy
                x = base_x + dx
                cpg = C // dg
                return _bilinear_gather(
                    img[g * cpg:(g + 1) * cpg], y, x)       # (cpg, OH, OW)

            return jnp.concatenate([group_sample(g) for g in range(dg)], 0)

        cols = jnp.stack([tap(t) for t in range(kh * kw)], 1)  # (C, khkw, OH, OW)
        return cols

    cols = jax.vmap(one_image)(data, offset)                # (N, C, khkw, OH, OW)
    w = weight.reshape(num_group, num_filter // num_group,
                       C // num_group, kh * kw)
    cols = cols.reshape(N, num_group, C // num_group, kh * kw, OH, OW)
    out = jnp.einsum("ngckhw,gock->ngohw", cols, w.transpose(0, 1, 2, 3))
    out = out.reshape(N, num_filter, OH, OW)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _register_aliases():
    from .registry import alias
    alias("contrib.Proposal", "MultiProposal", "multi_proposal",
          namespace="contrib")


_register_aliases()


@register("DeformablePSROIPooling", namespace=NS,
          aliases=("deformable_psroi_pooling",), num_outputs=1)
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale: float = 1.0,
                              output_dim: int = 0, group_size: int = 1,
                              pooled_size: int = 7, part_size: int = 0,
                              sample_per_part: int = 4,
                              trans_std: float = 0.0, no_trans: bool = False):
    """contrib/deformable_psroi_pooling.cc (Deformable ConvNets): PSROI
    pooling whose bins shift by learned normalized offsets ``trans``
    (R, 2*cls, part, part), sampled bilinearly ``sample_per_part``² per bin.

    TPU shape: one vmapped roi program of static (k, k, s, s) gathers — no
    data-dependent loops; `no_trans=True` degrades to offset-free sampling
    (the op's own fallback when trans is absent)."""
    k = pooled_size
    part = part_size if part_size > 0 else k
    group = group_size if group_size > 0 else k
    N, Ck, H, W = data.shape
    s = sample_per_part

    def one_roi(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / k, rw / k
        sub_h, sub_w = bin_h / s, bin_w / s
        img = data[b].reshape(output_dim, group * group, H, W)

        def bin_val(iy, ix):
            py = jnp.minimum(iy * part // k, part - 1)
            px = jnp.minimum(ix * part // k, part - 1)
            if no_trans or tr is None:
                dy = dx = 0.0
            else:
                # class 0 offsets (the detection head's shared-offset mode)
                dy = tr[0, py, px] * trans_std * rh
                dx = tr[1, py, px] * trans_std * rw
            oy = jnp.arange(s, dtype=jnp.float32)
            ox = jnp.arange(s, dtype=jnp.float32)
            yy = y1 + iy * bin_h + (oy + 0.5) * sub_h + dy
            xx = x1 + ix * bin_w + (ox + 0.5) * sub_w + dx
            gidx = (iy * group // k) * group + (ix * group // k)
            chan = img[:, gidx]                         # (output_dim, H, W)
            yg, xg = jnp.meshgrid(yy, xx, indexing="ij")
            yf, xf = yg.reshape(-1), xg.reshape(-1)
            # reference kernel (deformable_psroi_pooling.cu:84): samples more
            # than 0.5px outside are SKIPPED (count divides only in-bounds),
            # the rest clamp to the border
            valid = ((yf >= -0.5) & (yf <= H - 0.5) &
                     (xf >= -0.5) & (xf <= W - 0.5))
            yc = jnp.clip(yf, 0.0, H - 1.0)
            xc = jnp.clip(xf, 0.0, W - 1.0)
            vals = _bilinear_gather(chan, yc, xc)
            cnt = jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(vals * valid[None, :], axis=-1) / cnt

        iy = jnp.arange(k)
        ix = jnp.arange(k)
        vals = jax.vmap(lambda y: jax.vmap(lambda x: bin_val(y, x))(ix))(iy)
        return vals.transpose(2, 0, 1)                  # (output_dim, k, k)

    if trans is None or no_trans:
        return jax.vmap(lambda r: one_roi(r, None))(rois)
    if trans.shape[1] != 2:
        raise NotImplementedError(
            "DeformablePSROIPooling: class-aware offsets (trans second dim "
            f"{trans.shape[1]} = 2*num_classes > 2) are not bound — pass the "
            "shared (R, 2, part, part) offsets (reference class_id indexing "
            "is per-channel)")
    return jax.vmap(one_roi)(rois, trans)
