"""Sequence ops — parity with ``src/operator/sequence_{mask,last,reverse}-inl.h``.

Layout follows the reference: sequence axis 0, batch axis 1 (TNC). These are the
building blocks for variable-length RNN/attention batches (with bucketing at the
iterator/module layer, SURVEY.md §5 long-context notes).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _steps(data):
    return jnp.arange(data.shape[0])[:, None]  # (T,1) broadcast against (B,)


@register("SequenceMask", aliases=("sequence_mask",))
def _sequence_mask(data, sequence_length=None, use_sequence_length: bool = False,
                   value: float = 0.0, axis: int = 0):
    if not use_sequence_length or sequence_length is None:
        return data
    if axis == 1:
        data_t = jnp.swapaxes(data, 0, 1)
        out = _sequence_mask(data_t, sequence_length, True, value, 0)
        return jnp.swapaxes(out, 0, 1)
    mask = _steps(data) < sequence_length[None, :].astype(jnp.int32)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, dtype=data.dtype))


@register("SequenceLast", aliases=("sequence_last",))
def _sequence_last(data, sequence_length=None, use_sequence_length: bool = False,
                   axis: int = 0):
    if axis == 1:
        data = jnp.swapaxes(data, 0, 1)
    if not use_sequence_length or sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1).clip(0)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]


@register("SequenceReverse", aliases=("sequence_reverse",))
def _sequence_reverse(data, sequence_length=None, use_sequence_length: bool = False,
                      axis: int = 0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    lens = sequence_length.astype(jnp.int32)[None, :]  # (1,B)
    t = _steps(data)  # (T,1)
    src = jnp.where(t < lens, lens - 1 - t, t)  # reverse within length, keep tail
    src = src.reshape(src.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, jnp.broadcast_to(src, data.shape), axis=0)
