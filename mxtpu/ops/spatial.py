"""Spatial warp / correlation / FFT ops — capability parity with
``src/operator/grid_generator-inl.h``, ``bilinear_sampler.cc``,
``spatial_transformer.cc``, ``correlation-inl.h`` and
``src/operator/contrib/fft-inl.h``/``ifft-inl.h``.

All are direct XLA formulations: the bilinear sampler is a 4-tap gather
(differentiable through jax autodiff — the reference hand-writes the atomic
backward kernels), the correlation op is a static displacement-loop of fused
multiply-reduces, FFT rides ``jnp.fft`` (cuFFT's unnormalized convention kept).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NS = "contrib"


# ---------------------------------------------------------------------------
# GridGenerator / BilinearSampler / SpatialTransformer
# ---------------------------------------------------------------------------


def _dst_grid(h, w):
    """Normalized target grid, (3, h*w) rows [x, y, 1] in [-1, 1]
    (grid_generator-inl.h:97-105 layout)."""
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    xn = -1.0 + xs * 2.0 / (w - 1) if w > 1 else jnp.zeros_like(xs)
    yn = -1.0 + ys * 2.0 / (h - 1) if h > 1 else jnp.zeros_like(ys)
    ones = jnp.ones_like(xn)
    return jnp.stack([xn.ravel(), yn.ravel(), ones.ravel()], axis=0)


@register("GridGenerator", aliases=("grid_generator",))
def _grid_generator(data, transform_type: str = "affine", target_shape=(0, 0)):
    """grid_generator-inl.h: affine (N,6)→grid, or warp flow (N,2,H,W)→grid.
    Output (N, 2, H, W), channel order [x, y], normalized [-1, 1]."""
    if transform_type == "affine":
        h, w = target_shape
        theta = data.reshape(-1, 2, 3)
        grid = jnp.einsum("nij,jk->nik", theta, _dst_grid(h, w))
        return grid.reshape(-1, 2, h, w)
    # warp: grid = normalize(pixel_grid + flow)
    n, _, h, w = data.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    x = xs[None] + data[:, 0]
    y = ys[None] + data[:, 1]
    xn = x * 2.0 / jnp.maximum(w - 1, 1) - 1.0
    yn = y * 2.0 / jnp.maximum(h - 1, 1) - 1.0
    return jnp.stack([xn, yn], axis=1)


def _bilinear_sample_nchw(data, grid):
    """data (N,C,H,W), grid (N,2,OH,OW) normalized [-1,1] [x,y] →
    (N,C,OH,OW); zero padding outside (bilinear_sampler.cc:49-57)."""
    N, C, H, W = data.shape

    def one(img, g):
        x = (g[0] + 1.0) * (W - 1) / 2.0
        y = (g[1] + 1.0) * (H - 1) / 2.0
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        out = 0.0
        for dy, wy in ((0, 1.0 - (y - y0)), (1, y - y0)):
            for dx, wx in ((0, 1.0 - (x - x0)), (1, x - x0)):
                yy = (y0 + dy).astype(jnp.int32)
                xx = (x0 + dx).astype(jnp.int32)
                inside = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
                v = img[:, jnp.clip(yy, 0, H - 1), jnp.clip(xx, 0, W - 1)]
                out = out + v * (wy * wx * inside)[None]
        return out

    return jax.vmap(one)(data, grid)


@register("BilinearSampler", aliases=("bilinear_sampler",))
def _bilinear_sampler(data, grid):
    return _bilinear_sample_nchw(data, grid)


@register("SpatialTransformer", aliases=("spatial_transformer",))
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type: str = "affine",
                         sampler_type: str = "bilinear"):
    """spatial_transformer.cc: affine grid from loc (N,6) + bilinear sample."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise NotImplementedError("affine/bilinear only (reference parity)")
    h, w = target_shape
    if h == 0 or w == 0:
        h, w = data.shape[2], data.shape[3]
    grid = _grid_generator(loc, transform_type="affine", target_shape=(h, w))
    return _bilinear_sample_nchw(data, grid)


# ---------------------------------------------------------------------------
# Correlation
# ---------------------------------------------------------------------------


@register("Correlation", aliases=("correlation",))
def _correlation(data1, data2, kernel_size: int = 1, max_displacement: int = 1,
                 stride1: int = 1, stride2: int = 1, pad_size: int = 0,
                 is_multiply: bool = True):
    """correlation-inl.h (FlowNet cost volume): for each displacement in a
    (2r+1)² neighborhood (r = max_displacement//stride2), correlate kernel
    windows of data1 against shifted data2, normalized by kernel²·C."""
    N, C, H, W = data1.shape
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    ph, pw = H + 2 * pad_size, W + 2 * pad_size
    top_h = int(np.ceil((ph - border * 2) / float(stride1)))
    top_w = int(np.ceil((pw - border * 2) / float(stride1)))
    r = max_displacement // stride2
    gw = 2 * r + 1

    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    norm = float(kernel_size * kernel_size * C)

    # centers of output pixels in padded coords
    cy = border + jnp.arange(top_h) * stride1
    cx = border + jnp.arange(top_w) * stride1

    def window(d, oy, ox):
        """(N, C, kernel, kernel, top_h, top_w) patches at centers+offset."""
        ys = cy + oy
        xs = cx + ox
        rows = ys[:, None] + jnp.arange(-kr, kr + 1)[None, :]   # (th, k)
        cols = xs[:, None] + jnp.arange(-kr, kr + 1)[None, :]   # (tw, k)
        return d[:, :, rows[:, :, None, None], cols[None, None, :, :]]

    outs = []
    for iy in range(-r, r + 1):
        for ix in range(-r, r + 1):
            p1 = window(d1, 0, 0)
            p2 = window(d2, iy * stride2, ix * stride2)
            if is_multiply:
                v = (p1 * p2).sum(axis=(1, 3, 5)) / norm
            else:
                v = jnp.abs(p1 - p2).sum(axis=(1, 3, 5)) / norm
            outs.append(v)
    return jnp.stack(outs, axis=1)  # (N, gw*gw, top_h, top_w)


# ---------------------------------------------------------------------------
# FFT / IFFT
# ---------------------------------------------------------------------------


@register("fft", namespace=NS, aliases=("FFT",))
def _fft(data, compute_size: int = 128):
    """contrib/fft-inl.h: real (..., d) → interleaved complex (..., 2d)."""
    f = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register("ifft", namespace=NS, aliases=("IFFT",))
def _ifft(data, compute_size: int = 128):
    """contrib/ifft-inl.h: interleaved complex (..., 2d) → real (..., d);
    cuFFT's unnormalized inverse convention (scaled by d)."""
    d = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (d, 2)).astype(jnp.float32)
    z = lax.complex(c[..., 0], c[..., 1])
    out = jnp.fft.ifft(z, axis=-1).real * d
    return out.astype(data.dtype)
