"""``mx.nd.image``/``mx.sym.image`` operator namespace — parity with the
reference's C++ image ops (src/operator/image/image_random.cc, 845 LoC:
to_tensor / normalize / flips / resize / crop registered under the ``image``
op namespace; the Python transforms in gluon.data.vision wrap these).

Conventions match the reference: ``to_tensor`` takes HWC (or NHWC) uint8-range
input and yields CHW float32 in [0,1]; ``normalize`` takes CHW/NCHW; the
flip/resize/crop family operates on HWC/NHWC.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import rng
from ..base import dtype_np
from .registry import register

NS = "image"


def _hwc_axis(data, axis_from_end: int) -> int:
    # HWC (3d) or NHWC (4d): address spatial axes from the channel end
    return data.ndim - 1 - axis_from_end


@register("to_tensor", namespace=NS)
def _to_tensor(data):
    """HWC/NHWC [0,255] → CHW/NCHW float32 [0,1] (image_random.cc ToTensor)."""
    out = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return out.transpose(2, 0, 1)
    return out.transpose(0, 3, 1, 2)


@register("normalize", namespace=NS)
def _normalize(data, mean=0.0, std=1.0):
    """(x - mean) / std per channel on CHW/NCHW (image_random.cc Normalize)."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = [1] * data.ndim
    shape[c_axis] = -1
    m = jnp.reshape(jnp.atleast_1d(jnp.asarray(mean, jnp.float32)), shape)
    s = jnp.reshape(jnp.atleast_1d(jnp.asarray(std, jnp.float32)), shape)
    return (data - m) / s


@register("flip_left_right", namespace=NS)
def _flip_left_right(data):
    return jnp.flip(data, axis=_hwc_axis(data, 1))


@register("flip_top_bottom", namespace=NS)
def _flip_top_bottom(data):
    return jnp.flip(data, axis=_hwc_axis(data, 2))


@register("random_flip_left_right", namespace=NS, differentiable=False)
def _random_flip_left_right(data, p: float = 0.5, key=None):
    k = key if key is not None else rng.next_key()
    return jax.lax.cond(jax.random.uniform(k) < p,
                        lambda d: jnp.flip(d, axis=_hwc_axis(d, 1)),
                        lambda d: d, data)


@register("random_flip_top_bottom", namespace=NS, differentiable=False)
def _random_flip_top_bottom(data, p: float = 0.5, key=None):
    k = key if key is not None else rng.next_key()
    return jax.lax.cond(jax.random.uniform(k) < p,
                        lambda d: jnp.flip(d, axis=_hwc_axis(d, 2)),
                        lambda d: d, data)


@register("resize", namespace=NS)
def _resize(data, size=0, keep_ratio: bool = False, interp: int = 1):
    """Resize HWC/NHWC to ``size`` (int → square / shorter-edge-with-ratio,
    pair → (w, h)); interp 0=nearest, else bilinear (image_resize.cc)."""
    method = "nearest" if interp == 0 else "linear"
    if data.ndim == 3:
        h, w, c = data.shape
        batch = False
    else:
        _, h, w, c = data.shape
        batch = True
    if isinstance(size, (tuple, list)):
        new_w, new_h = int(size[0]), int(size[1])
    elif keep_ratio:
        scale = float(size) / float(min(h, w))
        if h < w:
            new_h, new_w = int(size), max(1, int(round(w * scale)))
        else:
            new_w, new_h = int(size), max(1, int(round(h * scale)))
    else:
        new_w = new_h = int(size)
    shape = ((data.shape[0], new_h, new_w, c) if batch
             else (new_h, new_w, c))
    out = jax.image.resize(data.astype(jnp.float32), shape, method=method)
    if jnp.issubdtype(data.dtype, jnp.integer):
        info = jnp.iinfo(data.dtype)
        return jnp.clip(jnp.round(out), info.min, info.max).astype(data.dtype)
    return out


@register("crop", namespace=NS)
def _crop(data, x: int = 0, y: int = 0, width: int = 1, height: int = 1):
    """Fixed crop of HWC/NHWC at (x, y) sized (width, height); bounds are
    CHECKed like the reference's crop.cc rather than silently clamped."""
    img_h, img_w = (data.shape[0], data.shape[1]) if data.ndim == 3 else \
        (data.shape[1], data.shape[2])
    if width <= 0 or height <= 0:
        raise ValueError(f"crop: width/height must be positive, got "
                         f"({width}, {height})")
    if x < 0 or y < 0 or x + width > img_w or y + height > img_h:
        raise ValueError(f"crop: window ({x},{y},{width},{height}) out of "
                         f"bounds for image ({img_h}, {img_w})")
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


# the reference registers image ops under BOTH mx.nd.image.* and internal
# root names (_image_normalize etc., src/operator/image/image_random.cc)
from .registry import alias as _alias  # noqa: E402
for _n in ("normalize", "to_tensor", "resize", "crop", "flip_left_right",
           "flip_top_bottom", "random_flip_left_right",
           "random_flip_top_bottom"):
    _alias(f"image.{_n}", f"_image_{_n}")
