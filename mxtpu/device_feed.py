"""Device-feed input pipeline — async sharded host→device prefetch.

The reference keeps the accelerator fed with producer threads
(``iter_prefetcher.h`` / ``PrefetchingIter``, SURVEY §1); the port in
``mxtpu.io`` double-buffers *host-side numpy* only, so ``Module.fit`` still
paid one synchronous placement per batch inside the step loop — the chip
idled through every host decode + transfer. :class:`DeviceFeed` is the
TPU-idiomatic completion of that design: the standard JAX
``prefetch_to_device`` idiom generalized to ``NamedSharding`` meshes. A
bounded producer thread pulls batches from any ``DataIter``/iterable and
pushes them THROUGH the host→device boundary (non-blocking committed
``jax.device_put``, sharded via the same placement path the training step
feeds through) a configurable ``depth`` of batches ahead, so the fused step
executor's next inputs are already resident when the previous program
retires.

Contracts:

* **Donation-safe** — a delivered batch is never re-enqueued and the feeder
  drops every reference to it the moment the consumer takes it, so a step
  with ``donate_argnums`` may consume the buffers (the same class of race
  the checkpoint snapshots had to close).
* **Multi-process-safe** — ``NamedSharding`` placements route through
  ``parallel.data_parallel.place``: each process feeds only its addressable
  shard and JAX assembles the global array.
* **Generation-safe reset** — the producer owns its queue and stop flag as
  locals, so a straggler thread from before ``reset()`` can never leak a
  stale batch into the new epoch's queue.
* **Exception transparency** — a producer-thread exception is latched and
  re-raised in the consumer on ``next()``.

Knobs: ``MXTPU_DEVICE_FEED=0`` opts the implicit ``Module.fit`` wrapping
out; ``MXTPU_FEED_DEPTH`` overrides the default depth of 2. Stall/transfer
accounting lands in ``profiler.get_feed_stats()``.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Optional

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P, SingleDeviceSharding

from . import profiler
from .io import DataBatch, DataIter
from .ndarray.ndarray import NDArray
from .observability import tracer

__all__ = ["DeviceFeed", "feed_enabled", "default_depth", "maybe_device_feed"]


def feed_enabled() -> bool:
    """The ``MXTPU_DEVICE_FEED`` opt-out gate (read at call time so tests and
    launch scripts can flip it per run)."""
    return os.environ.get("MXTPU_DEVICE_FEED", "1").lower() not in (
        "0", "false", "off")


def default_depth() -> int:
    """Prefetch depth: how many batches may be device-resident ahead of the
    consumer (``MXTPU_FEED_DEPTH``, default 2 — double buffering)."""
    try:
        return max(1, int(os.environ.get("MXTPU_FEED_DEPTH", "2")))
    except ValueError:
        return 2


def maybe_device_feed(data_iter, depth: Optional[int] = None, placement=None):
    """Wrap ``data_iter`` in a :class:`DeviceFeed` unless the env gate is off
    or it is already one. ``Module.fit`` routes its train iterator through
    this — the feed is THE path, not an opt-in. Iterator-declared knobs
    (``ImageRecordIter``'s ``prefetch_buffer`` → ``device_feed_depth``
    attribute) propagate into the wrapper automatically."""
    if not feed_enabled() or isinstance(data_iter, DeviceFeed):
        return data_iter
    if depth is None:
        depth = getattr(data_iter, "device_feed_depth", None)
    return DeviceFeed(data_iter, depth=depth, placement=placement)


class _Generation:
    """One producer lifetime. The thread receives this object's queue and
    stop flag as call arguments, so after ``reset()`` abandons a generation a
    straggler can only ever see ITS queue/stop — never the replacement's
    (the stale-batch race the old ``PrefetchingIter.reset`` had when a
    timed-out join left a producer blocked on the swapped-out queue)."""

    __slots__ = ("queue", "stop", "thread", "error")

    def __init__(self, depth: int):
        self.queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self.stop = threading.Event()
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None

    def put(self, item) -> bool:
        """Stop-aware bounded put; False once this generation is abandoned."""
        while not self.stop.is_set():
            try:
                self.queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False


class DeviceFeed(DataIter):
    """Async device-resident prefetcher over any batch source.

    ``data_iter`` may be a ``DataIter`` (yields ``DataBatch``; resettable →
    usable across epochs), or any iterable of arrays / ``(x, y)`` tuples /
    ``DataBatch`` (single pass). ``placement`` selects the device boundary:

    * ``None`` — commit to the process default device (what ``nd.array``
      lands on, so feed-on/off is bit-exact and signature-stable);
    * a jax ``Device`` or ``mxtpu.Context`` — commit there;
    * a ``jax.sharding.Mesh`` — batch-axis ``NamedSharding`` over the mesh's
      first axis (``parallel.shard_batch`` semantics; non-divisible or
      zero-dim arrays replicate);
    * a ``NamedSharding`` — its mesh + first named axis applied the same way;
    * a callable ``raw -> jax.Array`` — full custom placement.

    Dense ``NDArray``/numpy/jax leaves are staged; anything else (sparse
    batches, scalars) passes through untouched.
    """

    def __init__(self, data_iter, depth: Optional[int] = None, placement=None,
                 axis: int = 0):
        super().__init__(getattr(data_iter, "batch_size", 0))
        self.iter = data_iter
        self.depth = max(1, int(depth)) if depth else default_depth()
        self.axis = axis
        self._placement = placement
        self._gen: Optional[_Generation] = None
        self._warned_uneven = False

    # -- placement ---------------------------------------------------------
    def set_placement(self, placement) -> None:
        """Re-home the device boundary (live elasticity: the elastic
        controller points the feed at the survivor mesh mid-run). Safe to
        call from any thread at any time: the producer reads ``_placement``
        per array, so batches staged before the swap keep their OLD
        sharding — ``parallel.shard_batch`` re-places those transparently
        when the step consumes them, so no staged batch is lost."""
        self._placement = placement

    def _target_for(self, raw):
        """Resolve the placement target for one array (or None to pass a
        custom-callable result through)."""
        pl = self._placement
        if callable(pl) and not isinstance(pl, jax.sharding.Mesh):
            return None  # handled by the callable itself
        if pl is None:
            dev = jax.config.jax_default_device or jax.local_devices()[0]
            return SingleDeviceSharding(dev)
        if isinstance(pl, jax.Device):
            return SingleDeviceSharding(pl)
        jd = getattr(pl, "jax_device", None)  # mxtpu.Context
        if jd is not None:
            return SingleDeviceSharding(jd)
        mesh, name = None, None
        if isinstance(pl, jax.sharding.Mesh):
            mesh, name = pl, pl.axis_names[0]
        elif isinstance(pl, NamedSharding):
            mesh = pl.mesh
            name = next((ax for ax in pl.spec if ax is not None),
                        pl.mesh.axis_names[0])
        if mesh is not None:
            nshard = mesh.shape[name]
            if raw.ndim == 0 or raw.shape[self.axis] % nshard:
                if raw.ndim and not self._warned_uneven:
                    self._warned_uneven = True
                    import logging
                    logging.warning(
                        "DeviceFeed: batch axis %d not divisible by mesh "
                        "axis %r (%d); replicating this array", self.axis,
                        name, nshard)
                return NamedSharding(mesh, P())
            spec = [None] * raw.ndim
            spec[self.axis] = name
            return NamedSharding(mesh, P(*spec))
        raise TypeError(f"DeviceFeed: unsupported placement {pl!r}")

    def _place_raw(self, raw):
        """One array through the boundary. Already-resident arrays (committed
        with the target sharding) are NOT re-transferred — the 'at most one
        host→device transfer per batch' guarantee the CI guard asserts."""
        pl = self._placement
        if callable(pl) and not isinstance(pl, jax.sharding.Mesh):
            t0 = time.perf_counter()
            nbytes = int(getattr(raw, "nbytes", 0))
            placed = pl(raw)
            profiler.record_feed_transfer(
                nbytes, (time.perf_counter() - t0) * 1e3)
            return placed
        target = self._target_for(raw)
        if isinstance(raw, jax.Array) and getattr(raw, "committed", False) \
                and raw.sharding == target:
            profiler.record_feed_resident()
            return raw
        t0 = time.perf_counter()
        nbytes = int(getattr(raw, "nbytes", 0))
        if isinstance(target, NamedSharding):
            # the SAME placement path the training step feeds through:
            # multi-process ranks contribute their local shard only
            from .parallel.data_parallel import place
            placed = place(raw, target)
        else:
            placed = jax.device_put(raw, target)  # non-blocking dispatch
        profiler.record_feed_transfer(nbytes,
                                      (time.perf_counter() - t0) * 1e3)
        return placed

    def _place_arr(self, arr):
        if arr is None:
            return None
        if type(arr) is NDArray:
            return NDArray(self._place_raw(arr.data))
        if isinstance(arr, (np.ndarray, jax.Array)):
            return NDArray(self._place_raw(arr))
        return arr  # sparse batches, scalars, anything exotic: pass through

    def _stage(self, batch):
        """Move one batch's dense leaves through the device boundary,
        preserving the batch structure (pad/index/bucket_key ride along)."""
        if isinstance(batch, DataBatch):
            label = [self._place_arr(a) for a in batch.label] \
                if batch.label is not None else None
            return DataBatch(
                data=[self._place_arr(a) for a in (batch.data or [])],
                label=label, pad=batch.pad, index=batch.index,
                bucket_key=batch.bucket_key, provide_data=batch.provide_data,
                provide_label=batch.provide_label)
        if isinstance(batch, (tuple, list)):
            return type(batch)(self._place_arr(a) for a in batch)
        return self._place_arr(batch)

    # -- producer ----------------------------------------------------------
    def _produce(self, gen: _Generation, src):
        from .resilience import fault_point
        from .resilience.watchdog import heartbeat
        try:
            while not gen.stop.is_set():
                try:
                    batch = next(src)
                except StopIteration:
                    break
                # resilience seam: an injected producer fault takes the same
                # latched-error path a real decode/transfer failure does
                fault_point("feed.produce")
                heartbeat("feed")
                # producer-thread span: one batch through the host→device
                # boundary (its own tid row in the trace, overlapping the
                # consumer's feed/stall spans when the pipeline is behind)
                with tracer.span("feed/transfer", cat="feed"):
                    staged = self._stage(batch)
                batch = None
                from .analysis import sanitize
                if "threads" in sanitize.active():
                    # ownership transition: once delivered, the consumer owns
                    # the batch (and may donate its buffers) — a re-enqueue
                    # here is the hazard the contract above forbids
                    sanitize.assert_fresh_delivery(staged, origin="DeviceFeed")
                if not gen.put(("data", staged)):
                    return
                # donation safety: once the consumer can take the batch, the
                # feeder must hold NO reference a donate_argnums step could
                # race against — and a batch is never re-enqueued
                staged = None
                depth = gen.queue.qsize()
                profiler.record_feed_prefetch(depth)
                tracer.counter("feed/queue_depth", depth)
        except BaseException as e:  # latched: visible even if the put is lost
            gen.error = e
            gen.put(("error", e))
            return
        gen.put(("end", None))

    def _ensure(self) -> _Generation:
        if self._gen is None:
            gen = _Generation(self.depth)
            profiler.set_feed_depth(self.depth)
            gen.thread = threading.Thread(
                target=self._produce, args=(gen, iter(self.iter)),
                daemon=True, name="mxtpu-device-feed")
            gen.thread.start()
            self._gen = gen
        return self._gen

    # -- consumer ----------------------------------------------------------
    def next(self) -> DataBatch:
        gen = self._ensure()
        t0 = time.perf_counter()
        # consumer-side span: how long the step loop waited on the queue —
        # the input-stall metric as a timeline interval
        with tracer.span("feed/stall", cat="feed"):
            while True:
                try:
                    kind, payload = gen.queue.get(timeout=0.1)
                    break
                except queue.Empty:
                    if gen.error is not None:
                        raise gen.error
                    if gen.thread is not None and not gen.thread.is_alive():
                        raise RuntimeError(
                            "DeviceFeed producer thread died without "
                            "delivering a batch or an exception")
        stall_ms = (time.perf_counter() - t0) * 1e3
        if kind == "error":
            raise payload
        if kind == "end":
            raise StopIteration
        profiler.record_feed_consume(stall_ms)
        return payload

    def poll(self, timeout: float = 0.0):
        """Non-blocking consumer: the next staged batch if one is ready
        within ``timeout`` seconds, else ``None``. Producer errors re-raise
        and end-of-stream raises ``StopIteration`` exactly like
        :meth:`next`. This is the serving-engine admission path: the
        scheduler thread drains whatever requests the staging producer has
        made device-resident between decode steps without ever blocking the
        in-flight slot batch."""
        gen = self._ensure()
        t0 = time.perf_counter()
        try:
            if timeout > 0:
                kind, payload = gen.queue.get(timeout=timeout)
            else:
                kind, payload = gen.queue.get_nowait()
        except queue.Empty:
            if gen.error is not None:
                raise gen.error
            return None
        if kind == "error":
            raise payload
        if kind == "end":
            raise StopIteration
        profiler.record_feed_consume((time.perf_counter() - t0) * 1e3)
        return payload

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        """Stop the current producer generation and drop its queue (the
        queued device batches are dropped with it)."""
        gen, self._gen = self._gen, None
        if gen is None:
            return
        gen.stop.set()
        try:  # wake a put blocked on a full queue
            gen.queue.get_nowait()
        except queue.Empty:
            pass
        if gen.thread is not None:
            gen.thread.join(timeout=10)

    def reset(self):
        self.close()
        inner_reset = getattr(self.iter, "reset", None)
        if inner_reset is None:
            raise RuntimeError(
                "DeviceFeed wraps a single-pass iterable (no reset()); "
                "wrap a resettable DataIter for multi-epoch use")
        inner_reset()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- DataIter surface --------------------------------------------------
    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label
