"""Multi-process bring-up — the TPU-native replacement for ps-lite role bootstrap
(``include/mxnet/kvstore.h:257 InitPSEnv``, ``python/mxnet/kvstore_server.py``).

The reference starts scheduler/server/worker processes wired by DMLC_* env vars and
speaks ZMQ push/pull. Here every process is a *worker* peer: ``jax.distributed``
connects them to one coordinator, after which cross-process reduction is an XLA
collective over DCN/ICI (no server role exists — the "server" was only ever the
reduction + updater, which dist-mode KVStore runs identically on every rank).

Env contract (reference DMLC names kept for launcher parity, tools/launch.py):
  DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT — coordinator host/port
  DMLC_NUM_WORKER                      — number of processes
  DMLC_WORKER_ID                       — this process's rank
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["initialize", "auto_initialize", "is_initialized", "rank", "size",
           "shutdown"]

_initialized = False


def _pod_connected() -> bool:
    """Whether ``jax.distributed`` already holds a live coordinator client
    (connected by us or by someone calling ``jax.distributed.initialize``
    directly). Deliberately NOT ``jax.process_count()``: that would
    initialize the local XLA backend, after which a first
    ``jax.distributed.initialize`` is forbidden — the predicate must be
    safe to call from ``initialize()`` itself."""
    try:
        from jax._src import distributed as _jax_distributed
        return _jax_distributed.global_state.client is not None
    except Exception:  # jax internals moved — fall back to the module flag
        return False


def is_initialized() -> bool:
    """Whether the pod connection is up. An externally-connected pod counts,
    and in that case the module flag is synced so predicate and state can't
    diverge: before this fix the predicate returned True while
    ``_initialized`` stayed False, so a later explicit ``initialize()``
    still reached ``jax.distributed.initialize``, which rejects late
    calls."""
    global _initialized
    if not _initialized and _pod_connected():
        _initialized = True
    return _initialized


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Connect this process to the pod (jax.distributed.initialize wrapper).

    Transient bring-up failures (coordinator not yet listening, connection
    races during a gang start) are retried per ``resilience.retry_transient``;
    logic errors (bad addresses, double init) escalate immediately."""
    global _initialized
    if is_initialized():   # also syncs the flag for externally-connected pods
        return
    from .resilience import fault_point, retry_transient

    def _connect():
        fault_point("dist.initialize")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    retry_transient(_connect, label="dist.initialize")
    _initialized = True


def auto_initialize() -> bool:
    """Initialize from the DMLC_* env contract if present; returns whether this is
    a multi-process run.

    Runs at ``import mxtpu`` (InitPSEnv-at-lib-load parity) so it executes BEFORE
    any XLA backend initialization — jax.distributed.initialize rejects later
    calls. Also called defensively by dist-type KVStore construction."""
    global _initialized
    if _initialized:
        return True
    n = os.environ.get("DMLC_NUM_WORKER")
    if n is not None and int(n) > 1 \
            and os.environ.get("DMLC_ROLE", "worker") == "worker":
        uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
        wid = int(os.environ.get("DMLC_WORKER_ID", "0"))
        try:
            initialize(f"{uri}:{port}", int(n), wid)
        except RuntimeError as e:
            if _pod_connected():
                _initialized = True  # someone else already connected the pod
                return True
            raise RuntimeError(
                "mxtpu.dist: DMLC_* env set but the XLA backend was initialized "
                "before the pod connection — import mxtpu (or call "
                "dist.auto_initialize) before any jax computation") from e
        return True
    return jax.process_count() > 1


def rank() -> int:
    return jax.process_index()


def size() -> int:
    return jax.process_count()


def shutdown():
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
