"""Multi-process bring-up — the TPU-native replacement for ps-lite role bootstrap
(``include/mxnet/kvstore.h:257 InitPSEnv``, ``python/mxnet/kvstore_server.py``).

The reference starts scheduler/server/worker processes wired by DMLC_* env vars and
speaks ZMQ push/pull. Here every process is a *worker* peer: ``jax.distributed``
connects them to one coordinator, after which cross-process reduction is an XLA
collective over DCN/ICI (no server role exists — the "server" was only ever the
reduction + updater, which dist-mode KVStore runs identically on every rank).

Env contract (reference DMLC names kept for launcher parity, tools/launch.py):
  DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT — coordinator host/port
  DMLC_NUM_WORKER                      — number of processes
  DMLC_WORKER_ID                       — this process's rank

Rendezvous is factored behind a :class:`Transport` seam so the join/leave/
re-join protocol is testable without a real pod: the default
:class:`JaxTransport` talks to ``jax.distributed``; tests install a mock via
:func:`set_transport` and drive rank loss + re-rendezvous in-process
(``tests/test_elastic_guard.py``). The elastic story rides on two properties
pinned here:

* ``shutdown()`` → ``initialize()`` **re-entry** — both are idempotent and
  keep the module flag synced with the transport's live connection, so a
  rank can leave the pod and re-join (one :func:`rejoin` call) without a
  process restart;
* a monotone :func:`generation` counter — every successful ``initialize``
  bumps it, so layers above (KVStore, elastic controller) can detect that
  the pod membership changed under them and re-derive rank/size.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import jax

__all__ = ["initialize", "auto_initialize", "is_initialized", "rank", "size",
           "shutdown", "rejoin", "generation",
           "Transport", "JaxTransport", "get_transport", "set_transport"]

_lock = threading.Lock()
_initialized = False
_generation = 0


# -- the rendezvous transport seam -------------------------------------------

class Transport:
    """What a rendezvous backend must provide. The contract is deliberately
    tiny — connect/disconnect plus identity — because everything *above* the
    pod connection (collectives, exchange, KVStore) goes through XLA, not
    through this seam."""

    def connect(self, coordinator_address: Optional[str],
                num_processes: Optional[int],
                process_id: Optional[int]) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        raise NotImplementedError

    def connected(self) -> bool:
        raise NotImplementedError

    def process_index(self) -> int:
        raise NotImplementedError

    def process_count(self) -> int:
        raise NotImplementedError


class JaxTransport(Transport):
    """The real thing: ``jax.distributed`` against the pod coordinator."""

    def connect(self, coordinator_address, num_processes, process_id) -> None:
        # CPU backends ship multiprocess collectives (gloo-over-TCP) but jax
        # defaults the implementation to "none", so every process-spanning
        # computation dies with "Multiprocess computations aren't implemented
        # on the CPU backend" — the tier-1 test_dist failure mode. Select
        # gloo before the backend initializes; harmless on TPU (the flag only
        # affects CPU clients) and a no-op if the backend is already up.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    def disconnect(self) -> None:
        jax.distributed.shutdown()

    def connected(self) -> bool:
        """Whether ``jax.distributed`` already holds a live coordinator
        client (connected by us or by someone calling
        ``jax.distributed.initialize`` directly). Deliberately NOT
        ``jax.process_count()``: that would initialize the local XLA
        backend, after which a first ``jax.distributed.initialize`` is
        forbidden — the predicate must be safe to call from
        ``initialize()`` itself."""
        try:
            from jax._src import distributed as _jax_distributed
            return _jax_distributed.global_state.client is not None
        except Exception:  # jax internals moved — fall back to module flag
            return False

    def process_index(self) -> int:
        return jax.process_index()

    def process_count(self) -> int:
        return jax.process_count()


_transport: Transport = JaxTransport()


def get_transport() -> Transport:
    return _transport


def set_transport(transport: Transport) -> Transport:
    """Install a rendezvous backend (tests: a mock coordinator), returning
    the previous one so callers can restore it. Resets the initialized flag
    — the new transport's ``connected()`` is the source of truth from here."""
    global _transport, _initialized
    with _lock:
        prev, _transport = _transport, transport
        _initialized = False
    return prev


def generation() -> int:
    """Monotone rendezvous generation: bumped by every successful
    ``initialize`` (including re-joins), 0 before the first. Layers that
    cache rank/size or per-pod programs compare generations to notice that
    membership changed."""
    return _generation


# -- lifecycle ---------------------------------------------------------------

def is_initialized() -> bool:
    """Whether the pod connection is up. An externally-connected pod counts,
    and in that case the module flag is synced so predicate and state can't
    diverge: before this fix the predicate returned True while
    ``_initialized`` stayed False, so a later explicit ``initialize()``
    still reached ``jax.distributed.initialize``, which rejects late
    calls."""
    global _initialized
    if not _initialized and _transport.connected():
        _initialized = True
    return _initialized


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Connect this process to the pod (rendezvous through the installed
    :class:`Transport`; by default ``jax.distributed.initialize``).

    Idempotent — a second call on a live connection is a no-op, INCLUDING
    after :func:`shutdown` ran in between: the shutdown→initialize re-entry
    pair is the rank leave/re-join protocol live elasticity depends on.
    Transient bring-up failures (coordinator not yet listening, connection
    races during a gang start) are retried per ``resilience.retry_transient``;
    logic errors (bad addresses, double init) escalate immediately."""
    global _initialized, _generation
    if is_initialized():   # also syncs the flag for externally-connected pods
        return
    from .resilience import fault_point, retry_transient

    def _connect():
        fault_point("dist.initialize")
        _transport.connect(coordinator_address, num_processes, process_id)

    retry_transient(_connect, label="dist.initialize")
    with _lock:
        _initialized = True
        _generation += 1


def auto_initialize() -> bool:
    """Initialize from the DMLC_* env contract if present; returns whether this is
    a multi-process run.

    Runs at ``import mxtpu`` (InitPSEnv-at-lib-load parity) so it executes BEFORE
    any XLA backend initialization — jax.distributed.initialize rejects later
    calls. Also called defensively by dist-type KVStore construction."""
    global _initialized
    if _initialized:
        return True
    n = os.environ.get("DMLC_NUM_WORKER")
    if n is not None and int(n) > 1 \
            and os.environ.get("DMLC_ROLE", "worker") == "worker":
        uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
        wid = int(os.environ.get("DMLC_WORKER_ID", "0"))
        try:
            initialize(f"{uri}:{port}", int(n), wid)
        except RuntimeError as e:
            if _transport.connected():
                _initialized = True  # someone else already connected the pod
                return True
            raise RuntimeError(
                "mxtpu.dist: DMLC_* env set but the XLA backend was initialized "
                "before the pod connection — import mxtpu (or call "
                "dist.auto_initialize) before any jax computation") from e
        return True
    return jax.process_count() > 1


def rank() -> int:
    return _transport.process_index()


def size() -> int:
    return _transport.process_count()


def shutdown():
    """Leave the pod. Idempotent: a no-op when nothing is connected, so
    teardown paths can call it unconditionally. After shutdown the module is
    back in its pre-initialize state — :func:`initialize` may be called
    again (re-join), which bumps :func:`generation`."""
    global _initialized
    if is_initialized():   # syncs the flag for externally-connected pods
        _transport.disconnect()
        with _lock:
            _initialized = False


def rejoin(coordinator_address: Optional[str] = None,
           num_processes: Optional[int] = None,
           process_id: Optional[int] = None) -> int:
    """Leave and re-enter the pod in one call — the re-rendezvous a rank
    performs after the coordinator reports membership change (peer loss, or
    this rank rejoining after an elastic shrink). Returns the new
    :func:`generation`. ``num_processes``/``process_id`` normally differ
    from the previous join — that is the point."""
    shutdown()
    initialize(coordinator_address=coordinator_address,
               num_processes=num_processes, process_id=process_id)
    return _generation
