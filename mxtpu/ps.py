"""Host-side asynchronous parameter server — the ``dist_async`` backend.

Reference: ps-lite's server role applied updates the moment each worker's push
arrived (``kvstore_dist_server.h`` async mode: no ``ps::NumWorkers()`` wait, in
contrast to sync's aggregate-then-apply at :283-295), giving Hogwild-style
asynchronous SGD across workers. XLA collectives cannot express that — they
are bulk-synchronous — so the TPU-native design runs the server where the
reference ran it: ON THE HOST. Rank 0 owns a TCP server thread holding the
authoritative numpy copy of every key; workers' pushes apply the (pickled,
importable) optimizer immediately on arrival; pulls read the current state.
The accelerators stay busy on compute while parameter traffic rides the host
NIC exactly like ps-lite's ZMQ transport.

Wire protocol (little-endian, no pickle except the SET_OPTIMIZER payload):
  request  = u8 cmd | u16 keylen | key utf8 | u32 metalen | meta | u64 len | payload
  response = u8 status | u32 metalen | meta | u64 len | payload
meta is the ascii "dtype:shape,shape,..." descriptor of the array payload.
Commands: 0 INIT (first-wins), 1 PUSH (apply updater), 2 PULL, 3 SET_OPTIMIZER
(pickled mxtpu optimizer), 4 BARRIER (blocks until world_size arrivals).
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["ParamServer", "PSClient", "start_server", "default_port"]

(CMD_INIT, CMD_PUSH, CMD_PULL, CMD_SET_OPT, CMD_BARRIER, CMD_GET_STATES,
 CMD_SET_STATES) = range(7)
STATUS_OK, STATUS_ERR = 0, 1


def default_port() -> int:
    """PS port derived from the launcher contract (coordinator port + 1)."""
    import os
    return int(os.environ.get("MXTPU_PS_PORT",
                              int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
                              + 1))


# ---- framing ---------------------------------------------------------------
def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _encode_array(arr: Optional[np.ndarray]) -> Tuple[bytes, bytes]:
    if arr is None:
        return b"", b""
    meta = f"{arr.dtype.str}:{','.join(map(str, arr.shape))}".encode()
    return meta, np.ascontiguousarray(arr).tobytes()


def _decode_array(meta: bytes, payload: bytes) -> Optional[np.ndarray]:
    if not meta:
        return None
    dtype_s, shape_s = meta.decode().split(":")
    shape = tuple(int(d) for d in shape_s.split(",")) if shape_s else ()
    return np.frombuffer(payload, dtype=np.dtype(dtype_s)).reshape(shape).copy()


def _send_msg(sock: socket.socket, head: bytes, meta: bytes, payload: bytes):
    sock.sendall(head + struct.pack("<I", len(meta)) + meta +
                 struct.pack("<Q", len(payload)) + payload)


class ParamServer:
    """The rank-0 server thread pool (one thread per worker connection)."""

    def __init__(self, port: int, world_size: int):
        self.world_size = world_size
        self._store: Dict[str, np.ndarray] = {}
        self._updater = None          # (key, grad ndarray, stored NDArray-like)
        self._updater_obj = None      # the Updater (state save/load)
        self._lock = threading.Lock()
        self._barrier = threading.Barrier(world_size)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(world_size + 4)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="mxtpu-ps-accept")
        t.start()
        self._threads.append(t)

    # -- server internals --------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True,
                                 name="mxtpu-ps-conn")
            t.start()
            self._threads.append(t)

    def _apply_push(self, key: str, grad: np.ndarray):
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push before init for key {key!r}")
            if self._updater is not None:
                self._updater(key, grad, stored)      # in-place on stored
            else:
                stored += grad                        # default: accumulate

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                head = _recv_exact(conn, 3)
                cmd, keylen = head[0], struct.unpack("<H", head[1:3])[0]
                key = _recv_exact(conn, keylen).decode() if keylen else ""
                (metalen,) = struct.unpack("<I", _recv_exact(conn, 4))
                meta = _recv_exact(conn, metalen)
                (plen,) = struct.unpack("<Q", _recv_exact(conn, 8))
                payload = _recv_exact(conn, plen)
                status, rmeta, rpayload = STATUS_OK, b"", b""
                try:
                    if cmd == CMD_INIT:
                        val = _decode_array(meta, payload)
                        with self._lock:
                            self._store.setdefault(key, val)   # first wins
                    elif cmd == CMD_PUSH:
                        self._apply_push(key, _decode_array(meta, payload))
                    elif cmd == CMD_PULL:
                        # encode UNDER the lock: concurrent pushes mutate the
                        # stored buffer in place; encoding outside would ship
                        # a torn snapshot
                        with self._lock:
                            val = self._store.get(key)
                            if val is None:
                                raise KeyError(f"pull before init: {key!r}")
                            rmeta, rpayload = _encode_array(val)
                    elif cmd == CMD_SET_OPT:
                        self._set_optimizer_bytes(payload)
                    elif cmd == CMD_BARRIER:
                        try:
                            self._barrier.wait(timeout=300)
                        except threading.BrokenBarrierError:
                            # a peer died or timed out; replace the barrier so
                            # the job (or the next one on this singleton) can
                            # still synchronize, and report clearly
                            with self._lock:
                                if self._barrier.broken:
                                    self._barrier = threading.Barrier(
                                        self.world_size)
                            raise RuntimeError(
                                "barrier broken: a worker exited or timed "
                                "out while peers waited")
                    elif cmd == CMD_GET_STATES:
                        with self._lock:
                            if self._updater_obj is None:
                                raise RuntimeError("no optimizer set on server")
                            rpayload = self._updater_obj.get_states()
                    elif cmd == CMD_SET_STATES:
                        with self._lock:
                            if self._updater_obj is None:
                                raise RuntimeError("no optimizer set on server")
                            self._updater_obj.set_states(payload)
                    else:
                        raise ValueError(f"unknown cmd {cmd}")
                except Exception as e:  # report, keep serving
                    status = STATUS_ERR
                    rmeta, rpayload = b"", repr(e).encode()
                _send_msg(conn, bytes([status]), rmeta, rpayload)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _set_optimizer_bytes(self, payload: bytes):
        from . import optimizer as opt_mod
        opt = pickle.loads(payload)
        updater = opt_mod.get_updater(opt)

        def apply(key, grad, stored):
            from .ndarray.ndarray import NDArray
            import jax.numpy as jnp
            w = NDArray(jnp.asarray(stored))
            updater(key, NDArray(jnp.asarray(grad)), w)
            stored[...] = np.asarray(w.data)

        with self._lock:
            self._updater = apply
            self._updater_obj = updater

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class PSClient:
    """One worker's persistent connection to the parameter server."""

    def __init__(self, host: str, port: int, timeout: float = 300.0,
                 retries: int = 50):
        import time
        last = None
        for _ in range(retries):           # the server may still be binding
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout)
                break
            except OSError as e:
                last = e
                time.sleep(0.1)
        else:
            raise ConnectionError(f"cannot reach param server "
                                  f"{host}:{port}: {last}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _request_raw(self, cmd: int, key: str = "",
                     arr: Optional[np.ndarray] = None,
                     raw: bytes = b"") -> Tuple[bytes, bytes]:
        kb = key.encode()
        meta, payload = _encode_array(arr)
        if raw:
            payload = raw
        with self._lock:
            _send_msg(self._sock,
                      bytes([cmd]) + struct.pack("<H", len(kb)) + kb,
                      meta, payload)
            status = _recv_exact(self._sock, 1)[0]
            (metalen,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            rmeta = _recv_exact(self._sock, metalen)
            (plen,) = struct.unpack("<Q", _recv_exact(self._sock, 8))
            rpayload = _recv_exact(self._sock, plen)
        if status != STATUS_OK:
            raise RuntimeError(f"param server error: {rpayload.decode()}")
        return rmeta, rpayload

    def _request(self, cmd: int, key: str = "",
                 arr: Optional[np.ndarray] = None,
                 raw: bytes = b"") -> Optional[np.ndarray]:
        return _decode_array(*self._request_raw(cmd, key, arr, raw))

    def init(self, key: str, value: np.ndarray):
        self._request(CMD_INIT, key, value)

    def push(self, key: str, grad: np.ndarray):
        self._request(CMD_PUSH, key, grad)

    def pull(self, key: str) -> np.ndarray:
        return self._request(CMD_PULL, key)

    def set_optimizer(self, optimizer):
        self._request(CMD_SET_OPT, "", raw=pickle.dumps(optimizer))

    def get_optimizer_states(self) -> bytes:
        return self._request_raw(CMD_GET_STATES)[1]

    def set_optimizer_states(self, states: bytes):
        self._request(CMD_SET_STATES, "", raw=states)

    def barrier(self):
        self._request(CMD_BARRIER)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


_server: Optional[ParamServer] = None
_server_lock = threading.Lock()


def start_server(port: int, world_size: int) -> ParamServer:
    """Start (once) the in-process server — called on rank 0."""
    global _server
    with _server_lock:
        if _server is None:
            _server = ParamServer(port, world_size)
        return _server
