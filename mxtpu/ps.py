"""Host-side asynchronous parameter server — the ``dist_async`` backend.

Reference: ps-lite's server role applied updates the moment each worker's push
arrived (``kvstore_dist_server.h`` async mode: no ``ps::NumWorkers()`` wait, in
contrast to sync's aggregate-then-apply at :283-295), giving Hogwild-style
asynchronous SGD across workers. XLA collectives cannot express that — they
are bulk-synchronous — so the TPU-native design runs the server where the
reference ran it: ON THE HOST. Rank 0 owns a TCP server thread holding the
authoritative numpy copy of every key; workers' pushes apply the serialized
optimizer immediately on arrival; pulls read the current state.
The accelerators stay busy on compute while parameter traffic rides the host
NIC exactly like ps-lite's ZMQ transport.

Wire protocol (little-endian):
  request  = u8 cmd | u16 keylen | key utf8 | u32 metalen | meta | u64 len | payload
  response = u8 status | u32 metalen | meta | u64 len | payload
meta is the ascii "dtype:shape,shape,..." descriptor of the array payload.
Commands: 0 INIT (first-wins), 1 PUSH (apply updater), 2 PULL, 3 SET_OPTIMIZER,
4 BARRIER (blocks until world_size arrivals).

The SET_OPTIMIZER payload is a restricted spec — ``b"J" + json`` carrying the
optimizer's registry name and its captured constructor arguments (re-instantiated
through ``optimizer.create``; LR schedulers are encoded the same way, resolved
only against ``mxtpu.lr_scheduler`` classes). Arbitrary pickle is NOT accepted
unless both sides share ``MXTPU_PS_SECRET``, in which case an HMAC-SHA256-signed
pickle (``b"P" + mac + body``) is allowed for exotic optimizers whose ctor args
aren't JSON scalars. The server binds the interface named by DMLC_PS_ROOT_URI
(default loopback), not 0.0.0.0 — unauthenticated remote reachability plus
pickle was an RCE surface (round-3 advisor finding).
"""

from __future__ import annotations

import hmac
import json
import os
import pickle
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["ParamServer", "PSClient", "start_server", "default_port"]

(CMD_INIT, CMD_PUSH, CMD_PULL, CMD_SET_OPT, CMD_BARRIER, CMD_GET_STATES,
 CMD_SET_STATES, CMD_PULL_ROWS, CMD_PUSH_ROWS) = range(9)
STATUS_OK, STATUS_ERR = 0, 1


def default_port() -> int:
    """PS port derived from the launcher contract (coordinator port + 1)."""
    return int(os.environ.get("MXTPU_PS_PORT",
                              int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
                              + 1))


def default_bind_host() -> str:
    """The interface the server binds: the launcher's root URI (it names rank
    0's address), falling back to loopback — never 0.0.0.0."""
    return os.environ.get("MXTPU_PS_BIND",
                          os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"))


# ---- restricted optimizer serialization ------------------------------------
def _spec_value(v):
    """JSON-encode one ctor argument; LRSchedulers become tagged specs."""
    from . import lr_scheduler as lrs_mod
    if isinstance(v, lrs_mod.LRScheduler):
        if getattr(lrs_mod, type(v).__name__, None) is not type(v):
            # a user-defined scheduler would serialize by bare name but could
            # never resolve server-side — fail here so the signed-pickle
            # fallback is actually reachable
            raise TypeError(f"scheduler {type(v).__name__} is not an "
                            f"mxtpu.lr_scheduler class")
        args, kwargs = getattr(v, "_init_spec", ((), {}))
        return {"__lr_scheduler__": type(v).__name__,
                "args": [_spec_value(a) for a in args],
                "kwargs": {k: _spec_value(x) for k, x in kwargs.items()}}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_spec_value(x) for x in v]
    raise TypeError(f"cannot serialize optimizer ctor argument {v!r} for the "
                    f"restricted wire format")


def _spec_resolve(v):
    from . import lr_scheduler as lrs_mod
    if isinstance(v, dict) and "__lr_scheduler__" in v:
        cls = getattr(lrs_mod, v["__lr_scheduler__"], None)
        if cls is None or not (isinstance(cls, type)
                               and issubclass(cls, lrs_mod.LRScheduler)):
            raise ValueError(f"unknown lr scheduler {v['__lr_scheduler__']!r}")
        return cls(*[_spec_resolve(a) for a in v["args"]],
                   **{k: _spec_resolve(x) for k, x in v["kwargs"].items()})
    if isinstance(v, list):
        return [_spec_resolve(x) for x in v]
    return v


#: post-construction attributes the JSON wire format carries (everything else
#: must come from the ctor spec — see serialize_optimizer)
_CARRIED_STATE = ("lr", "wd", "rescale_grad", "clip_gradient", "num_update",
                  "lr_mult", "wd_mult")

#: deliberately NOT carried and not an error: client-side bookkeeping the
#: server-side updater never consults (gluon Trainer sets param_dict on every
#: dist run; the server applies updates by key, not Parameter object)
_UNCARRIED_OK = ("param_dict", "idx2name", "sym_info")

#: sub-object attrs the state dict carries explicitly, so their in-place
#: mutation is fine (see "sched_base_lr" in serialize/deserialize)
_CARRIED_SUBATTRS = {"lr_scheduler": ("base_lr",)}


def _attr_equal(a, b, exclude=()) -> bool:
    from .base import ObjSnap
    if isinstance(b, ObjSnap):
        # spec-captured sub-object (e.g. lr_scheduler): same object AND its
        # public attrs unchanged since __init__ — the wire re-creates it from
        # its ctor spec, so in-place edits would silently diverge
        if a is not b.obj:
            return False
        live = {k: v for k, v in vars(a).items()
                if not k.startswith("_") and k not in exclude}
        attrs = {k: v for k, v in b.attrs.items() if k not in exclude}
        return (live.keys() == attrs.keys()
                and all(_attr_equal(live[k], v) for k, v in attrs.items()))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray) \
            or hasattr(a, "__jax_array__") or type(a).__module__.startswith("jax") \
            or type(b).__module__.startswith("jax"):
        try:
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        except Exception:
            return a is b
    try:
        return bool(a == b)
    except Exception:
        return a is b


def serialize_optimizer(opt) -> bytes:
    """Optimizer → wire bytes: restricted JSON spec, or HMAC-signed pickle when
    MXTPU_PS_SECRET is shared (for ctor args the JSON form can't carry).

    Carried-state contract: the JSON form ships the ctor ``(args, kwargs)``
    plus the ``_CARRIED_STATE`` attributes only (``_UNCARRIED_OK`` names are
    client-side bookkeeping and intentionally dropped). Any OTHER
    post-construction attribute mutation (e.g. ``opt.momentum = x`` after
    ``__init__``) is detected by diffing against the post-``__init__``
    snapshot (``base.capture_init_spec``) and raises — set MXTPU_PS_SECRET
    for pickle transport of such optimizers."""
    from . import optimizer as opt_mod
    try:
        name = next(k for k, c in opt_mod.registry._registry.items()
                    if c is type(opt))
        args, kwargs = opt._init_spec   # always set (base __init__ captures)
        snap = getattr(opt, "_post_init_attrs", None)
        for attr, val in vars(opt).items():
            if (snap is None or attr.startswith("_")
                    or attr in _CARRIED_STATE or attr in _UNCARRIED_OK):
                continue
            if attr not in snap or not _attr_equal(
                    val, snap[attr], _CARRIED_SUBATTRS.get(attr, ())):
                raise TypeError(
                    f"post-construction mutation of {attr!r} is not carried "
                    f"by the JSON wire format")
        sched = opt.lr_scheduler
        spec = {"name": name, "args": [_spec_value(a) for a in args],
                "kwargs": {k: _spec_value(v) for k, v in kwargs.items()},
                # post-construction mutations the ctor spec can't carry
                # (reference pickle transport shipped the whole object)
                "state": {"lr": opt.lr, "wd": opt.wd,
                          "sched_base_lr":
                              None if sched is None else sched.base_lr,
                          "rescale_grad": opt.rescale_grad,
                          "clip_gradient": opt.clip_gradient,
                          "num_update": opt.num_update,
                          "lr_mult": [[_spec_value(k), v]
                                      for k, v in opt.lr_mult.items()],
                          "wd_mult": [[_spec_value(k), v]
                                      for k, v in opt.wd_mult.items()]}}
        return b"J" + json.dumps(spec).encode()
    except (TypeError, StopIteration) as e:
        secret = os.environ.get("MXTPU_PS_SECRET", "")
        if not secret:
            raise TypeError(
                f"optimizer {type(opt).__name__} cannot use the restricted "
                f"wire format ({e}); set MXTPU_PS_SECRET on every rank to "
                f"allow HMAC-authenticated pickle transport") from e
        body = pickle.dumps(opt)
        mac = hmac.new(secret.encode(), body, "sha256").digest()
        return b"P" + mac + body


def deserialize_optimizer(payload: bytes):
    from . import optimizer as opt_mod
    tag, body = payload[:1], payload[1:]
    if tag == b"J":
        spec = json.loads(body.decode())
        opt = opt_mod.registry.get(spec["name"])(
            *[_spec_resolve(a) for a in spec["args"]],
            **{k: _spec_resolve(v) for k, v in spec["kwargs"].items()})
        st = spec.get("state")
        if st:
            opt.set_learning_rate(st["lr"])
            if st.get("sched_base_lr") is not None \
                    and opt.lr_scheduler is not None:
                opt.lr_scheduler.base_lr = st["sched_base_lr"]
            opt.wd = st["wd"]
            opt.rescale_grad = st["rescale_grad"]
            opt.clip_gradient = st["clip_gradient"]
            opt.num_update = st["num_update"]
            opt.set_lr_mult({k: v for k, v in st["lr_mult"]})
            opt.set_wd_mult({k: v for k, v in st["wd_mult"]})
        return opt
    if tag == b"P":
        secret = os.environ.get("MXTPU_PS_SECRET", "")
        if not secret:
            raise PermissionError(
                "signed-pickle optimizer payload refused: MXTPU_PS_SECRET is "
                "not set on the server")
        mac, body = body[:32], body[32:]
        if not hmac.compare_digest(
                mac, hmac.new(secret.encode(), body, "sha256").digest()):
            raise PermissionError("optimizer payload HMAC mismatch")
        return pickle.loads(body)
    raise ValueError("unrecognized optimizer payload (legacy raw pickle is "
                     "no longer accepted)")


# ---- framing ---------------------------------------------------------------
def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _dtype_token(dt: np.dtype) -> str:
    """Wire token for a dtype: numpy's .str for standard dtypes, the NAME for
    extension dtypes (bfloat16's .str is an opaque '<V2' that cannot
    round-trip)."""
    return dt.name if dt.kind == "V" else dt.str


def _dtype_from_token(tok: str) -> np.dtype:
    try:
        return np.dtype(tok)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, tok))


def _encode_array(arr: Optional[np.ndarray]) -> Tuple[bytes, bytes]:
    if arr is None:
        return b"", b""
    meta = f"{_dtype_token(arr.dtype)}:{','.join(map(str, arr.shape))}".encode()
    return meta, np.ascontiguousarray(arr).tobytes()


def _decode_array(meta: bytes, payload: bytes) -> Optional[np.ndarray]:
    if not meta:
        return None
    dtype_s, shape_s = meta.decode().split(":")
    shape = tuple(int(d) for d in shape_s.split(",")) if shape_s else ()
    return np.frombuffer(payload, dtype=_dtype_from_token(dtype_s)) \
        .reshape(shape).copy()


def _send_msg(sock: socket.socket, head: bytes, meta: bytes, payload: bytes):
    sock.sendall(head + struct.pack("<I", len(meta)) + meta +
                 struct.pack("<Q", len(payload)) + payload)


def _encode_rows_vals(rows: np.ndarray, vals: np.ndarray) -> Tuple[bytes, bytes]:
    """Rows + values in one frame: meta = '<vals meta>|<n rows>', payload =
    int64 row ids then the value bytes — the O(rows) sparse wire format
    (EncodeRowSparseKey parity, kvstore_dist.h:236)."""
    vmeta, vbytes = _encode_array(vals)
    rows = np.ascontiguousarray(rows, np.int64)
    return vmeta + b"|" + str(rows.size).encode(), rows.tobytes() + vbytes


def _decode_rows_vals(meta: bytes, payload: bytes) -> Tuple[np.ndarray, np.ndarray]:
    vmeta, n = meta.rsplit(b"|", 1)
    n = int(n)
    rows = np.frombuffer(payload[:8 * n], np.int64).copy()
    return rows, _decode_array(vmeta, payload[8 * n:])


class ParamServer:
    """The rank-0 server thread pool (one thread per worker connection)."""

    def __init__(self, port: int, world_size: int, host: Optional[str] = None):
        self.world_size = world_size
        host = host if host is not None else default_bind_host()
        self._store: Dict[str, np.ndarray] = {}
        self._updater = None          # (key, grad ndarray, stored NDArray-like)
        self._updater_obj = None      # the Updater (state save/load)
        self._lock = threading.Lock()
        self._barrier = threading.Barrier(world_size)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(world_size + 4)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="mxtpu-ps-accept")
        t.start()
        self._threads.append(t)

    # -- server internals --------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True,
                                 name="mxtpu-ps-conn")
            t.start()
            self._threads.append(t)

    def _apply_push(self, key: str, grad: np.ndarray):
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push before init for key {key!r}")
            if self._updater is not None:
                self._updater(key, grad, stored)      # in-place on stored
            else:
                stored += grad                        # default: accumulate

    @staticmethod
    def _check_rows(rows: np.ndarray, nrows: int, key: str):
        """Wire row ids are untrusted: negative int64 ids would wrap through
        numpy indexing and silently touch the wrong rows."""
        if rows.size and (rows.min() < 0 or rows.max() >= nrows):
            raise ValueError(
                f"row ids out of range for key {key!r}: "
                f"[{rows.min()}, {rows.max()}] vs {nrows} stored rows")

    def _apply_push_rows(self, key: str, rows: np.ndarray, vals: np.ndarray):
        """Row-subset push: only the shipped rows touch the stored value —
        with an optimizer set, its lazy row-sparse path runs on the row slab
        (kvstore_dist_server.h row_sparse async parity)."""
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push before init for key {key!r}")
            self._check_rows(rows, stored.shape[0], key)
            if self._updater is not None:
                self._updater(key, (rows, vals), stored)
            else:
                np.add.at(stored, rows, vals)

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                head = _recv_exact(conn, 3)
                cmd, keylen = head[0], struct.unpack("<H", head[1:3])[0]
                key = _recv_exact(conn, keylen).decode() if keylen else ""
                (metalen,) = struct.unpack("<I", _recv_exact(conn, 4))
                meta = _recv_exact(conn, metalen)
                (plen,) = struct.unpack("<Q", _recv_exact(conn, 8))
                payload = _recv_exact(conn, plen)
                status, rmeta, rpayload = STATUS_OK, b"", b""
                try:
                    if cmd == CMD_INIT:
                        val = _decode_array(meta, payload)
                        with self._lock:
                            self._store.setdefault(key, val)   # first wins
                    elif cmd == CMD_PUSH:
                        self._apply_push(key, _decode_array(meta, payload))
                    elif cmd == CMD_PUSH_ROWS:
                        self._apply_push_rows(
                            key, *_decode_rows_vals(meta, payload))
                    elif cmd == CMD_PULL_ROWS:
                        rows = _decode_array(meta, payload).astype(np.int64)
                        with self._lock:
                            val = self._store.get(key)
                            if val is None:
                                raise KeyError(f"pull before init: {key!r}")
                            self._check_rows(rows, val.shape[0], key)
                            rmeta, rpayload = _encode_array(val[rows])
                    elif cmd == CMD_PULL:
                        # encode UNDER the lock: concurrent pushes mutate the
                        # stored buffer in place; encoding outside would ship
                        # a torn snapshot
                        with self._lock:
                            val = self._store.get(key)
                            if val is None:
                                raise KeyError(f"pull before init: {key!r}")
                            rmeta, rpayload = _encode_array(val)
                    elif cmd == CMD_SET_OPT:
                        self._set_optimizer_bytes(payload)
                    elif cmd == CMD_BARRIER:
                        try:
                            self._barrier.wait(timeout=300)
                        except threading.BrokenBarrierError:
                            # a peer died or timed out; replace the barrier so
                            # the job (or the next one on this singleton) can
                            # still synchronize, and report clearly
                            with self._lock:
                                if self._barrier.broken:
                                    self._barrier = threading.Barrier(
                                        self.world_size)
                            raise RuntimeError(
                                "barrier broken: a worker exited or timed "
                                "out while peers waited")
                    elif cmd == CMD_GET_STATES:
                        with self._lock:
                            if self._updater_obj is None:
                                raise RuntimeError("no optimizer set on server")
                            rpayload = self._updater_obj.get_states()
                    elif cmd == CMD_SET_STATES:
                        with self._lock:
                            if self._updater_obj is None:
                                raise RuntimeError("no optimizer set on server")
                            self._updater_obj.set_states(payload)
                    else:
                        raise ValueError(f"unknown cmd {cmd}")
                except Exception as e:  # report, keep serving
                    status = STATUS_ERR
                    rmeta, rpayload = b"", repr(e).encode()
                _send_msg(conn, bytes([status]), rmeta, rpayload)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _set_optimizer_bytes(self, payload: bytes):
        from . import optimizer as opt_mod
        opt = deserialize_optimizer(payload)
        updater = opt_mod.get_updater(opt)

        def apply(key, grad, stored):
            from .ndarray.ndarray import NDArray
            from .ndarray import sparse as sp
            import jax.numpy as jnp
            w = NDArray(jnp.asarray(stored))
            if isinstance(grad, tuple):        # (rows, vals): lazy sparse path
                rows, vals = grad
                # wire rows are untrusted: merge duplicates host-side (cheap,
                # already numpy) so device consumers can skip their defensive
                # merge via the _trusted invariant
                rows = np.asarray(rows)
                vals = np.asarray(vals)
                uniq, inv = np.unique(rows, return_inverse=True)
                if uniq.size != rows.size:
                    summed = np.zeros((uniq.size,) + vals.shape[1:], vals.dtype)
                    np.add.at(summed, inv, vals)
                    rows, vals = uniq, summed
                else:
                    # _trusted promises sorted-unique: reorder even when
                    # already unique (wire order is arbitrary)
                    rows, vals = uniq, vals[np.argsort(inv, kind="stable")]
                g = sp.RowSparseNDArray._trusted(rows, jnp.asarray(vals),
                                                 stored.shape)
            else:
                g = NDArray(jnp.asarray(grad))
            updater(key, g, w)
            stored[...] = np.asarray(w.data)

        with self._lock:
            self._updater = apply
            self._updater_obj = updater

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class PSClient:
    """One worker's persistent connection to the parameter server."""

    def __init__(self, host: str, port: int, timeout: float = 300.0,
                 connect_deadline: float = 60.0):
        import time
        # time-based deadline, not a fixed attempt count: rank 0's server may
        # take tens of seconds to come up in multi-host launches
        deadline = time.monotonic() + connect_deadline
        last = None
        while True:
            # cap each attempt at the remaining deadline so a blackholed SYN
            # can't stretch one connect() past the promised window
            remaining = deadline - time.monotonic()
            try:
                self._sock = socket.create_connection(
                    (host, port), timeout=min(timeout, max(0.5, remaining)))
                self._sock.settimeout(timeout)   # operational timeout
                break
            except OSError as e:
                last = e
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"cannot reach param server {host}:{port} within "
                        f"{connect_deadline:.0f}s: {last}") from e
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _request_raw(self, cmd: int, key: str = "",
                     arr: Optional[np.ndarray] = None,
                     raw: bytes = b"",
                     frame: Optional[Tuple[bytes, bytes]] = None
                     ) -> Tuple[bytes, bytes]:
        kb = key.encode()
        meta, payload = frame if frame is not None else _encode_array(arr)
        if raw:
            payload = raw
        with self._lock:
            _send_msg(self._sock,
                      bytes([cmd]) + struct.pack("<H", len(kb)) + kb,
                      meta, payload)
            status = _recv_exact(self._sock, 1)[0]
            (metalen,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            rmeta = _recv_exact(self._sock, metalen)
            (plen,) = struct.unpack("<Q", _recv_exact(self._sock, 8))
            rpayload = _recv_exact(self._sock, plen)
        if status != STATUS_OK:
            raise RuntimeError(f"param server error: {rpayload.decode()}")
        return rmeta, rpayload

    def _request(self, cmd: int, key: str = "",
                 arr: Optional[np.ndarray] = None,
                 raw: bytes = b"") -> Optional[np.ndarray]:
        return _decode_array(*self._request_raw(cmd, key, arr, raw))

    def init(self, key: str, value: np.ndarray):
        self._request(CMD_INIT, key, value)

    def push(self, key: str, grad: np.ndarray):
        self._request(CMD_PUSH, key, grad)

    def push_rows(self, key: str, rows: np.ndarray, vals: np.ndarray):
        """Ship ONLY the live rows (O(rows) wire payload)."""
        self._request_raw(CMD_PUSH_ROWS, key, frame=_encode_rows_vals(
            np.asarray(rows), np.asarray(vals)))

    def pull(self, key: str) -> np.ndarray:
        return self._request(CMD_PULL, key)

    def pull_rows(self, key: str, rows: np.ndarray) -> np.ndarray:
        """Fetch ONLY the requested rows (O(rows) wire payload)."""
        return self._request(CMD_PULL_ROWS, key,
                             np.ascontiguousarray(rows, np.int64))

    def set_optimizer(self, optimizer):
        self._request(CMD_SET_OPT, "", raw=serialize_optimizer(optimizer))

    def get_optimizer_states(self) -> bytes:
        return self._request_raw(CMD_GET_STATES)[1]

    def set_optimizer_states(self, states: bytes):
        self._request(CMD_SET_STATES, "", raw=states)

    def barrier(self):
        self._request(CMD_BARRIER)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


_server: Optional[ParamServer] = None
_server_lock = threading.Lock()


def start_server(port: int, world_size: int) -> ParamServer:
    """Start (once) the in-process server — called on rank 0."""
    global _server
    with _server_lock:
        if _server is None:
            _server = ParamServer(port, world_size)
        return _server
