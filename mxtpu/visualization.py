"""Visualization — parity with ``python/mxnet/visualization.py`` (print_summary,
plot_network). ``plot_network`` emits DOT source directly (a ``graphviz.Source``
when that package is installed, the raw string otherwise); detailed op graphs
live in StableHLO dumps (jit.export_stablehlo)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from .gluon.block import Block


def _block_param_count(b: Block) -> int:
    """Materialized parameter count of one block (shared by print_summary and
    the DOT renderer)."""
    return sum(int(np.prod(p.shape)) for p in b.params.values()
               if p.shape and all(s > 0 for s in p.shape))


def print_summary(block: Block, shape=None, line_length: int = 72):
    """Parameter-count table per sub-block (visualization.py print_summary parity)."""
    rows = []
    total = 0

    def visit(b: Block, depth: int):
        nonlocal total
        own = _block_param_count(b)
        total += own
        rows.append(("  " * depth + type(b).__name__, b.name, own))
        for child in b._children.values():
            visit(child, depth + 1)

    visit(block, 0)
    print("=" * line_length)
    print(f"{'Layer':<40}{'Name':<20}{'Params':>10}")
    print("=" * line_length)
    for layer, name, n in rows:
        print(f"{layer:<40}{name:<20}{n:>10}")
    print("=" * line_length)
    print(f"Total params: {total}")
    return total


def network_dot_source(block: Block, title: str = "plot") -> str:
    """Graphviz DOT source for the block tree — generated directly (no
    graphviz dependency), same visual vocabulary as the reference's
    plot_network (visualization.py:plot_network node styling)."""
    import itertools

    def esc(s):
        return str(s).replace("\\", "\\\\").replace('"', '\\"')

    _palette = {"Conv": "#fb8072", "Dense": "#fb8072", "Pool": "#80b1d3",
                "BatchNorm": "#bebada", "Activation": "#ffffb3"}
    lines = [f'digraph "{esc(title)}" {{',
             '  node [shape=box, style=filled, fillcolor="#8dd3c7"];']
    counter = itertools.count(1)

    def visit(b, parent_id):
        nid = f"n{next(counter)}"
        tname = type(b).__name__
        color = next((c for k, c in _palette.items() if k in tname), "#8dd3c7")
        n_params = _block_param_count(b)
        label = f"{esc(tname)}\\n{esc(b.name)}" + (
            f"\\n{n_params} params" if n_params else "")
        lines.append(f'  {nid} [label="{label}", fillcolor="{color}"];')
        if parent_id:
            lines.append(f"  {parent_id} -> {nid};")
        for c in b._children.values():
            visit(c, nid)

    visit(block, None)
    lines.append("}")
    return "\n".join(lines)


def plot_network(block: Block, title: str = "plot", save_format: str = "pdf",
                 shape=None, **kwargs):
    """Return a renderable graph of the block tree: a ``graphviz.Digraph``
    when the python package is installed, otherwise the DOT source string
    (pipe it to ``dot -Tpdf`` yourself)."""
    src = network_dot_source(block, title)
    try:
        import graphviz
    except ImportError:
        return src
    return graphviz.Source(src, filename=title, format=save_format)
