"""Visualization — parity with ``python/mxnet/visualization.py`` (print_summary,
plot_network). ``plot_network`` renders block trees (graphviz if available, text
otherwise); detailed op graphs live in StableHLO dumps (jit.export_stablehlo)."""

from __future__ import annotations

from typing import Optional

from .gluon.block import Block


def print_summary(block: Block, shape=None, line_length: int = 72):
    """Parameter-count table per sub-block (visualization.py print_summary parity)."""
    rows = []
    total = 0

    def visit(b: Block, depth: int):
        nonlocal total
        own = 0
        for name, p in b.params.items():
            if p.shape and all(s > 0 for s in p.shape):
                n = 1
                for s in p.shape:
                    n *= s
                own += n
        total += own
        rows.append(("  " * depth + type(b).__name__, b.name, own))
        for child in b._children.values():
            visit(child, depth + 1)

    visit(block, 0)
    print("=" * line_length)
    print(f"{'Layer':<40}{'Name':<20}{'Params':>10}")
    print("=" * line_length)
    for layer, name, n in rows:
        print(f"{layer:<40}{name:<20}{n:>10}")
    print("=" * line_length)
    print(f"Total params: {total}")
    return total


def plot_network(block: Block, title: str = "plot", save_format: str = "pdf",
                 shape=None, **kwargs):
    try:
        import graphviz
    except ImportError:
        # text fallback
        lines = []

        def visit(b, depth):
            lines.append("  " * depth + f"{type(b).__name__}({b.name})")
            for c in b._children.values():
                visit(c, depth + 1)

        visit(block, 0)
        return "\n".join(lines)
    dot = graphviz.Digraph(name=title)

    def visit2(b, parent=None):
        nid = b.name or str(id(b))
        dot.node(nid, f"{type(b).__name__}\n{b.name}")
        if parent:
            dot.edge(parent, nid)
        for c in b._children.values():
            visit2(c, nid)

    visit2(block)
    return dot
