"""RecordIO — parity with ``python/mxnet/recordio.py`` (MXRecordIO, MXIndexedRecordIO,
IRHeader, pack/unpack/pack_img/unpack_img) and dmlc-core's on-disk format.

Format (dmlc-core recordio parity): each record is
``[magic:4][lrecord:4][data][pad to 4]`` where lrecord's upper 3 bits are the
continuation flag (unused here — single-chunk records) and lower 29 bits the length.
Python-native implementation; the hot read path (sequential chunked reads) is IO-bound,
and JPEG decode (the actual CPU cost in the reference's C++ path) happens in
DataLoader worker threads.
"""

from __future__ import annotations

import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as np

_MAGIC = 0xCED7230A
_LMASK = (1 << 29) - 1

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential record reader/writer (recordio.py:74 MXRecordIO)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.open()

    def open(self):
        if self.flag == "w":
            self._f = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self._f = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError(f"invalid flag {self.flag!r}")
        self._closed = False

    def close(self):
        if not self._closed:
            self._f.close()
            self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self._f.tell()

    def seek(self, pos: int):
        assert not self.writable
        self._f.seek(pos)

    def write(self, buf: bytes):
        assert self.writable
        self._f.write(struct.pack("<II", _MAGIC, len(buf) & _LMASK))
        self._f.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self._f.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        assert not self.writable
        head = self._f.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise IOError(f"invalid RecordIO magic at {self._f.tell() - 8}")
        length = lrec & _LMASK
        data = self._f.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self._f.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a ``.idx`` sidecar (recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif not self.writable:
            # no .idx sidecar: index by scanning the file — native C scan when
            # built (rio_index, ≈ the reference's InputSplit chunk walk), python
            # fallback otherwise; keys are sequential ints
            try:
                from . import native
                offsets, _ = native.rio_index(uri)
                positions = offsets - 8  # record start = payload start − header
            except Exception:
                positions = []
                pos = self.tell()
                while self.read() is not None:
                    positions.append(pos)
                    pos = self.tell()
                self.seek(0)
            for i, p in enumerate(positions):
                key = key_type(i)
                self.idx[key] = int(p)
                self.keys.append(key)

    def close(self):
        if self.writable and not getattr(self, "_closed", True):
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx) -> bytes:
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf: bytes):
        pos = self.tell()
        self.write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a header + payload (recordio.py:pack). Vector labels use flag>0."""
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)) and not np.isscalar(label):
        label = np.asarray(label, np.float32)
        header = header._replace(flag=label.size, label=0.0)
        payload = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                              header.id2) + label.tobytes() + s
        return payload
    return struct.pack(_IR_FORMAT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s: bytes):
    """Unpack to (IRHeader, payload) (recordio.py:unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(payload[:header.flag * 4], np.float32)
        header = header._replace(label=label)
        payload = payload[header.flag * 4:]
    return header, payload


def pack_img(header: IRHeader, img: np.ndarray, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode image + pack (recordio.py:pack_img); PIL replaces OpenCV."""
    import io
    from PIL import Image
    buf = io.BytesIO()
    arr = np.asarray(img, np.uint8)
    pil = Image.fromarray(arr.squeeze() if arr.ndim == 3 and arr.shape[2] == 1 else arr)
    fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}[img_fmt.lstrip(".").lower()]
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor: int = -1):
    """Unpack + decode image (recordio.py:unpack_img)."""
    header, payload = unpack(s)
    import io
    from PIL import Image
    img = np.asarray(Image.open(io.BytesIO(payload)))
    return header, img
