"""Module API — parity with ``python/mxnet/module/`` (SURVEY.md §2.5: BaseModule.fit
is the canonical symbolic training loop; Module wraps bind/init_params/init_optimizer;
BucketingModule shares compiled executors across variable-length buckets).

Re-design: the reference binds a Symbol into a GraphExecutor; here a Module wraps a
Gluon-style (Hybrid)Block — "bind" allocates/initializes parameters for the declared
shapes and hybridizes (the XLA compile is the executor). BucketingModule's per-bucket
executor sharing maps to CachedOp's signature cache: one Block, one weight set, one
compiled executable per bucket shape — exactly the reference's
``shared executor`` semantics (bucketing_module.py:36-108) without the bookkeeping.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import autograd
from . import metric as metric_mod
from . import ndarray as nd
from . import optimizer as opt_mod
from .callback import BatchEndParam
from .gluon.block import Block
from .gluon.trainer import Trainer
from .io import DataBatch, DataIter
from .ndarray.ndarray import NDArray


class BaseModule:
    """Training-loop surface (base_module.py:64): fit/score/predict/forward/backward."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # subclass interface ---------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True, **kwargs):
        raise NotImplementedError

    def init_params(self, initializer=None, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, **kwargs):
        raise NotImplementedError

    def forward(self, data_batch: DataBatch, is_train: Optional[bool] = None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self) -> List[NDArray]:
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def _monitor_blocks(self):
        """Blocks a Monitor should hook (valid after bind/init_params)."""
        return []

    def _program_flops(self):
        """FLOPs of one execution of the current compiled step program, when
        the subclass runs the fused StepExecutor path (None otherwise) — the
        numerator of the fit loop's per-epoch MFU roll-up."""
        return None

    # shared loop ----------------------------------------------------------
    def forward_backward(self, data_batch: DataBatch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data: DataIter, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        assert self.binded and self.params_initialized
        eval_metric = metric_mod.create(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data: DataIter, num_batch=None, reset: bool = True,
                chain: int = 1):
        """``chain=n`` turns on dispatch-amortized serving: n batches run as
        ONE compiled program (mxtpu.serving.ChainedPredictor), paying the
        per-call dispatch once per chain — the cure for RPC-floor-gated
        small-batch serving on disaggregated accelerators."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        # chained serving needs a directly-callable block: Module only
        # (Bucketing/Sequential modules fall through to the per-batch loop)
        if chain > 1 and getattr(self, "_block", None) is not None \
                and not getattr(self, "_symbolic", True):
            return self._predict_chained(eval_data, num_batch, chain)
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if batch.pad:
                outs = [o[:o.shape[0] - batch.pad] for o in outs]
            outputs.append(outs)
        if not outputs:
            return []
        joined = [nd.concatenate([o[i] for o in outputs], axis=0)
                  for i in range(len(outputs[0]))]
        return joined[0] if len(joined) == 1 else joined

    def _predict_chained(self, eval_data: DataIter, num_batch, chain: int):
        from .serving import ChainedPredictor
        # predictor cached per chain length: its jitted programs are the
        # whole point — a fresh one per call would recompile every time
        cache = getattr(self, "_chained_predictors", None)
        if cache is None:
            cache = self._chained_predictors = {}
        cp = cache.get(chain)
        if cp is None:
            cp = cache[chain] = ChainedPredictor(self._block, chain)
        pads = []

        def stream():
            for nbatch, batch in enumerate(eval_data):
                if num_batch is not None and nbatch == num_batch:
                    break
                if len(batch.data) != 1:
                    raise ValueError(
                        "predict(chain=n) supports single-input modules; use "
                        "the per-batch path for multi-input data")
                pads.append(batch.pad)
                yield batch.data[0]

        per_batch = cp.predict_batches(stream())
        if not per_batch:
            return []
        from .gluon.loss import SoftmaxCrossEntropyLoss
        softmax_head = isinstance(self._loss, SoftmaxCrossEntropyLoss)
        outputs = []
        for outs, pad in zip(per_batch, pads):
            if softmax_head:           # get_outputs() probability parity
                outs = [outs[0].softmax()] + outs[1:]
            if pad:
                outs = [o[:o.shape[0] - pad] for o in outs]
            outputs.append(outs)
        joined = [nd.concatenate([o[i] for o in outputs], axis=0)
                  for i in range(len(outputs[0]))]
        return joined[0] if len(joined) == 1 else joined

    def fit(self, train_data: DataIter, eval_data: Optional[DataIter] = None,
            eval_metric="acc", epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd", optimizer_params=None,
            eval_end_callback=None, initializer=None, arg_params=None,
            aux_params=None, allow_missing=False, force_init=False, begin_epoch=0,
            num_epoch=None, validation_metric=None, monitor=None,
            resume_from=None):
        """The canonical train loop (base_module.py:399).

        ``resume_from`` — a ``checkpoint.CheckpointManager`` or a checkpoint
        directory path — auto-restores the latest committed step (params,
        optimizer slots, RNG) after bind/init and continues the loop at the
        saved epoch/nbatch. A checkpoint without a committed step is a no-op
        (fresh start), so the same launch command works for both the first
        run and every preemption restart.

        The train iterator is routed through a ``device_feed.DeviceFeed``
        (opt-out: ``MXTPU_DEVICE_FEED=0``; depth: ``MXTPU_FEED_DEPTH``): a
        producer thread keeps the next batches device-resident so the step
        never waits on host decode + transfer. Input-stall and transfer
        accounting land in ``profiler.get_feed_stats()`` and are logged per
        epoch.
        """
        assert num_epoch is not None, "num_epoch required"
        from . import profiler
        from .device_feed import DeviceFeed, maybe_device_feed
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        # ZeRO-1 fits feed batches pre-sharded over the dp mesh so the fused
        # step's shard_batch sees them resident (no second device_put)
        feed_placement = None
        zero_on = False
        tr = getattr(self, "_trainer", None)
        if tr is not None:
            try:
                zero_on = tr.zero_requested()
            except Exception:
                zero_on = False
        if zero_on:
            from .parallel.mesh import get_default_mesh
            feed_placement = get_default_mesh()
        train_data = maybe_device_feed(train_data, placement=feed_placement)
        feed_on = isinstance(train_data, DeviceFeed)
        resume_nbatch = None
        if resume_from is not None:
            from .checkpoint import CheckpointManager
            mgr = resume_from if isinstance(resume_from, CheckpointManager) \
                else CheckpointManager(resume_from)
            snap = mgr.restore(module=self,
                               trainer=getattr(self, "_trainer", None))
            if snap is not None:
                if snap.meta.get("epoch") is not None:
                    begin_epoch = int(snap.meta["epoch"])
                if snap.meta.get("nbatch") is not None:
                    resume_nbatch = int(snap.meta["nbatch"])
                self.logger.info(
                    "fit: resumed from checkpoint step %s (epoch=%s nbatch=%s)",
                    snap.step, begin_epoch, resume_nbatch)
            # the watchdog's stall policy gets a final blocking save through
            # this manager (current params + live epoch/nbatch progress) —
            # a hung step still leaves a resumable checkpoint behind
            from .resilience import watchdog as _watchdog

            def _emergency_save(_mgr=mgr, _mod=self):
                prog = getattr(_mod, "_fit_progress", None) or {}
                _mgr.save(step=(_mgr._last_step or 0) + 1, module=_mod,
                          trainer=getattr(_mod, "_trainer", None),
                          epoch=prog.get("epoch"), nbatch=prog.get("nbatch"),
                          blocking=True)

            _watchdog.set_emergency_save(_emergency_save)
        eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        if monitor is not None:
            for b in self._monitor_blocks():
                monitor.install(b)

        from .observability import flops as flops_mod
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            flops_mod.reset_steps()   # per-epoch step-latency/MFU window
            feed0 = profiler.get_feed_stats() if feed_on else None
            comm0 = profiler.get_comm_stats() if zero_on else None
            from .analysis import sanitize
            san_modes = sanitize.active()
            san0 = profiler.get_sanitizer_stats() if san_modes else None
            for nbatch, data_batch in enumerate(train_data):
                if resume_nbatch is not None and epoch == begin_epoch \
                        and nbatch <= resume_nbatch:
                    continue   # batches 0..nbatch of the saved epoch are done
                if monitor is not None:
                    monitor.tic()
                t_step = time.perf_counter()
                self.forward_backward(data_batch)
                self.update()
                # update_metric reads the outputs back, so the sample below
                # is a host-synced step wall time, not just dispatch
                self.update_metric(eval_metric, data_batch.label)
                flops_mod.record_step(time.perf_counter() - t_step)
                # live progress marker (updated AFTER the batch completes, so
                # a preemption/emergency save resumes past this batch, never
                # replaying it) — read by install_preemption_handler's
                # default state_fn and the watchdog emergency save
                self._fit_progress = {"epoch": epoch, "nbatch": nbatch}
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(BatchEndParam(epoch, nbatch, eval_metric))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)
            mstats = flops_mod.get_mfu_stats(
                flops_per_step=self._program_flops())
            if mstats["steps"]:
                mfu_msg = (", MFU=%.1f%%" % (100 * mstats["mfu"])
                           if mstats["mfu"] is not None else "")
                self.logger.info(
                    "Epoch[%d] Speed: %.2f steps/s, step p50=%.2f ms "
                    "p99=%.2f ms%s", epoch, mstats["steps_per_sec"],
                    mstats["p50_step_ms"], mstats["p99_step_ms"], mfu_msg)
            if feed0 is not None:
                f = profiler.get_feed_stats()
                consumed = f["batches_consumed"] - feed0["batches_consumed"]
                if consumed:
                    self.logger.info(
                        "Epoch[%d] Input: stall=%.1f ms, h2d=%.2f MB in "
                        "%.1f ms, prefetched=%d consumed=%d, queue hw=%d/%d",
                        epoch,
                        f["stall_ms_total"] - feed0["stall_ms_total"],
                        (f["transfer_bytes"] - feed0["transfer_bytes"]) / 1e6,
                        f["transfer_ms_total"] - feed0["transfer_ms_total"],
                        f["batches_prefetched"] - feed0["batches_prefetched"],
                        consumed, f["queue_depth_max"], f["feed_depth"])
            if comm0 is not None:
                c = profiler.get_comm_stats()
                zsteps = c["zero_steps"] - comm0["zero_steps"]
                if zsteps:
                    self.logger.info(
                        "Epoch[%d] Comm (ZeRO-%d, dp=%d): %.2f MB reduce-"
                        "scatter + %.2f MB all-gather per step over %d "
                        "bucket(s); %.2f MB optimizer shard per device",
                        epoch,
                        max(1, profiler.get_memory_stats()["stage"]),
                        c["dp"],
                        (c["bytes_reduced"] - comm0["bytes_reduced"])
                        / max(zsteps, 1) / 1e6,
                        (c["bytes_gathered"] - comm0["bytes_gathered"])
                        / max(zsteps, 1) / 1e6,
                        c["bucket_count"],
                        c["shard_bytes_per_device"] / 1e6)
                m = profiler.get_memory_stats()
                if m["param_bytes_per_device"] or m["slot_bytes_per_device"]:
                    repl = (m["replicated_param_bytes"]
                            + m["replicated_grad_bytes"]
                            + m["replicated_slot_bytes"])
                    dev = (m["param_bytes_per_device"]
                           + m["grad_bytes_per_device"]
                           + m["slot_bytes_per_device"])
                    self.logger.info(
                        "Epoch[%d] Memory (ZeRO-%d, data=%d fsdp=%d): "
                        "%.2f MB/device (params %.2f + grads %.2f + slots "
                        "%.2f) vs %.2f MB replicated (%.1fx)",
                        epoch, m["stage"], m["data_degree"],
                        m["fsdp_degree"], dev / 1e6,
                        m["param_bytes_per_device"] / 1e6,
                        m["grad_bytes_per_device"] / 1e6,
                        m["slot_bytes_per_device"] / 1e6,
                        repl / 1e6, repl / max(dev, 1))
            if san0 is not None:
                s = profiler.get_sanitizer_stats()
                self.logger.info(
                    "Epoch[%d] Sanitizer[%s]: transfer-guards=%d poisons=%d "
                    "ownership-checks=%d retrace-escalations=%d, trips=%d",
                    epoch, ",".join(sorted(san_modes)),
                    s["transfer_guards"] - san0["transfer_guards"],
                    s["donation_poisons_armed"]
                    - san0["donation_poisons_armed"],
                    s["ownership_checks"] - san0["ownership_checks"],
                    s["retrace_escalations"] - san0["retrace_escalations"],
                    profiler.sanitizer_violations(s)
                    - profiler.sanitizer_violations(san0))
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, getattr(self, "_symbol", None), arg, aux)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric, epoch=epoch)
                if eval_end_callback is not None:
                    for cb in _as_list(eval_end_callback):
                        cb(BatchEndParam(epoch, 0, validation_metric))
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    """Module over a Block (module.py:40 Module-over-Symbol parity)."""

    def __init__(self, block, data_names: Sequence[str] = ("data",),
                 label_names: Sequence[str] = ("softmax_label",), logger=logging,
                 context=None, loss=None):
        super().__init__(logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._symbolic = False
        self._symbol_obj = None
        from .symbol import Symbol
        if isinstance(block, Symbol):
            # Module-over-Symbol (module.py:40 native case): wrap the graph in a
            # SymbolBlock; data + any label arguments are graph inputs, the
            # loss-fused head (SoftmaxOutput et al.) owns the backward semantics.
            from .gluon.block import SymbolBlock
            self._symbol_obj = block
            args = block.list_arguments()
            self._sym_inputs = [n for n in self._data_names if n in args] + \
                [n for n in self._label_names if n in args]
            block = SymbolBlock(block, self._sym_inputs)
            self._symbolic = True
        self._block = block
        self._context = context
        from .gluon.loss import SoftmaxCrossEntropyLoss
        self._loss = loss if loss is not None else SoftmaxCrossEntropyLoss()
        self._trainer: Optional[Trainer] = None
        self._outputs: List[NDArray] = []
        self._loss_val: Optional[NDArray] = None
        self._batch_size = 0
        # fused-step state (step_cache.StepExecutor): forward_backward+update
        # collapse into one compiled program when the step is fusable
        self._step_exec = None
        self._fused_pending = False
        self._fuse_broken = False

    @property
    def symbol(self):
        return self._symbol_obj if self._symbolic else self._block

    def _monitor_blocks(self):
        return [self._block]

    def _program_flops(self):
        if self._step_exec is None:
            return None
        return self._step_exec.program_flops()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        if self.params_initialized and not force_init:
            return
        self._block.initialize(init=initializer, force_reinit=force_init)
        # run one forward on zeros to complete deferred shapes (all declared inputs;
        # symbolic graphs also need their label arguments fed)
        dummies = [nd.zeros(tuple(d.shape)) for d in self._data_shapes]
        if self._symbolic:
            by_name = {d.name: tuple(d.shape)
                       for d in list(self._data_shapes) +
                       list(self._label_shapes or [])}
            dummies = [nd.zeros(by_name[n]) if n in by_name
                       else nd.zeros(dummies[0].shape[:1])
                       for n in self._sym_inputs]
        with autograd.predict_mode():
            self._block(*dummies)
        if arg_params:
            for name, p in self._block.collect_params().items():
                short = name[len(self._block.prefix):] \
                    if name.startswith(self._block.prefix) else name
                if short in arg_params:
                    p.set_data(arg_params[short])
                elif name in arg_params:
                    p.set_data(arg_params[name])
        if aux_params:
            for name, p in self._block.collect_params().items():
                short = name[len(self._block.prefix):] \
                    if name.startswith(self._block.prefix) else name
                if short in aux_params or name in aux_params:
                    p.set_data(aux_params.get(short, aux_params.get(name)))
        self.params_initialized = True

    def get_params(self):
        arg, aux = {}, {}
        for name, p in self._block.collect_params().items():
            short = name[len(self._block.prefix):] \
                if name.startswith(self._block.prefix) else name
            if p._data is None:
                continue
            (aux if p.grad_req == "null" else arg)[short] = p.data()
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        optimizer_params = dict(optimizer_params or {})
        if "learning_rate" not in optimizer_params and isinstance(optimizer, str):
            optimizer_params["learning_rate"] = 0.01
        self._trainer = Trainer(self._block.collect_params(), optimizer,
                                optimizer_params, kvstore=kvstore)
        self.optimizer_initialized = True

    def forward(self, data_batch: DataBatch, is_train: Optional[bool] = None):
        assert self.binded
        data = list(data_batch.data)
        label = data_batch.label[0] if data_batch.label else None
        self._batch_size = data[0].shape[0]
        is_train = self._for_training if is_train is None else is_train
        if self._symbolic:
            # feed label args too; absent labels get zeros (forward output of a
            # loss-fused head does not depend on the label)
            n_label = len(self._sym_inputs) - len(data)
            extra = [label] * n_label if label is not None else \
                [nd.zeros((self._batch_size,))] * n_label
            data = data + extra
        if is_train and getattr(self, "_inputs_need_grad", False):
            n_data = len(data_batch.data)  # exclude appended symbolic labels
            for d in data[:n_data]:
                if d._grad_entry is None:
                    d.attach_grad()        # true leaf (host batch)
                else:
                    autograd.retain_grad(d)  # another module's live output
            self._input_arrays = list(data[:n_data])
        if is_train:
            from .gluon.loss import SoftmaxCrossEntropyLoss
            with autograd.record():
                out = self._block(*data)
                self._outputs = [out] if isinstance(out, NDArray) else list(out)
                # expose the SAME tensors get_outputs() returns while still on
                # the tape, so backward(out_grads) seeds the right node
                if not self._symbolic and isinstance(self._loss,
                                                     SoftmaxCrossEntropyLoss):
                    self._exposed = [self._outputs[0].softmax()] \
                        + self._outputs[1:]
                else:
                    self._exposed = None
                if label is not None and not self._symbolic:
                    self._loss_val = self._loss(self._outputs[0], label)
                elif self._symbolic:
                    # the loss-fused head injects its own gradient; backward seeds
                    # the output with ones (GraphExecutor::Backward parity)
                    self._loss_val = None
        else:
            with autograd.predict_mode():
                out = self._block(*data)
            self._outputs = [out] if isinstance(out, NDArray) else list(out)
            self._loss_val = None
            self._exposed = None  # never serve a stale train-time exposure

    # -- fused step (forward+backward+update as ONE compiled program) -------
    def _hooks_installed(self, block) -> bool:
        if block._forward_hooks or block._forward_pre_hooks:
            return True
        return any(self._hooks_installed(c) for c in block._children.values())

    def _step_fusable(self, data_batch) -> bool:
        """The whole-step compile covers the monitor-less, locally-updated
        common case; anything needing per-op visibility or special gradient
        plumbing takes the eager path (reference analogue: ops with monitors
        or cross-device reduction are never bulked)."""
        from . import engine
        if engine.bulk_size() == 0 or self._fuse_broken or self._symbolic:
            return False
        if self._trainer is None or not self.optimizer_initialized:
            return False
        if getattr(self, "_inputs_need_grad", False):
            return False
        if not data_batch.label:
            return False
        if self._hooks_installed(self._block):
            return False     # Monitor / user hooks need eager per-op outputs
        tr = self._trainer
        try:
            tr._init_kvstore()
        except Exception:
            return False
        if tr._kvstore is not None and getattr(tr, "_update_on_kv", False) \
                and not tr.zero_requested():
            # server-side updates can't fuse into the step — EXCEPT when the
            # ZeRO path takes over: its in-program reduce-scatter over the
            # (process-spanning) dp mesh IS the dist_sync reduction, so the
            # fused step replaces the push/pull round-trip entirely
            return False
        opt = tr._optimizer
        if getattr(opt, "multi_precision", False):
            return False
        if any(p.grad_req != "write" or p._data is None for p in tr._params):
            return False     # grad_req='add' accumulation stays eager
        return True

    def forward_backward(self, data_batch: DataBatch):
        if self._step_fusable(data_batch):
            try:
                self._fused_step(data_batch)
                return
            except Exception as e:
                from .analysis.sanitize import SanitizerError
                from .resilience.faults import InjectedFault
                if isinstance(e, (SanitizerError, InjectedFault)):
                    # a sanitizer escalation or an injected fault is a
                    # deliberate failure — the eager fallback would hide the
                    # very hazard it names (and permanently de-fuse the step)
                    raise
                # trace/compile failure (unsupported optimizer kernel, exotic
                # block): permanently fall back to the eager path — behavior
                # is preserved, only the fusion speedup is lost
                self._fuse_broken = True
                self.logger.warning(
                    "Module: fused-step compile failed; falling back to "
                    "eager forward/backward/update", exc_info=True)
        self.forward(data_batch, is_train=True)
        self.backward()

    def _fused_step(self, data_batch: DataBatch):
        if self._step_exec is None:
            from .step_cache import StepExecutor
            self._step_exec = StepExecutor(self._block, self._loss,
                                           self._trainer)
        data = [d if isinstance(d, NDArray) else nd.array(d)
                for d in data_batch.data]
        label = data_batch.label[0]
        label = label if isinstance(label, NDArray) else nd.array(label)
        self._batch_size = data[0].shape[0]
        res = self._step_exec.step(data, label, batch_size=self._batch_size)
        self._outputs = res["outputs_list"]
        self._exposed = res["exposed"]
        self._loss_val = res["loss"]
        self._fused_pending = True

    def backward(self, out_grads=None):
        if self._symbolic:
            autograd.backward(list(self._outputs),
                              list(out_grads) if out_grads is not None else None)
        elif out_grads is not None:
            # explicit head gradients seed the EXPOSED outputs (what
            # get_outputs() returned — softmaxed for classification modules)
            heads = self._exposed if getattr(self, "_exposed", None) \
                else self._outputs
            autograd.backward(list(heads), list(out_grads))
        elif self._loss_val is not None:
            autograd.backward([self._loss_val])

    def update(self):
        assert self._trainer is not None, "init_optimizer first"
        if self._fused_pending:
            # the fused step already applied the optimizer inside the same
            # compiled program; update() just completes the protocol
            self._fused_pending = False
            return
        self._trainer.step(self._batch_size)

    def get_outputs(self, merge_multi_context=True) -> List[NDArray]:
        # classification modules output probabilities (SoftmaxOutput-symbol parity);
        # other losses pass raw outputs through
        from .gluon.loss import SoftmaxCrossEntropyLoss
        if self._symbolic:
            return list(self._outputs)  # loss-fused heads already emit probabilities
        if getattr(self, "_exposed", None):
            return list(self._exposed)
        if self._outputs and isinstance(self._loss, SoftmaxCrossEntropyLoss):
            return [self._outputs[0].softmax()] + self._outputs[1:]
        return list(self._outputs)

    def get_input_grads(self):
        """Gradients w.r.t. the data inputs (module.py:40 inputs_need_grad
        contract); requires bind(inputs_need_grad=True) + forward/backward."""
        if not getattr(self, "_inputs_need_grad", False):
            raise RuntimeError("bind with inputs_need_grad=True first")
        return [d.grad for d in self._input_arrays]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def save_checkpoint(self, prefix, epoch: int, save_optimizer_states=False,
                        blocking: bool = True):
        """Persist the module state. ``prefix`` is a path prefix (legacy
        ``prefix-####.params`` layout, written atomically through the
        checkpoint subsystem) or a ``checkpoint.CheckpointManager`` — then
        the full state (params, optimizer slots, RNG) is saved through the
        async atomic-commit path; ``blocking=False`` returns after the
        device→host handoff."""
        from .checkpoint import CheckpointManager
        if isinstance(prefix, CheckpointManager):
            # manager mode always captures the FULL resumable state — params,
            # optimizer slots, RNG (save_optimizer_states exists for the
            # legacy two-file layout, where optimizer state is a second file)
            prefix.save(epoch, module=self, trainer=self._trainer,
                        epoch=epoch, blocking=blocking)
            return
        from .model import save_checkpoint
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol_obj, arg, aux)
        if save_optimizer_states and self._trainer is not None:
            self._trainer.save_states(f"{prefix}-{epoch:04d}.states")


class BucketingModule(BaseModule):
    """Variable-length training (bucketing_module.py:36).

    ``sym_gen(bucket_key) -> (block, data_names, label_names)``; one parameter set is
    shared across buckets; each bucket shape compiles once in the CachedOp cache.
    """

    def __init__(self, sym_gen: Callable, default_bucket_key=None, logger=logging,
                 context=None, loss=None):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_key = default_bucket_key
        self._modules: Dict = {}
        self._context = context
        self._loss = loss
        self._curr: Optional[Module] = None
        self._shared_params = None
        self._opt_args = None

    def _get_module(self, bucket_key, data_shapes=None, label_shapes=None):
        if bucket_key not in self._modules:
            block, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(block, data_names, label_names, self.logger,
                         self._context, self._loss)
            mod.bind(data_shapes or self._data_shapes,
                     label_shapes or self._label_shapes, self._for_training)
            mod.init_params(initializer=self._init)
            if self._modules:
                # one weight set across buckets (reference shared-executor
                # semantics, bucketing_module.py:36): sym_gen must build blocks
                # over shared Parameters (same block, or params=shared ParameterDict)
                # — detect violations instead of silently training disjoint weights.
                first_key, first = next(iter(self._modules.items()))
                first_ids = set(map(id, first._block.collect_params().values()))
                new_ids = set(map(id, block.collect_params().values()))
                if first_ids.isdisjoint(new_ids):
                    raise ValueError(
                        f"BucketingModule: bucket {bucket_key!r} shares no "
                        f"parameters with bucket {first_key!r}; sym_gen must build "
                        "blocks over shared parameters (reuse one block or pass "
                        "params=first_block.collect_params())")
                # share the trainer so optimizer state is per-weight, not per-bucket
                mod._trainer = first._trainer
                mod.optimizer_initialized = first.optimizer_initialized
            elif self._opt_args is not None:
                mod.init_optimizer(*self._opt_args)
            self._modules[bucket_key] = mod
        return self._modules[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True, **kwargs):
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._for_training = for_training
        self.binded = True

    def init_params(self, initializer=None, **kwargs):
        self._init = initializer
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, **kwargs):
        self._opt_args = (kvstore, optimizer, optimizer_params)
        mods = list(self._modules.values())
        if mods:
            mods[0].init_optimizer(kvstore, optimizer, optimizer_params)
            for m in mods[1:]:  # one trainer across buckets (shared weights)
                m._trainer = mods[0]._trainer
                m.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch: DataBatch, is_train=None):
        key = data_batch.bucket_key if data_batch.bucket_key is not None \
            else self._default_key
        self._curr = self._get_module(key, data_batch.provide_data,
                                      data_batch.provide_label)
        self._curr.forward(data_batch, is_train)

    def forward_backward(self, data_batch: DataBatch):
        # delegate to the bucket's Module so each bucket shape gets the fused
        # whole-step compile (one step-cache entry per bucket — the
        # shared-executor story at step granularity)
        key = data_batch.bucket_key if data_batch.bucket_key is not None \
            else self._default_key
        self._curr = self._get_module(key, data_batch.provide_data,
                                      data_batch.provide_label)
        self._curr.forward_backward(data_batch)

    def backward(self, out_grads=None):
        self._curr.backward(out_grads)

    def update(self):
        self._curr.update()

    def get_outputs(self):
        return self._curr.get_outputs()

    def update_metric(self, eval_metric, labels):
        self._curr.update_metric(eval_metric, labels)

    def get_params(self):
        return self._curr.get_params() if self._curr else ({}, {})

    def _monitor_blocks(self):
        return self._curr._monitor_blocks() if self._curr else []

    def _program_flops(self):
        # per-bucket programs differ in shape; report the current bucket's
        return self._curr._program_flops() if self._curr else None


class SequentialModule(BaseModule):
    """Chain of modules executed back-to-back (sequential_module.py parity).

    ``add(module, take_labels=True)`` marks the module that consumes labels
    (META_TAKE_LABELS; defaults to the last). Data shapes auto-wire: each
    module binds on the previous module's output shape (discovered with a
    zeros forward, since blocks infer shapes by running). Backward chains
    through ``get_input_grads`` — every non-first module binds with
    ``inputs_need_grad=True``."""

    def __init__(self, logger=logging):
        super().__init__(logger)
        self._modules: List[BaseModule] = []
        self._metas: List[dict] = []

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append({"take_labels": kwargs.get("take_labels", False)})
        return self

    def _label_module_index(self) -> int:
        for i, meta in enumerate(self._metas):
            if meta["take_labels"]:
                return i
        return len(self._modules) - 1

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        assert self._modules, "add modules before bind"
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self.binded = True

    def _monitor_blocks(self):
        return [b for m in self._modules for b in m._monitor_blocks()]

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        from .io import DataDesc
        shapes = list(self._data_shapes)
        label_idx = self._label_module_index()
        for i, m in enumerate(self._modules):
            ing = self._inputs_need_grad if i == 0 else True
            m.bind(shapes, self._label_shapes if i == label_idx else None,
                   for_training=self._for_training, inputs_need_grad=ing,
                   force_rebind=True)
            m.init_params(initializer=initializer, arg_params=arg_params,
                          aux_params=aux_params, allow_missing=True,
                          force_init=force_init)
            # discover output shapes with a zeros forward (auto-wiring)
            dummy = DataBatch(data=[nd.zeros(tuple(d.shape)) for d in shapes],
                              label=None)
            m.forward(dummy, is_train=False)
            shapes = [DataDesc(f"data{j}", o.shape)
                      for j, o in enumerate(m.get_outputs())]
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        for m in self._modules:
            m.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                             optimizer_params=optimizer_params,
                             force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch: DataBatch, is_train=None):
        label_idx = self._label_module_index()
        batch = data_batch
        for i, m in enumerate(self._modules):
            label = data_batch.label if i == label_idx else None
            m.forward(DataBatch(data=list(batch.data), label=label,
                                pad=getattr(data_batch, "pad", 0)),
                      is_train=is_train)
            # chain the RAW outputs (still attached to the live tape);
            # get_outputs() would apply the classification-head softmax
            # outside the record context and detach the graph
            batch = DataBatch(data=list(m._outputs), label=None)

    def backward(self, out_grads=None):
        # all chained forwards record onto ONE connected tape (each module's
        # output NDArrays are the next module's inputs), so a single backward
        # from the loss-owning module reaches every submodule's params — the
        # reference's per-executor out_grads relay (sequential_module.py:344)
        # collapses. Intermediate input grads remain readable via
        # modules[i].get_input_grads() (their bind sets inputs_need_grad).
        idx = (len(self._modules) - 1 if out_grads is not None
               else self._label_module_index())
        self._modules[idx].backward(out_grads=out_grads)

    def update(self):
        for m in self._modules:
            m.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_params(self):
        arg, aux = {}, {}
        for m in self._modules:
            a, x = m.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def update_metric(self, eval_metric, labels):
        self._modules[self._label_module_index()].update_metric(eval_metric,
                                                                labels)


class PythonModule(BaseModule):
    """Parameter-less module written directly in Python
    (python_module.py:PythonModule parity): computation supplied by
    subclassing or a ``forward_fn``; get_params is empty, init/update are
    no-ops. The glue that lets hand-written stages (losses, samplers,
    metrics-side computations) slot into SequentialModule/fit pipelines."""

    def __init__(self, data_names=("data",), label_names=("softmax_label",),
                 output_names=("output",), logger=logging, forward_fn=None):
        super().__init__(logger)
        self.data_names = list(data_names)
        self.label_names = list(label_names or [])
        self.output_names = list(output_names)
        self._forward_fn = forward_fn
        self._outputs: List = []
        self._labels: List = []

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._for_training = for_training
        self.binded = True

    def init_params(self, initializer=None, **kwargs):
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self.optimizer_initialized = True

    def get_params(self):
        return {}, {}

    def forward(self, data_batch: DataBatch, is_train=None):
        self._labels = list(data_batch.label or [])
        outs = self._forward_impl(list(data_batch.data), self._labels)
        self._outputs = outs if isinstance(outs, (list, tuple)) else [outs]

    def _forward_impl(self, data, labels):
        if self._forward_fn is None:
            raise NotImplementedError(
                "subclass PythonModule and implement _forward_impl, or pass "
                "forward_fn=")
        return self._forward_fn(data, labels)

    def backward(self, out_grads=None):
        pass                       # parameter-less: nothing to do by default

    def update(self):
        pass

    def get_outputs(self, merge_multi_context=True):
        return list(self._outputs)

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self._outputs)

    def _monitor_blocks(self):
        return []


class PythonLossModule(PythonModule):
    """Loss stage in Python (python_module.py:PythonLossModule): forward
    passes scores through; backward injects ``grad_func(scores, labels)``
    into the tape so upstream modules receive it via the connected-tape
    chain (here: by re-recording the forward with the custom cotangent)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, (name + "_output",), logger)
        self._grad_func = grad_func
        self._scores = None

    def _forward_impl(self, data, labels):
        self._scores = data[0]
        return [self._scores]

    def backward(self, out_grads=None):
        if self._scores is None:
            raise RuntimeError("backward before forward")
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
        elif self._labels:
            # default: d/dscores of softmax CE with the given sparse labels
            probs = nd.softmax(self._scores)
            onehot = nd.one_hot(self._labels[0], int(self._scores.shape[-1]))
            grad = probs - onehot
        else:
            raise RuntimeError("PythonLossModule needs labels or grad_func")
        self._scores.backward(out_grad=grad)
