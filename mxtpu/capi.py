"""On-demand build + ctypes binding of the C ABI library (native/mxtpu_capi.cc).

Mirrors :mod:`mxtpu.native`'s build-at-first-use pattern. The library is the
stable C boundary other languages bind against (c_predict_api.h role, SURVEY
§2.6); this module additionally exposes it back to Python so the test suite can
exercise the exact ABI a C/R/JVM client would use.
"""

from __future__ import annotations

import ctypes
import os
import sysconfig
import threading
from typing import Optional

import numpy as np

from .native import compile_shared

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "mxtpu_capi.cc")
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libmxtpu_capi.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def python_link_flags():
    """(include_dir, libdir, libname) for embedding this interpreter."""
    include = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    return include, libdir, f"python{ver}"


def build() -> bool:
    """g++ against this interpreter's libpython; mtime-cached via compile_shared."""
    include, libdir, libname = python_link_flags()
    return compile_shared(_SRC, _LIB_PATH, ([
        f"-I{include}", f"-L{libdir}", f"-l{libname}", f"-Wl,-rpath,{libdir}"],))


def lib_path() -> str:
    return _LIB_PATH


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC) or not build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        u32 = ctypes.c_uint32
        lib.MXGetLastError.restype = ctypes.c_char_p
        lib.MXCAPIGetVersion.argtypes = [ctypes.POINTER(ctypes.c_int)]
        lib.MXPredCreate.restype = ctypes.c_int
        lib.MXPredCreate.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, u32, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(u32), ctypes.POINTER(u32),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.MXPredGetNumOutputs.argtypes = [ctypes.c_void_p,
                                            ctypes.POINTER(u32)]
        lib.MXPredGetOutputShape.argtypes = [
            ctypes.c_void_p, u32, ctypes.POINTER(ctypes.POINTER(u32)),
            ctypes.POINTER(u32)]
        lib.MXPredSetInput.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"), u32]
        lib.MXPredForward.argtypes = [ctypes.c_void_p]
        lib.MXPredGetOutput.argtypes = [
            ctypes.c_void_p, u32,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"), u32]
        lib.MXPredFree.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class CPredictor:
    """Python client of the C ABI — the same calls a C binding would make.

    This deliberately goes through the flat-buffer boundary (not capi_impl
    directly) so tests cover marshalling, the error convention, and the
    embedded-interpreter attach path.
    """

    def __init__(self, symbol_json: str, param_bytes: bytes,
                 input_shapes: dict, dev_type: int = 1, dev_id: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("C ABI library unavailable (no g++/libpython?)")
        self._lib = lib
        names = list(input_shapes.keys())
        keys = (ctypes.c_char_p * len(names))(
            *[n.encode() for n in names])
        indptr = [0]
        flat: list = []
        for n in names:
            flat.extend(int(d) for d in input_shapes[n])
            indptr.append(len(flat))
        c_indptr = (ctypes.c_uint32 * len(indptr))(*indptr)
        c_shape = (ctypes.c_uint32 * max(1, len(flat)))(*(flat or [0]))
        handle = ctypes.c_void_p()
        rc = lib.MXPredCreate(symbol_json.encode(), param_bytes,
                              len(param_bytes), dev_type, dev_id, len(names),
                              keys, c_indptr, c_shape, ctypes.byref(handle))
        if rc != 0:
            raise RuntimeError(f"MXPredCreate: {self.last_error()}")
        self._handle = handle

    def last_error(self) -> str:
        return (self._lib.MXGetLastError() or b"").decode()

    def set_input(self, key: str, arr: np.ndarray):
        arr = np.ascontiguousarray(arr, np.float32)
        rc = self._lib.MXPredSetInput(self._handle, key.encode(), arr,
                                      arr.size)
        if rc != 0:
            raise RuntimeError(f"MXPredSetInput: {self.last_error()}")

    def forward(self):
        if self._lib.MXPredForward(self._handle) != 0:
            raise RuntimeError(f"MXPredForward: {self.last_error()}")

    @property
    def num_outputs(self) -> int:
        n = ctypes.c_uint32()
        if self._lib.MXPredGetNumOutputs(self._handle, ctypes.byref(n)) != 0:
            raise RuntimeError(f"MXPredGetNumOutputs: {self.last_error()}")
        return n.value

    def output_shape(self, index: int) -> tuple:
        data = ctypes.POINTER(ctypes.c_uint32)()
        ndim = ctypes.c_uint32()
        rc = self._lib.MXPredGetOutputShape(self._handle, index,
                                            ctypes.byref(data),
                                            ctypes.byref(ndim))
        if rc != 0:
            raise RuntimeError(f"MXPredGetOutputShape: {self.last_error()}")
        return tuple(data[i] for i in range(ndim.value))

    def get_output(self, index: int) -> np.ndarray:
        shape = self.output_shape(index)
        out = np.empty(shape, np.float32)
        rc = self._lib.MXPredGetOutput(self._handle, index,
                                       out.reshape(-1), out.size)
        if rc != 0:
            raise RuntimeError(f"MXPredGetOutput: {self.last_error()}")
        return out

    def free(self):
        if getattr(self, "_handle", None):
            self._lib.MXPredFree(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass
