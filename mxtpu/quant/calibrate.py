"""Streaming activation calibration — entropy/min-max over a ``DeviceFeed``.

The calibration math (naive min/max and the TensorRT-style KL-optimal
threshold sweep) lived inside ``contrib/quantization.py`` and worked by
CONCATENATING every observed activation on the host — O(samples) memory,
unusable against a production feed. This module lifts it into a streaming
API: :class:`StreamingCalibrator` folds each observed chunk into per-tensor
min/max/absmax plus a fixed-width histogram (range expands by power-of-two
rebinning when a later chunk overflows it), so memory is O(bins) per tensor
regardless of how many batches stream through. ``contrib.quantize_net``'s
collection pass now runs on this calibrator; :func:`calibrate_feed` drives
it over any batch source — including an async :class:`~mxtpu.device_feed.
DeviceFeed` — and records the calibrated ranges into
``profiler.get_quant_stats()``.
"""

from __future__ import annotations

import logging
import math
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["StreamingCalibrator", "calibrate_feed", "collect_stats",
           "optimal_threshold_from_hist", "_get_optimal_threshold",
           "_smooth_distribution"]


def _smooth_distribution(p: np.ndarray, eps: float = 1e-4) -> np.ndarray:
    """Replace zeros with eps, taking the mass off nonzero entries
    (reference quantization.py:234 _smooth_distribution behavior)."""
    is_zero = p == 0
    n_zero = int(is_zero.sum())
    n_nonzero = p.size - n_zero
    if n_zero == 0 or n_nonzero == 0:
        return p.astype(np.float64)
    out = p.astype(np.float64).copy()
    out[is_zero] = eps
    out[~is_zero] -= eps * n_zero / n_nonzero
    return out


def optimal_threshold_from_hist(hist: np.ndarray, edges: np.ndarray,
                                num_quantized_bins: int = 255,
                                sweep_stride: Optional[int] = None) -> float:
    """KL-optimal clipping threshold from a symmetric histogram (the
    TensorRT algorithm; reference quantization.py:253).

    The clipped reference distribution P absorbs the outlier mass into its
    edge bins while the int8-quantized candidate Q is built from the
    *sliced* histogram only — that asymmetry is what makes aggressive
    clipping of real mass expensive in KL(P||Q). ``sweep_stride`` subsamples
    the threshold sweep (default covers ~256 candidates, bounding the KL gap
    to adjacent-bin resolution)."""
    num_bins = int(hist.size)
    zero = num_bins // 2
    half_q = num_quantized_bins // 2
    stride = sweep_stride or max(1, (zero + 1 - half_q) // 256)
    best_kl, best_t = np.inf, float(edges[-1])
    for i in range(half_q, zero + 1, stride):
        start, stop = zero - i, zero + i + 1
        sliced = hist[start:stop].astype(np.float64)
        p = sliced.copy()
        p[0] += hist[:start].sum()
        p[-1] += hist[stop:].sum()
        if p.sum() == 0:
            continue
        nonzero = sliced != 0
        m = p.size // num_quantized_bins
        q = np.zeros_like(p)
        for j in range(num_quantized_bins):
            s = j * m
            e = s + m if j != num_quantized_bins - 1 else p.size
            cnt = int(nonzero[s:e].sum())
            if cnt:
                q[s:e][nonzero[s:e]] = sliced[s:e].sum() / cnt
        ps = _smooth_distribution(p)
        qs = _smooth_distribution(q)
        ps /= ps.sum()
        qs /= qs.sum()
        kl = float(np.sum(ps * np.log(ps / qs)))
        if kl < best_kl:
            best_kl, best_t = kl, float(edges[stop])
    return best_t


def _get_optimal_threshold(arr: np.ndarray, num_bins: int = 2001,
                           num_quantized_bins: int = 255,
                           sweep_stride: Optional[int] = None) -> float:
    """One-shot threshold over a materialized array (the pre-streaming
    surface; ``contrib.quantization`` re-exports it for compatibility)."""
    arr = np.asarray(arr, np.float64).ravel()
    th = float(np.max(np.abs(arr))) if arr.size else 0.0
    if th == 0.0:
        return 1e-30
    hist, edges = np.histogram(arr, bins=num_bins, range=(-th, th))
    return optimal_threshold_from_hist(hist, edges, num_quantized_bins,
                                       sweep_stride)


class StreamingCalibrator:
    """Constant-memory per-tensor activation statistics.

    ``observe(name, chunk)`` folds a chunk into running min/max/absmax and a
    ``num_bins``-wide symmetric histogram. The histogram's range is fixed by
    the first chunk's absmax; when a later chunk overflows it, the range
    doubles (power-of-two) and existing counts REBIN by bin-center — each
    count lands within half a (new, coarser) bin of where an exact
    re-histogram would put it, so the entropy sweep sees at most one-bin
    drift versus the concatenate-everything baseline."""

    def __init__(self, num_bins: int = 2001):
        self.num_bins = int(num_bins)
        self._min: Dict[str, float] = {}
        self._max: Dict[str, float] = {}
        self._absmax: Dict[str, float] = {}
        self._hist: Dict[str, np.ndarray] = {}
        self._th: Dict[str, float] = {}
        self._count: Dict[str, int] = {}

    # -- accumulation ------------------------------------------------------
    def observe(self, name: str, chunk) -> None:
        arr = np.asarray(chunk, np.float64).ravel()
        if arr.size == 0:
            return
        lo, hi = float(arr.min()), float(arr.max())
        am = max(abs(lo), abs(hi))
        self._min[name] = min(self._min.get(name, lo), lo)
        self._max[name] = max(self._max.get(name, hi), hi)
        self._absmax[name] = max(self._absmax.get(name, am), am)
        self._count[name] = self._count.get(name, 0) + arr.size
        th = self._th.get(name)
        if th is None:
            th = am if am > 0 else 1.0
            self._th[name] = th
            self._hist[name] = np.zeros(self.num_bins, np.int64)
        elif am > th:
            factor = 2 ** int(math.ceil(math.log2(am / th)))
            self._rebin(name, th * factor)
            th = self._th[name]
        self._hist[name] += np.histogram(arr, bins=self.num_bins,
                                         range=(-th, th))[0]

    def _rebin(self, name: str, th_new: float) -> None:
        th = self._th[name]
        hist = self._hist[name]
        centers = (np.arange(self.num_bins) + 0.5) * (2 * th / self.num_bins) - th
        idx = np.clip(((centers + th_new) * self.num_bins
                       / (2 * th_new)).astype(np.int64), 0, self.num_bins - 1)
        out = np.zeros(self.num_bins, np.int64)
        np.add.at(out, idx, hist)
        self._hist[name] = out
        self._th[name] = th_new

    # -- readout -----------------------------------------------------------
    def names(self):
        return sorted(self._count)

    def seen(self, name: str) -> bool:
        return self._count.get(name, 0) > 0

    def minmax(self, name: str) -> Tuple[float, float]:
        return self._min[name], self._max[name]

    def absmax(self, name: str) -> float:
        return self._absmax[name]

    def threshold(self, name: str, num_quantized_bins: int = 255) -> float:
        """KL-optimal clipping threshold from the streamed histogram."""
        th = self._th[name]
        if self._absmax[name] == 0.0:
            return 1e-30
        edges = np.linspace(-th, th, self.num_bins + 1)
        return optimal_threshold_from_hist(self._hist[name], edges,
                                           num_quantized_bins)

    def ranges(self) -> Dict[str, Tuple[float, float]]:
        return {n: (self._min[n], self._max[n]) for n in self.names()}


def _batch_input(batch):
    """First data tensor of whatever the feed yields: DataBatch / (x, y) /
    bare array."""
    data = getattr(batch, "data", None)
    if data is not None and isinstance(data, (list, tuple)):
        return data[0]
    if isinstance(batch, (tuple, list)):
        return batch[0]
    return batch


def collect_stats(net, sites, batches, num_batches: Optional[int] = None,
                  calib: Optional[StreamingCalibrator] = None):
    """Stream ``batches`` through ``net`` with forward pre-hooks folding each
    site's input into a :class:`StreamingCalibrator` — no activation is ever
    retained. ``sites`` is the ``contrib.quantization._walk`` site list."""
    from .. import autograd
    from ..ndarray.ndarray import NDArray
    import jax.numpy as jnp

    calib = calib or StreamingCalibrator()
    hooked = []
    for parent, key, child, name in sites:
        def mk(nm):
            def hook(block, args):
                x = args[0]
                raw = x.data if isinstance(x, NDArray) else x
                calib.observe(nm, raw)
            return hook
        child.register_forward_pre_hook(mk(name))
        hooked.append(child)
    try:
        n = 0
        for batch in batches:
            x = _batch_input(batch)
            with autograd.predict_mode():
                net(x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)))
            n += 1
            if num_batches is not None and n >= num_batches:
                break
    finally:
        for child in hooked:
            child._forward_pre_hooks.pop()
    return calib


def calibrate_feed(net, feed, mode: str = "entropy",
                   num_batches: Optional[int] = None, exclude=(),
                   logger: Optional[logging.Logger] = None
                   ) -> StreamingCalibrator:
    """Calibrate every eligible Dense/Conv site of ``net`` over ``feed`` —
    any batch iterable, including an async :class:`DeviceFeed` (reset first
    when the source is resettable, so calibration sees epoch-aligned data).

    Returns the :class:`StreamingCalibrator`; per-site ranges land in
    ``profiler.get_quant_stats()['ranges']`` so the calibration a deployment
    shipped with stays observable. ``mode`` is 'naive' (absmax) or 'entropy'
    (KL threshold) — it only selects what gets LOGGED/recorded here; both
    readouts stay available on the returned calibrator."""
    if mode not in ("naive", "entropy"):
        raise ValueError(f"calib_mode {mode!r} (naive | entropy)")
    from ..contrib.quantization import _walk
    from .. import profiler
    sites = [(p, k, c, n) for p, k, c, n in _walk(net)
             if not any(e in n for e in exclude)]
    if hasattr(feed, "reset"):
        try:
            feed.reset()
        except Exception:
            pass
    calib = collect_stats(net, sites, feed, num_batches)
    for *_, name in sites:
        if not calib.seen(name):
            continue
        lo, hi = calib.minmax(name)
        profiler.record_quant_range(name, lo, hi)
        if logger:
            t = (calib.absmax(name) if mode == "naive"
                 else calib.threshold(name))
            logger.info("calib %s: threshold=%.5g min=%.5g max=%.5g (%s)",
                        name, t, lo, hi, mode)
    return calib
