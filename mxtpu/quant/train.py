"""Quantized fused training step — fake-quant forward, straight-through grads.

``MXTPU_QUANT_STEP=int8|fp8`` switches the :class:`StepExecutor` fused step
into quantization-aware training: master weights, optimizer state, and every
gradient stay float32, but each Dense/Conv forward matmul runs low-precision
(int8 on the real MXU ``dot_general`` path with int32 accumulation; fp8 via
fake-quantization). The backward pass is the STRAIGHT-THROUGH ESTIMATOR —
gradients are computed as if the quantizer were the identity — which is the
standard QAT recipe: the fp32 master weights keep integrating small updates
the int8 grid couldn't represent, so loss stays convergent with fp32 (the
tier-1 fits assert 3-epoch parity; rtol documented in docs/quantization.md).

Plumbing: the mode is a component of the executor's trace signature (so
flipping the env var retraces exactly once and the retrace sanitizer labels
it "quant"), and :func:`quant_scope` installs the low-precision twins into
``ops.nn``'s module-level hook points only around the traced call — eager
ops, serving, and every other step cache are untouched.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import kv_quant

__all__ = ["quant_step_mode", "quant_scope", "quant_dense", "quant_conv",
           "fake_quant"]

_STEP_MODES = ("int8", "fp8")
_OFF = ("", "0", "off", "none", "fp32", "float32")


def quant_step_mode(value=None) -> Optional[str]:
    """Resolve the fused-step quantization mode: ``value`` if given, else
    ``MXTPU_QUANT_STEP``. Returns None (fp32), 'int8', or 'fp8'; anything
    else raises ``ValueError`` (never a silent fp32 fallback)."""
    raw = os.environ.get("MXTPU_QUANT_STEP", "") if value is None else value
    raw = str(raw).strip().lower()
    if raw in _OFF:
        return None
    if raw not in _STEP_MODES:
        raise ValueError(
            f"MXTPU_QUANT_STEP={raw!r} (choose from {list(_STEP_MODES)}, "
            "or unset for float32)")
    if raw == "fp8" and "fp8" not in kv_quant.KV_MODES:
        raise ValueError("MXTPU_QUANT_STEP=fp8 requires a jax with "
                         "float8_e4m3fn")
    return raw


def fake_quant(x, mode: str, per_row: bool = False):
    """Quantize-dequantize ``x`` through the ``mode`` grid in one shot —
    the value actually seen by a fake-quant forward. ``per_row`` scales per
    last-axis row (weights, per-output-channel after a (O, -1) reshape);
    default is one per-tensor scale (activations)."""
    if per_row:
        q, s = kv_quant.quantize_rows(x, mode)
        return kv_quant.dequantize_rows(q, s).astype(x.dtype)
    dtype, qmax = kv_quant.KV_MODES[mode]
    absmax = jnp.max(jnp.abs(x))
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)
    if mode == "int8":
        q = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(dtype)
    else:
        q = (x / scale).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(x.dtype)


# ---------------------------------------------------------------------------
# dense: real int8 dot_general forward, straight-through backward
# ---------------------------------------------------------------------------


def _dense_fwd_impl(x, w, mode):
    """``x (..., in) @ w (out, in).T`` low-precision. int8 runs the MXU
    2x-peak path (int8 operands, int32 accumulation, per-row activation and
    per-out-channel weight scales — same kernel shape as serve._int8_matmul);
    fp8 fake-quantizes both operands and matmuls in fp32."""
    if mode == "int8":
        x2 = x.reshape(-1, x.shape[-1])
        xq, xs = kv_quant.quantize_rows(x2, "int8")
        wq, ws = kv_quant.quantize_rows(w, "int8")
        acc = lax.dot_general(xq, wq, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * xs[:, None] * ws[None, :]
        return y.reshape(x.shape[:-1] + (w.shape[0],)).astype(x.dtype)
    return jnp.matmul(fake_quant(x, mode), fake_quant(w, mode, True).T)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ste_dense(x, w, mode):
    return _dense_fwd_impl(x, w, mode)


def _ste_dense_fwd(x, w, mode):
    return _dense_fwd_impl(x, w, mode), (x, w)


def _ste_dense_bwd(mode, res, g):
    # straight-through: the grads of the UNQUANTIZED y = x @ w.T
    x, w = res
    dx = jnp.matmul(g, w)
    lead = tuple(range(g.ndim - 1))
    dw = jnp.tensordot(g, x, axes=(lead, lead))
    return dx.astype(x.dtype), dw.astype(w.dtype)


_ste_dense.defvjp(_ste_dense_fwd, _ste_dense_bwd)


def quant_dense(x, w, mode: str = "int8"):
    """The ``ops.nn._fully_connected`` matmul twin (bias is added by the
    caller in fp32). Records the staged site into ``get_quant_stats()`` —
    the call fires at TRACE time, so the counter reads 'quantized matmul
    sites compiled', not per-step dispatches."""
    from .. import profiler
    profiler.record_quant_matmuls(1)
    return _ste_dense(x, w, mode)


# ---------------------------------------------------------------------------
# conv: fake-quant forward, fp32-vjp backward
# ---------------------------------------------------------------------------


def _conv_apply(x, w, cfg):
    _, stride, padding, dilate, dn, groups = cfg
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=list(padding),
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=groups)


def _conv_fwd_impl(x, w, cfg):
    mode = cfg[0]
    O = w.shape[0]
    wf = fake_quant(w.reshape(O, -1), mode, True).reshape(w.shape)
    return _conv_apply(fake_quant(x, mode), wf, cfg)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ste_conv(x, w, cfg):
    return _conv_fwd_impl(x, w, cfg)


def _ste_conv_fwd(x, w, cfg):
    return _conv_fwd_impl(x, w, cfg), (x, w)


def _ste_conv_bwd(cfg, res, g):
    # straight-through via the vjp of the fp32 conv at the UNQUANTIZED point
    x, w = res
    _, vjp = jax.vjp(lambda a, b: _conv_apply(a, b, cfg), x, w)
    return vjp(g)


_ste_conv.defvjp(_ste_conv_fwd, _ste_conv_bwd)


def quant_conv(x, w, *, window_strides, padding, rhs_dilation,
               dimension_numbers, feature_group_count, mode: str = "int8"):
    """The ``ops.nn._convolution`` kernel twin. The conv geometry is folded
    into one hashable nondiff cfg tuple so ``custom_vjp`` treats it as
    static (``ConvDimensionNumbers`` is a namedtuple of tuples)."""
    from .. import profiler
    profiler.record_quant_matmuls(1)
    cfg = (mode, tuple(window_strides), tuple(tuple(p) for p in padding),
           tuple(rhs_dilation), dimension_numbers, int(feature_group_count))
    return _ste_conv(x, w, cfg)


@contextmanager
def quant_scope(mode: Optional[str]):
    """Install the low-precision Dense/Conv twins into ``ops.nn``'s hook
    points for the duration of the block — the StepExecutor wraps exactly
    its traced call in this, so the scope decides what gets STAGED; the
    compiled program keeps its precision for life regardless of the hooks'
    later state. No-op (and zero overhead) when ``mode`` is None."""
    if not mode:
        yield
        return
    from ..ops import nn as _nn
    prev = (_nn._QUANT_DENSE, _nn._QUANT_CONV)
    _nn._QUANT_DENSE = partial(quant_dense, mode=mode)
    _nn._QUANT_CONV = partial(quant_conv, mode=mode)
    try:
        yield
    finally:
        _nn._QUANT_DENSE, _nn._QUANT_CONV = prev
