"""Quantized paged KV cache — the int8/fp8 twin of the serving cache arrays.

The serving engine's KV cache is one static ``(L, 2, S, H, TOT, D)`` array
(``mxtpu/serving/kv.py``); at float32 its bytes are the binding constraint on
resident slots per device (ROADMAP item 2). :class:`QuantKV` stores the same
geometry as an int8 (or float8_e4m3fn) ``data`` array plus a float32
``scale`` array of shape ``(L, 2, S, H, TOT)`` — ONE symmetric absmax scale
per (layer, k/v, slot, head, token) row, stored alongside the 32-token blocks
so every slice the paging layer takes (slot rows, prefix blocks, bucket
promotions) slices ``data`` and ``scale`` congruently.

Why per-token-per-head rows:

* **Quantize-on-append** — the decode/prefill step writes exactly one
  ``(S, H, D)`` row per position; a per-row scale is computed from that row
  alone, so appending NEVER re-quantizes a neighbor and a row's bytes are
  immutable once written (the property the radix prefix cache's bit-exact
  sharing rests on).
* **Bounded error** — symmetric round-to-nearest over ``±absmax`` gives a
  per-element round-trip error ``|x - deq(q(x))| <= absmax / 254`` for int8
  (half a quantization step, ``step = absmax/127``); the bound is asserted
  per block by ``tests/test_quant.py``.
* **Capacity math** — per-row overhead is 4 bytes of scale per ``D`` int8
  elements: shrink vs float32 = ``4D / (D + 4)`` — 3.56x at the tiny
  preset's D=32, 3.94x at D=128, always >= 1.9x for D >= 5 (the acceptance
  floor; ``docs/quantization.md`` has the table).

:class:`QuantKV` is a registered jax pytree, so it rides ``lax.scan``
carries, ``jax.jit`` arguments, and ``ServingHandoff`` host round-trips
exactly like the raw array it replaces. Every helper here dispatches on
raw-array vs QuantKV, so ``serving/kv.py`` and the engine call ONE function
(``empty``/``promote``/``merge_page``/...) regardless of cache dtype.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["QuantKV", "KV_MODES", "quantize_rows", "dequantize_rows",
           "roundtrip_error_bound", "empty", "empty_page", "promote",
           "merge_page", "slot_page", "to_host", "to_device", "install_rows",
           "block_slice", "cache_nbytes", "page_nbytes", "shrink_vs_f32"]

# fp8 support is gated on the installed jax exposing float8_e4m3fn (it does
# from 0.4.x); the int8 path never touches it
_FP8 = getattr(jnp, "float8_e4m3fn", None)

# mode -> (storage dtype, max representable magnitude the scale maps onto)
KV_MODES = {"int8": (jnp.int8, 127.0)}
if _FP8 is not None:
    KV_MODES["fp8"] = (_FP8, 448.0)


@jax.tree_util.register_pytree_node_class
class QuantKV:
    """A quantized KV cache/page: ``data`` (..., D) low-precision values and
    ``scale`` (...,) float32 per-row dequantization factors, with
    ``deq = data.astype(f32) * scale[..., None]``. ``mode`` ('int8'/'fp8')
    is static metadata and participates in trace signatures via the pytree
    aux, so an int8 and an fp8 cache can never silently share a program."""

    __slots__ = ("data", "scale", "mode")

    def __init__(self, data, scale, mode: str = "int8"):
        self.data = data
        self.scale = scale
        self.mode = mode

    def tree_flatten(self):
        return (self.data, self.scale), self.mode

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.data.shape

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes) + int(self.scale.nbytes)

    def dequantize(self):
        """Full-precision view (tests/debugging; the serving step dequantizes
        per layer in-kernel instead of materializing this)."""
        return dequantize_rows(self.data, self.scale)

    def __repr__(self):
        return (f"QuantKV(mode={self.mode!r}, shape={self.data.shape}, "
                f"nbytes={self.nbytes})")


def _mode_of(mode: str) -> Tuple:
    try:
        return KV_MODES[mode]
    except KeyError:
        raise ValueError(
            f"unknown KV quantization mode {mode!r} "
            f"(choose from {sorted(KV_MODES)})") from None


def quantize_rows(x, mode: str = "int8"):
    """Symmetric per-row quantization over the LAST axis.

    Returns ``(q, scale)`` with ``x ~= q.astype(f32) * scale[..., None]``;
    ``scale = absmax / qmax`` (1.0 for all-zero rows, so zeros round-trip
    exactly and freshly-zeroed cache rows are valid)."""
    dtype, qmax = _mode_of(mode)
    absmax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)
    inv = x / scale[..., None]
    if mode == "int8":
        q = jnp.clip(jnp.round(inv), -qmax, qmax).astype(dtype)
    else:
        q = inv.astype(dtype)
    return q, scale


def dequantize_rows(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


def roundtrip_error_bound(x, mode: str = "int8"):
    """Per-row worst-case |x - deq(q(x))| bound: half a quantization step
    for int8's round-to-nearest; fp8 e4m3 keeps >= 2 mantissa bits over the
    top binade, so half of absmax/2^2 bounds it (loose but sufficient for
    the tests' contract)."""
    _, qmax = _mode_of(mode)
    absmax = jnp.max(jnp.abs(x), axis=-1)
    if mode == "int8":
        return absmax / (2.0 * qmax)
    return absmax / 8.0


# ---------------------------------------------------------------------------
# paging helpers — ONE surface over raw arrays and QuantKV
# ---------------------------------------------------------------------------


def empty(shape: Tuple[int, ...], dtype=jnp.float32,
          quant: Optional[str] = None):
    """An all-zero cache/page of the serving geometry ``(..., TOT, D)``:
    a plain ``dtype`` array, or a :class:`QuantKV` when ``quant`` names a
    mode (zero data + unit scales — a valid round-trip of zeros)."""
    if quant is None:
        return jnp.zeros(shape, dtype)
    qdtype, _ = _mode_of(quant)
    return QuantKV(jnp.zeros(shape, qdtype),
                   jnp.ones(shape[:-1], jnp.float32), quant)


def empty_page(L: int, H: int, D: int, PB: int, dtype=jnp.float32,
               quant: Optional[str] = None):
    """A fresh single-request prefill page ``(L, 2, 1, H, PB, D)``."""
    return empty((L, 2, 1, H, PB, D), dtype, quant)


def promote(caches, TOT_new: int):
    """Zero-pad into a bigger TOT bucket (content-preserving: positions past
    the old TOT are unwritten by definition). Mirrors ``serving.kv.promote``
    for the quantized cache — pad scales with 1.0 so the padded rows stay a
    valid round-trip of zeros."""
    if not isinstance(caches, QuantKV):
        L, two, S, H, TOT_old, D = caches.shape
        if TOT_new <= TOT_old:
            return caches
        return jnp.zeros((L, two, S, H, TOT_new, D), caches.dtype) \
            .at[..., :TOT_old, :].set(caches)
    L, two, S, H, TOT_old, D = caches.data.shape
    if TOT_new <= TOT_old:
        return caches
    data = jnp.zeros((L, two, S, H, TOT_new, D), caches.data.dtype) \
        .at[..., :TOT_old, :].set(caches.data)
    scale = jnp.ones((L, two, S, H, TOT_new), jnp.float32) \
        .at[..., :TOT_old].set(caches.scale)
    return QuantKV(data, scale, caches.mode)


def merge_page(caches, page, slot: int):
    """Install a prefilled ``(L, 2, 1, H, PB, D)`` page as slot row ``slot``,
    zeroing the row's tail past PB (stale K/V from the slot's previous
    tenant must not survive admission) — data and scale congruently."""
    if not isinstance(caches, QuantKV):
        PB = page.shape[4]
        row = jnp.zeros(caches.shape[:2] + caches.shape[3:], caches.dtype) \
            .at[..., :PB, :].set(page[:, :, 0])
        return caches.at[:, :, slot].set(row)
    PB = page.data.shape[4]
    dsh = caches.data.shape
    row = jnp.zeros(dsh[:2] + dsh[3:], caches.data.dtype) \
        .at[..., :PB, :].set(page.data[:, :, 0])
    # scale row shape is (L, 2, H, TOT): the data row minus its D axis
    srow = jnp.ones(dsh[:2] + (dsh[3], dsh[4]), jnp.float32) \
        .at[..., :PB].set(page.scale[:, :, 0])
    return QuantKV(caches.data.at[:, :, slot].set(row),
                   caches.scale.at[:, :, slot].set(srow), caches.mode)


def slot_page(caches, slot: int):
    """One slot's page ``(L, 2, 1, H, TOT, D)`` — the drain() unit."""
    if not isinstance(caches, QuantKV):
        return caches[:, :, slot:slot + 1]
    return QuantKV(caches.data[:, :, slot:slot + 1],
                   caches.scale[:, :, slot:slot + 1], caches.mode)


def to_host(page):
    """Host-land a page for a mesh-independent handoff (numpy leaves)."""
    if not isinstance(page, QuantKV):
        return np.asarray(page)
    return QuantKV(np.asarray(page.data), np.asarray(page.scale), page.mode)


def to_device(page):
    if not isinstance(page, QuantKV):
        return jnp.asarray(page)
    return QuantKV(jnp.asarray(page.data), jnp.asarray(page.scale),
                   page.mode)


def install_rows(page, blocks, m: int):
    """Seed a fresh page's first ``m`` token rows from a list of cached
    prefix blocks (the PrefixCache hit path). Quantized blocks install their
    BYTES — the shared prefix stays bit-identical across requests and never
    pays a second quantization."""
    if not blocks or m == 0:
        return page
    if not isinstance(page, QuantKV):
        return page.at[..., :m, :].set(jnp.concatenate(blocks, axis=4))
    return QuantKV(
        page.data.at[..., :m, :].set(
            jnp.concatenate([b.data for b in blocks], axis=4)),
        page.scale.at[..., :m].set(
            jnp.concatenate([b.scale for b in blocks], axis=4)),
        page.mode)


def block_slice(page, start: int, size: int):
    """Token rows ``[start, start+size)`` of a page — the PrefixCache
    insertion unit (data and scale sliced congruently)."""
    if not isinstance(page, QuantKV):
        return page[..., start:start + size, :]
    return QuantKV(page.data[..., start:start + size, :],
                   page.scale[..., start:start + size], page.mode)


def cache_nbytes(caches) -> int:
    """Resident bytes of a cache/page (data + scales for QuantKV) — the
    ``kv_bytes_resident`` stat and the bench shrink numerator."""
    if caches is None:
        return 0
    return int(caches.nbytes)


def page_nbytes(L: int, H: int, D: int, tokens: int, dtype=jnp.float32,
                quant: Optional[str] = None) -> int:
    """Analytic bytes of ``tokens`` KV positions (both K and V) across all
    layers/heads — the PrefixCache block accounting and the fixed-HBM-budget
    slot math in ``bench.py quant``."""
    rows = L * 2 * H * tokens
    if quant is None:
        return rows * D * jnp.dtype(dtype).itemsize
    qdtype, _ = _mode_of(quant)
    return rows * (D * jnp.dtype(qdtype).itemsize + 4)   # +4: f32 scale


def shrink_vs_f32(L: int, H: int, D: int, tokens: int,
                  quant: str = "int8") -> float:
    """KV-bytes shrink factor vs a float32 cache of identical geometry
    (= ``4D / (D + 4)`` for int8; the acceptance floor is 1.9x)."""
    return (page_nbytes(L, H, D, tokens)
            / page_nbytes(L, H, D, tokens, quant=quant))
