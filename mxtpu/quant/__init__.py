"""mxtpu.quant — end-to-end low-precision execution (ROADMAP item 2).

Four surfaces, one subsystem:

* :mod:`~mxtpu.quant.kv_quant` — int8/fp8 paged KV cache (QuantKV pytree,
  per-token-per-head scales, quantize-on-append).
* :mod:`~mxtpu.quant.serve` — quantized serving decode: ``QuantSpec`` /
  ``parse_quant`` (``MXTPU_SERVING_QUANT``), ``quantize_lm`` weight-only
  int8, and the quantized twin of ``TransformerLM.serving_step``.
* :mod:`~mxtpu.quant.train` — QAT fused step (``MXTPU_QUANT_STEP``):
  fake-quant/int8 forward matmuls with straight-through grads under fp32
  master weights, installed into the StepExecutor trace scope.
* :mod:`~mxtpu.quant.calibrate` — streaming entropy/min-max calibration
  over a ``DeviceFeed`` (lifted out of ``contrib/quantization.py``).

Submodules import lazily so ``import mxtpu.quant`` costs nothing until a
surface is touched (the step cache probes ``quant.train`` per step).
"""

from __future__ import annotations

import importlib

_SUBMODULES = ("kv_quant", "serve", "train", "calibrate")

# re-exported names -> owning submodule
_LAZY = {
    "QuantKV": "kv_quant", "KV_MODES": "kv_quant",
    "quantize_rows": "kv_quant", "dequantize_rows": "kv_quant",
    "QuantSpec": "serve", "parse_quant": "serve", "quantize_lm": "serve",
    "quant_step_mode": "train", "quant_scope": "train",
    "StreamingCalibrator": "calibrate", "calibrate_feed": "calibrate",
}

__all__ = list(_SUBMODULES) + sorted(_LAZY)


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    mod = _LAZY.get(name)
    if mod is not None:
        return getattr(importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
