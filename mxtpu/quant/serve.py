"""Quantized serving decode — int8/fp8 KV and int8 per-channel weights.

Two independently selectable axes (``MXTPU_SERVING_QUANT`` tokens, or
``ServingEngine(quant=...)``):

* ``int8_kv`` / ``fp8_kv`` — the paged KV cache is a
  :class:`~mxtpu.quant.kv_quant.QuantKV` (quantize-on-append, per-token-
  per-head scales, dequantize-in-kernel at attention). Composes with the
  radix :class:`~mxtpu.serving.kv.PrefixCache` (cached prefix blocks are
  stored and shared QUANTIZED, so the capacity win multiplies with the hit
  rate) and with ``drain()/adopt()`` handoff.
* ``int8_w`` — :func:`quantize_lm` rewrites the model's ``_gen_params()``
  pytree: every matmul weight becomes an int8 tensor + a per-output-channel
  float32 scale (LLM.int8()/AWQ-style weight-only quantization). Matmuls
  issue ``lax.dot_general`` with int8 operands and
  ``preferred_element_type=int32`` — the MXU's 2x-peak int8 path —
  with a dynamic per-row activation scale folded into the accumulator
  readout. Biases, LayerNorms, and the position table stay float32.

:func:`build_step` mirrors :meth:`TransformerLM.serving_step` exactly —
same einsums, same per-slot scatter, same masking — so the quantized
program keeps every contract the engine relies on (row independence,
one trace per (slots, TOT) bucket; quantized params and scales ride as
traced jit ARGUMENTS, so weight updates or engine restarts never retrace).
The fp32 path through ``serving/kv.py`` is untouched: ``build_decode`` /
``build_prefill_chunk`` select this step fn only when a spec is active.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import kv_quant

__all__ = ["QuantSpec", "parse_quant", "quantize_lm", "build_step",
           "build_verify_step", "quant_param_specs"]

# weight tensors of one transformer layer's _gen_params dict that carry a
# matmul (biases/norms excluded); "embed" is handled separately (tied head)
_LAYER_MATMULS = ("qw", "kw", "vw", "ow", "f1w", "f2w")

_VALID_TOKENS = {"int8_kv": ("kv", "int8"), "fp8_kv": ("kv", "fp8"),
                 "int8_w": ("weights", "int8")}


def _constrain_raw(x, entry: str):
    """Activation/cache constraint hook mirroring the fp32 step functions
    (identity outside ``parallel.fsdp.layout_scope``; the sharded serving
    engine opens the scope while the quantized programs trace)."""
    from ..parallel import fsdp as _fsdp
    return _fsdp.constrain(x, entry)


@dataclass(frozen=True)
class QuantSpec:
    """Resolved low-precision configuration for one serving engine.

    ``kv`` is the KV-cache mode (None | 'int8' | 'fp8'); ``weights`` the
    matmul-weight mode (None | 'int8'). Frozen: an engine holds ONE spec
    for its lifetime, so its program caches stay keyed on (slots, bucket,
    chunk) exactly as the fp32 engine — no retrace churn."""
    kv: Optional[str] = None
    weights: Optional[str] = None

    @property
    def enabled(self) -> bool:
        return bool(self.kv or self.weights)

    @property
    def tag(self) -> str:
        """Stable human-readable tag ('fp32', 'int8_kv', 'int8_kv+int8_w',
        ...) — the stats/bench label."""
        parts = []
        if self.kv:
            parts.append(f"{self.kv}_kv")
        if self.weights:
            parts.append(f"{self.weights}_w")
        return "+".join(parts) if parts else "fp32"


def parse_quant(value) -> QuantSpec:
    """Parse ``MXTPU_SERVING_QUANT`` / ``ServingEngine(quant=...)``:
    a :class:`QuantSpec` passes through; a comma-separated token string
    (``int8_kv``, ``fp8_kv``, ``int8_w``) composes one; None/'' disables.
    Unknown tokens raise ``ValueError`` (never silently fp32)."""
    if value is None:
        return QuantSpec()
    if isinstance(value, QuantSpec):
        return value
    fields = {}
    for tok in str(value).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok not in _VALID_TOKENS:
            raise ValueError(
                f"unknown quantization token {tok!r} in {value!r} "
                f"(choose from {sorted(_VALID_TOKENS)})")
        field, mode = _VALID_TOKENS[tok]
        if fields.get(field, mode) != mode:
            raise ValueError(f"conflicting quantization tokens in {value!r}")
        fields[field] = mode
    if fields.get("kv") == "fp8" and "fp8" not in kv_quant.KV_MODES:
        raise ValueError("fp8_kv requires a jax with float8_e4m3fn")
    return QuantSpec(**fields)


def _quantize_weight(w):
    """Symmetric per-output-channel int8: ``w (out, in) ~= q * s[:, None]``
    (scale = absmax/127 — kv_quant's row convention over the IN axis)."""
    return kv_quant.quantize_rows(w, "int8")


def quantize_lm(model, spec: QuantSpec = None):
    """The engine-side params pytree for ``spec``.

    With ``weights='int8'`` every matmul weight ``<name>`` in the model's
    ``_gen_params()`` pytree is replaced by ``<name>_q`` (int8) +
    ``<name>_s`` (float32 per-output-channel scales); the embedding table
    becomes ``embed_q``/``embed_s`` with per-VOCAB-ROW scales, which serves
    both the lookup (dequantize one row) and the tied head (the row axis is
    the output axis of ``h @ E^T``). Biases, LayerNorm params, and the
    position table stay float32. Everything returned is a traced jit
    argument downstream — quantizing is a one-time host-side pass.

    Per-tensor max-abs round-trip error is recorded into
    ``profiler.get_quant_stats()`` (the quant-regression observability
    contract)."""
    params = model._gen_params()
    if spec is None or spec.weights != "int8":
        return params
    from .. import profiler

    def q(name, w):
        wq, ws = _quantize_weight(w)
        err = float(jnp.max(jnp.abs(w - kv_quant.dequantize_rows(wq, ws))))
        profiler.record_quant_error(name, err)
        return wq, ws

    out = {k: v for k, v in params.items() if k != "embed"}
    out["embed_q"], out["embed_s"] = q("embed", params["embed"])
    layers = []
    for i, lp in enumerate(params["layers"]):
        nlp = {k: v for k, v in lp.items() if k not in _LAYER_MATMULS}
        for name in _LAYER_MATMULS:
            nlp[name + "_q"], nlp[name + "_s"] = q(f"layers[{i}].{name}",
                                                   lp[name])
        layers.append(nlp)
    out["layers"] = layers
    if "head_w" in params:
        out.pop("head_w")
        out["head_w_q"], out["head_w_s"] = q("head_w", params["head_w"])
    return out


def _int8_matmul(h, w_q, w_s):
    """``h (S, in) @ deq(w_q (out, in)).T`` on the int8 MXU path: dynamic
    per-row activation quantization, int32 accumulation, one fused rescale
    by (activation scale x per-out-channel weight scale)."""
    h_q, h_s = kv_quant.quantize_rows(h, "int8")
    acc = lax.dot_general(h_q, w_q, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * h_s[:, None] * w_s[None, :]


def build_step(model, S: int, TOT: int, spec: QuantSpec, decode_kernel=None):
    """The quantized twin of :meth:`TransformerLM.serving_step` — identical
    decode math with (a) KV rows quantized on append and the attention read
    running FUSED over the quantized storage when ``spec.kv`` is set
    (``caches`` is then a :class:`QuantKV`; see
    :mod:`mxtpu.ops.quant_attention` — the full-precision KV view is never
    materialized on either the pallas or the xla path), and (b) weight
    matmuls on the int8 path when ``spec.weights`` is set (``params`` from
    :func:`quantize_lm`).

    ``decode_kernel`` picks the attention-read path ('pallas'/'xla'/None =
    ``MXTPU_DECODE_KERNEL`` + backend auto) and is resolved ONCE here at
    build time, so the compiled program is pinned to one kernel and env
    flips between dispatches cannot retrace.

    Returns ``step(params, caches, tok, p) -> (new_caches, logits)`` with
    the same row-independence property as the fp32 step: slot ``s``'s
    output depends only on its own cache row and position, so the engine's
    continuous-batching semantics carry over unchanged. Records the
    quantized-matmul site count into ``get_quant_stats()`` at build time."""
    H = model.blocks[0].attn._heads
    U = model._units
    D = U // H
    scale = 1.0 / math.sqrt(D)
    wq = spec.weights == "int8"
    kvq = spec.kv
    if kvq:
        from ..ops import quant_attention
        dec_kernel = quant_attention.resolve_decode_kernel(
            decode_kernel, TOT=TOT, D=D)
    if wq or kvq:
        from .. import profiler
        # matmul sites staged per step: 6 per layer + tied/untied head
        n_sites = (6 * len(model.blocks) + 1) if wq else 0
        profiler.record_quant_matmuls(n_sites)

    def ln(x, g, b, eps=1e-5):
        m = jnp.mean(x, axis=-1, keepdims=True)
        v = jnp.var(x, axis=-1, keepdims=True)
        return (x - m) * lax.rsqrt(v + eps) * g + b

    def mm(h, lp, w, b):
        if wq:
            return _int8_matmul(h, lp[w + "_q"], lp[w + "_s"]) + lp[b]
        return h @ lp[w].T + lp[b]

    def step(params, caches, tok, p):
        rows = jnp.arange(S)
        pc = jnp.clip(p, 0, TOT - 1)
        if wq:
            x = kv_quant.dequantize_rows(params["embed_q"][tok],
                                         params["embed_s"][tok]) \
                + params["pos"][pc]
        else:
            x = params["embed"][tok] + params["pos"][pc]       # (S, U)
        x = _constrain_raw(x, "activations")
        mask = jnp.arange(TOT)[None, :] <= pc[:, None]         # (S, TOT)
        new_caches = caches
        for i, lp in enumerate(params["layers"]):
            h = ln(x, lp["ln1_g"], lp["ln1_b"])
            q = mm(h, lp, "qw", "qb").reshape(S, H, D)
            k = mm(h, lp, "kw", "kb").reshape(S, H, D)
            v = mm(h, lp, "vw", "vb").reshape(S, H, D)
            # per-slot scatter, quantize-on-append: slot s writes only its
            # own row at its own position, as one (D,) int8 row + one f32
            # scale — written bytes are immutable, so prefix blocks sliced
            # off this cache are shareable bit-exactly
            if kvq:
                k_q, k_s = kv_quant.quantize_rows(k, kvq)
                v_q, v_s = kv_quant.quantize_rows(v, kvq)
                data = new_caches.data \
                    .at[i, 0, rows, :, pc].set(k_q) \
                    .at[i, 1, rows, :, pc].set(v_q)
                scl = new_caches.scale \
                    .at[i, 0, rows, :, pc].set(k_s) \
                    .at[i, 1, rows, :, pc].set(v_s)
                new_caches = kv_quant.QuantKV(data, scl, kvq)
                # fused dequant-attention: the quantized storage feeds the
                # read directly — no dequantized (S, H, TOT, D) view exists
                # on either path (the 0.78x-regression fix)
                ctx = quant_attention.dequant_attention_decode(
                    q, new_caches.data[i, 0], new_caches.scale[i, 0],
                    new_caches.data[i, 1], new_caches.scale[i, 1],
                    pc, scale=scale, kernel=dec_kernel).reshape(S, U)
            else:
                new_caches = new_caches.at[i, 0, rows, :, pc].set(k)
                new_caches = new_caches.at[i, 1, rows, :, pc].set(v)
                K = new_caches[i, 0]        # (S, H, TOT, D)
                V = new_caches[i, 1]
                s = jnp.einsum("bhd,bhtd->bht", q, K) * scale
                s = jnp.where(mask[:, None, :], s, -1e30)
                att = jax.nn.softmax(s, axis=-1)
                ctx = jnp.einsum("bht,bhtd->bhd", att, V).reshape(S, U)
            # all-gather before each row matmul — replicated ow/f2w under
            # the serving layout keep the contraction a full local dot
            # (the sharded bit-exactness contract; mxtpu/serving/sharded.py)
            ctx = _constrain_raw(ctx, "activations")
            x = x + mm(ctx, lp, "ow", "ob")
            g = ln(x, lp["ln2_g"], lp["ln2_b"])
            g = jax.nn.gelu(mm(g, lp, "f1w", "f1b"), approximate=False)
            g = _constrain_raw(g, "activations")
            x = x + mm(g, lp, "f2w", "f2b")
        h = ln(x, params["ln_f_g"], params["ln_f_b"])
        if wq:
            if "head_w_q" in params:
                logits = _int8_matmul(h, params["head_w_q"],
                                      params["head_w_s"]) + params["head_b"]
            else:
                logits = _int8_matmul(h, params["embed_q"],
                                      params["embed_s"])
        elif "head_w" in params:
            logits = h @ params["head_w"].T + params["head_b"]
        else:
            logits = h @ params["embed"].T                      # (S, vocab)
        # pin the carry sharding to the engine's canonical placement
        if kvq:
            new_caches = kv_quant.QuantKV(
                _constrain_raw(new_caches.data, "kv_cache"),
                _constrain_raw(new_caches.scale, "kv_cache"), kvq)
        else:
            new_caches = _constrain_raw(new_caches, "kv_cache")
        return new_caches, logits

    return step


def build_verify_step(model, S: int, TOT: int, K1: int, spec: QuantSpec,
                      decode_kernel=None):
    """The quantized twin of :meth:`TransformerLM.serving_verify_step`:
    one forward scoring ``K1`` = k + 1 consecutive positions per slot for
    speculative decode, over quantized KV and/or int8 weights.

    Bit-exactness with :func:`build_step` is structural, exactly as the
    fp32 pair: dense matmuls run on the flattened ``(S * K1, in)`` row
    batch (per-row activation scales make each row's int8 dot identical to
    the single-step one), all ``K1`` K/V rows quantize-on-append before
    any query reads, and the attention read loops the drafted positions
    through the SAME :func:`~mxtpu.ops.quant_attention
    .dequant_attention_decode` call the decode step issues — one position
    per call, per-slot read cursor ``p + j`` — on both the pallas and the
    xla kernel. Rejected drafts leave quantized garbage rows (data AND
    per-row scales) above the accept point; both are overwritten
    congruently by the next dispatch before anything attends them, so the
    int8 scales roll back with the write cursor for free."""
    H = model.blocks[0].attn._heads
    U = model._units
    D = U // H
    scale = 1.0 / math.sqrt(D)
    wq = spec.weights == "int8"
    kvq = spec.kv
    if kvq:
        from ..ops import quant_attention
        dec_kernel = quant_attention.resolve_decode_kernel(
            decode_kernel, TOT=TOT, D=D)

    def ln(x, g, b, eps=1e-5):
        m = jnp.mean(x, axis=-1, keepdims=True)
        v = jnp.var(x, axis=-1, keepdims=True)
        return (x - m) * lax.rsqrt(v + eps) * g + b

    def mm(h, lp, w, b):
        if wq:
            return _int8_matmul(h, lp[w + "_q"], lp[w + "_s"]) + lp[b]
        return h @ lp[w].T + lp[b]

    def step(params, caches, toks, p):
        rows = jnp.arange(S)
        pcs = jnp.clip(p[:, None] + jnp.arange(K1)[None, :], 0, TOT - 1)
        if wq:
            x = kv_quant.dequantize_rows(params["embed_q"][toks],
                                         params["embed_s"][toks]) \
                + params["pos"][pcs]
        else:
            x = params["embed"][toks] + params["pos"][pcs]   # (S, K1, U)
        x = _constrain_raw(x, "activations")
        mask = jnp.arange(TOT)[None, None, :] <= pcs[:, :, None]
        new_caches = caches
        for i, lp in enumerate(params["layers"]):
            h = ln(x, lp["ln1_g"], lp["ln1_b"])
            flat = h.reshape(S * K1, U)
            q = mm(flat, lp, "qw", "qb").reshape(S, K1, H, D)
            k = mm(flat, lp, "kw", "kb").reshape(S, K1, H, D)
            v = mm(flat, lp, "vw", "vb").reshape(S, K1, H, D)
            if kvq:
                data, scl = new_caches.data, new_caches.scale
                for j in range(K1):
                    k_q, k_s = kv_quant.quantize_rows(k[:, j], kvq)
                    v_q, v_s = kv_quant.quantize_rows(v[:, j], kvq)
                    data = data.at[i, 0, rows, :, pcs[:, j]].set(k_q) \
                               .at[i, 1, rows, :, pcs[:, j]].set(v_q)
                    scl = scl.at[i, 0, rows, :, pcs[:, j]].set(k_s) \
                             .at[i, 1, rows, :, pcs[:, j]].set(v_s)
                new_caches = kv_quant.QuantKV(data, scl, kvq)
                ctx = jnp.stack([
                    quant_attention.dequant_attention_decode(
                        q[:, j], new_caches.data[i, 0],
                        new_caches.scale[i, 0], new_caches.data[i, 1],
                        new_caches.scale[i, 1], pcs[:, j], scale=scale,
                        kernel=dec_kernel)
                    for j in range(K1)], axis=1).reshape(S, K1, U)
            else:
                for j in range(K1):
                    new_caches = new_caches \
                        .at[i, 0, rows, :, pcs[:, j]].set(k[:, j]) \
                        .at[i, 1, rows, :, pcs[:, j]].set(v[:, j])
                K = new_caches[i, 0]            # (S, H, TOT, D)
                V = new_caches[i, 1]
                ctxs = []
                for j in range(K1):
                    s = jnp.einsum("bhd,bhtd->bht", q[:, j], K) * scale
                    s = jnp.where(mask[:, j][:, None, :], s, -1e30)
                    att = jax.nn.softmax(s, axis=-1)
                    ctxs.append(jnp.einsum("bht,bhtd->bhd", att, V))
                ctx = jnp.stack(ctxs, axis=1).reshape(S, K1, U)
            # all-gather-before-row-matmul, as in build_step
            flatc = _constrain_raw(ctx.reshape(S * K1, U), "activations")
            x = x + mm(flatc, lp, "ow", "ob").reshape(S, K1, U)
            g = ln(x, lp["ln2_g"], lp["ln2_b"])
            g = jax.nn.gelu(mm(g.reshape(S * K1, U), lp, "f1w", "f1b"),
                            approximate=False)
            g = _constrain_raw(g, "activations")
            x = x + mm(g, lp, "f2w", "f2b").reshape(S, K1, U)
        h = ln(x, params["ln_f_g"], params["ln_f_b"])
        hf = h.reshape(S * K1, U)
        if wq:
            if "head_w_q" in params:
                logits = _int8_matmul(hf, params["head_w_q"],
                                      params["head_w_s"]) + params["head_b"]
            else:
                logits = _int8_matmul(hf, params["embed_q"],
                                      params["embed_s"])
        elif "head_w" in params:
            logits = hf @ params["head_w"].T + params["head_b"]
        else:
            logits = hf @ params["embed"].T
        V = logits.shape[-1]
        if kvq:
            new_caches = kv_quant.QuantKV(
                _constrain_raw(new_caches.data, "kv_cache"),
                _constrain_raw(new_caches.scale, "kv_cache"), kvq)
        else:
            new_caches = _constrain_raw(new_caches, "kv_cache")
        return new_caches, logits.reshape(S, K1, V)

    return step


def quant_param_specs(model, layout=None):
    """Partition specs for a :func:`quantize_lm` pytree under the composed
    dp x fsdp x tp flagship mesh: each ``<name>_q`` tensor inherits the
    fp32 weight's :class:`~mxtpu.parallel.fsdp.SpecLayout` entry, and each
    ``<name>_s`` scale vector follows its weight's OUTPUT-channel axis
    (``parallel.fsdp.scale_spec``) — so a tp-sharded column-parallel weight
    carries tp-sharded scales and the rescale stays local to the shard."""
    from ..parallel.fsdp import SpecLayout, scale_spec
    from jax.sharding import PartitionSpec as P
    layout = layout or SpecLayout()
    wspec = {"qw": layout.qkv_projection(), "kw": layout.qkv_projection(),
             "vw": layout.qkv_projection(), "ow": layout.attn_out(),
             "f1w": layout.ffn_up(), "f2w": layout.ffn_down()}
    layers = []
    for _ in model.blocks:
        lp = {}
        for name, sp in wspec.items():
            lp[name + "_q"] = sp
            lp[name + "_s"] = scale_spec(sp)
        for v in ("ln1_g", "ln1_b", "qb", "kb", "vb", "ob",
                  "ln2_g", "ln2_b", "f1b", "f2b"):
            lp[v] = layout.vector()
        layers.append(lp)
    emb = layout.embeddings()
    return {"embed_q": emb, "embed_s": scale_spec(emb),
            "pos": layout.vector(), "ln_f_g": layout.vector(),
            "ln_f_b": layout.vector(), "layers": layers,
            "_replicated": P()}
