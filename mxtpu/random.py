"""``mx.random`` parity module (python/mxnet/random.py): seed + top-level samplers."""

from __future__ import annotations

from .rng import seed
from .ndarray import random as _ndrand

uniform = _ndrand.uniform
normal = _ndrand.normal
randn = _ndrand.normal
gamma = _ndrand.gamma
exponential = _ndrand.exponential
poisson = _ndrand.poisson
negative_binomial = _ndrand.negative_binomial
generalized_negative_binomial = _ndrand.generalized_negative_binomial
multinomial = _ndrand.multinomial
shuffle = _ndrand.shuffle
randint = _ndrand.randint
bernoulli = _ndrand.bernoulli

__all__ = ["seed", "uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "multinomial",
           "shuffle", "randint", "bernoulli"]
