"""``mx.nd`` fused optimizer updates — reference in-place calling convention.

The reference exposes ``mx.nd.sgd_update(weight, grad, out=weight, lr=...)``
with optimizer state tensors (mom/mean/var/z/n/d/delta/weight32) declared as
MUTABLE inputs (``optimizer_op.cc:317`` ``FMutateInputs``): the op writes them
in place and outputs only the weight. The pure kernels live in
``ops/optimizer_ops.py``; this layer restores the mutation contract — states
are written back through ``_set_data``, the weight result honors ``out=`` —
and adds the reference's lazy row-sparse path (SGDDnsRspKernel /
AdamDnsRspDnsKernel / FtrlDnsRspDnsKernel: only rows live in the row_sparse
grad touch weight and state).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..ops import registry as _reg
from .ndarray import NDArray

# op name -> (state input names, supports lazy row-sparse grad)
_FUSED = {
    "sgd_update": ((), True),
    "sgd_mom_update": (("mom",), True),
    "mp_sgd_update": (("weight32",), False),
    "mp_sgd_mom_update": (("mom", "weight32"), False),
    "signsgd_update": ((), False),
    "signum_update": (("mom",), False),
    "adam_update": (("mean", "var"), True),
    "ftml_update": (("d", "v", "z"), False),
    "rmsprop_update": (("n",), False),
    "rmspropalex_update": (("n", "g", "delta"), False),
    "ftrl_update": (("z", "n"), True),
    "_sparse_adagrad_update": (("history",), True),
    "adagrad_update": (("history",), True),
}


def _apply_dense(op, weight, grad, states: Sequence[NDArray], out, kwargs):
    raw_states = [s.data for s in states]
    res = op.fn(weight.data, grad.data, *raw_states, **kwargs)
    res = res if isinstance(res, tuple) else (res,)
    new_w, new_states = res[0], res[1:]
    for s, ns in zip(states, new_states):
        s._set_data(ns)
    target = out if out is not None else weight
    target._set_data(new_w.astype(target.dtype))
    return target


def _apply_lazy(op, weight, grad, states: Sequence[NDArray], out, kwargs):
    """Row-slab update: gather live rows, run the dense kernel on the slab,
    scatter back — weight and full-shape states only change on live rows
    (reference *DnsRspDnsKernel semantics)."""
    rows = grad._indices
    vals = grad._values.astype(weight.dtype)
    if rows.shape[0] > 1 and not getattr(grad, "_rows_trusted_unique", False):
        # Reference *DnsRspDnsKernel assumes deduped row ids; our scatter is
        # last-write-wins, so duplicate rows would drop updates. Merge them
        # shape-statically (no host sync, jit-safe): sort, sum runs of equal
        # ids into the leading segments, point the padding segments past the
        # last weight row so the gather clamps and the scatter drops them.
        n, nrows = rows.shape[0], weight.shape[0]
        order = jnp.argsort(rows)
        r_s, v_s = rows[order], vals[order]
        seg = jnp.cumsum(jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), (r_s[1:] != r_s[:-1]).astype(jnp.int32)]))
        vals = jax.ops.segment_sum(v_s, seg, num_segments=n)
        rows = jnp.full((n,), nrows, rows.dtype).at[seg].set(r_s)
    w = weight.data
    row_like = [s.shape == weight.shape for s in states]
    slab_states = [s.data[rows] if rl else s.data
                   for s, rl in zip(states, row_like)]
    res = op.fn(w[rows], vals, *slab_states, **kwargs)
    res = res if isinstance(res, tuple) else (res,)
    new_rows, new_states = res[0], res[1:]
    for s, ns, rl in zip(states, new_states, row_like):
        s._set_data(s.data.at[rows].set(ns) if rl else ns)
    target = out if out is not None else weight
    target._set_data(w.at[rows].set(new_rows.astype(w.dtype)))
    return target


def _make_fused(name: str, state_names, lazy_ok: bool):
    import inspect
    op = _reg.get_op(name)
    kernel_takes_lazy = "lazy_update" in inspect.signature(op.fn).parameters

    def fused(weight, grad, *states, out: Optional[NDArray] = None, **kwargs):
        if len(states) != len(state_names):
            raise TypeError(
                f"{name} expects inputs (weight, grad"
                + "".join(f", {s}" for s in state_names) + ")")
        # lazy_update gates THIS wrapper's row-sparse path; only kernels that
        # declare it (reference *Param structs) see it as an attr
        lazy = (kwargs.pop("lazy_update", True) if not kernel_takes_lazy
                else kwargs.get("lazy_update", True))
        if getattr(grad, "stype", "default") == "row_sparse":
            if not (lazy_ok and lazy):
                grad = NDArray(grad._dense())
            else:
                return _apply_lazy(op, weight, grad, states, out, kwargs)
        return _apply_dense(op, weight, grad, states, out, kwargs)

    fused.__name__ = name
    fused.__doc__ = op.doc
    return fused


def install(module):
    """Bind the in-place wrappers into the ``mx.nd`` namespace (overriding the
    auto-generated pure wrappers)."""
    for name, (state_names, lazy_ok) in _FUSED.items():
        setattr(module, name, _make_fused(name, state_names, lazy_ok))
