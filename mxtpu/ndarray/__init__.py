"""``mx.nd``-equivalent namespace.

The reference autogenerates ``mx.nd.*`` wrappers from the C-API op registry at import
time (python/mxnet/ndarray/register.py); here the wrappers are generated from the
in-process op registry. Sub-namespaces ``linalg``/``random``/``contrib`` mirror
``mx.nd.linalg`` etc.
"""

from __future__ import annotations

import sys
import types
from typing import Optional

from ..context import Context
from ..ops import registry as _reg
from .ndarray import (NDArray, array, concatenate, empty, from_dlpack, from_numpy,
                      load, save, to_dlpack, waitall)

_this = sys.modules[__name__]


def _make_wrapper(key: str):
    op = _reg.get_op(key)

    def _fn(*args, **kwargs):
        ctx: Optional[Context] = kwargs.pop("ctx", None)
        out = _reg.invoke(op, *args, **kwargs)
        if ctx is not None:
            import jax
            if isinstance(out, tuple):
                out = tuple(NDArray(jax.device_put(o._data, ctx.jax_device)) for o in out)
            else:
                out = NDArray(jax.device_put(out._data, ctx.jax_device))
        return out

    _fn.__name__ = op.name
    try:  # dmlc::Parameter-style auto-doc: summary + typed attr table
        _fn.__doc__ = _reg.op_doc(key)
    except Exception:
        _fn.__doc__ = op.doc
    return _fn


def _populate(namespace: str, module):
    for name in _reg.list_ops(namespace):
        key = f"{namespace}.{name}" if namespace else name
        if not hasattr(module, name):
            setattr(module, name, _make_wrapper(key))


_populate("", _this)

# fused optimizer updates need the reference's in-place/mutable-state calling
# convention — hand-written wrappers override the auto-generated pure ones
from . import fused_optimizer as _fused_opt  # noqa: E402
_fused_opt.install(_this)

# one namespace list shared with mx.sym (registry.OP_NAMESPACES) so the two
# frontends expose the same sub-surfaces
for _ns in _reg.OP_NAMESPACES:
    _mod = types.ModuleType(f"{__name__}.{_ns}")
    _populate(_ns, _mod)
    globals()[_ns] = _mod
    sys.modules[_mod.__name__] = _mod
del _ns, _mod

# reference-name conveniences
def moveaxis(a, source, destination):
    import jax.numpy as jnp
    return NDArray(jnp.moveaxis(a._data, source, destination))


# add_n / ElementWiseSum / _sum resolve to the registered fused op
# (ops/elementwise.py) via the auto-generated wrappers — one tape node, not
# N-1 recorded binary adds


# sparse sub-namespace (mx.nd.sparse parity)
from . import sparse  # noqa: E402
sys.modules[__name__ + ".sparse"] = sparse

# control flow lives under nd.contrib (reference: mxnet.ndarray.contrib)
from ..ops import control_flow as _control_flow  # noqa: E402
contrib.foreach = _control_flow.foreach
contrib.while_loop = _control_flow.while_loop
contrib.cond = _control_flow.cond


def __getattr__(name):
    """Resolve ops registered AFTER import (e.g. ``Custom`` from
    mxtpu.operator, user-registered ops) straight from the registry."""
    try:
        _reg.get_op(name)
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    fn = _make_wrapper(name)
    setattr(_this, name, fn)
    return fn


def cast_storage(arr, stype: str):
    """Reference op-name parity (cast_storage, src/operator/tensor/
    cast_storage.cc): convert default/row_sparse/csr storage. Lives at the nd
    level, not the raw registry — sparse handles don't cross the raw-array
    op boundary."""
    from . import sparse as _sparse
    return _sparse.cast_storage(arr, stype)


def sparse_retain(data, indices):
    """Reference op-name parity (_sparse_retain, sparse_retain-inl.h): keep
    only the requested rows of a row_sparse array."""
    from . import sparse as _sparse
    return _sparse.retain(data, indices)
