"""Sparse NDArrays — row_sparse + CSR, TPU-native.

Capability parity with the reference's sparse storage types
(``include/mxnet/ndarray.h:62-66`` kRowSparseStorage/kCSRStorage,
``python/mxnet/ndarray/sparse.py``, ``src/operator/tensor/dot-inl.h`` sparse dot,
``src/operator/tensor/cast_storage-inl.h``), re-designed for XLA:

* A ``RowSparseNDArray`` holds ``indices`` (sorted unique int32 row ids) + ``values``
  (``(nnz_rows, *row_shape)``). This is exactly the shape of an embedding gradient —
  the dominant sparse workload — and maps to TPU-friendly gather/scatter +
  ``segment_sum`` (no dynamic shapes inside a jit: nnz is a trace-time constant per
  bucket, like the reference's per-batch kernel launches).
* A ``CSRNDArray`` holds ``data``/``indices``/``indptr``; ``dot(csr, dense)`` lowers to
  one ``segment_sum`` over expanded rows (MXU-adjacent: the inner product stays a
  vectorized multiply), ``dot(csr, dense, transpose_a=True)`` produces a
  ``RowSparseNDArray`` touching only the referenced columns — the sparse
  backward-of-embedding/linear pattern (dot-inl.h DotCsrTransDnsRsp parity).
* Gradients: ``RawRowSparse`` is the tape-level cotangent carrier; the autograd flush
  materializes it as a ``RowSparseNDArray`` in ``param.grad`` so lazy optimizers
  (optimizer.py:445 SGD lazy_update parity) touch only the live rows.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype_np
from ..context import Context
from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "BaseSparseNDArray", "RawRowSparse",
           "row_sparse_array", "csr_matrix", "cast_storage", "dot", "retain",
           "zeros", "add", "elemwise_add"]

_INT = jnp.int32  # TPU-native index dtype (the reference uses int64 on host)


class RawRowSparse:
    """Tape-level row-sparse cotangent: (indices, values, dense shape).

    Produced by sparse-grad backward rules; supports ``+`` so the autograd
    accumulation loop composes sparse+sparse (concat, dedup deferred to
    materialization) and sparse+dense (densify) without special cases.
    """

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape):
        self.indices = indices
        self.values = values
        self.shape = tuple(shape)

    def densify(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def __add__(self, other):
        if isinstance(other, RawRowSparse):
            return RawRowSparse(jnp.concatenate([self.indices, other.indices]),
                                jnp.concatenate([self.values, other.values]),
                                self.shape)
        return self.densify() + other

    __radd__ = __add__

    def dedup(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Unique sorted rows + segment-summed values (eager: nnz is data-dependent)."""
        idx_host = np.asarray(jax.device_get(self.indices))
        uniq, inv = np.unique(idx_host, return_inverse=True)
        vals = jax.ops.segment_sum(self.values, jnp.asarray(inv, _INT),
                                   num_segments=len(uniq))
        return jnp.asarray(uniq, _INT), vals


class BaseSparseNDArray:
    """Common surface of the sparse handle types (mx.nd.sparse parity)."""

    stype = "undefined"

    @property
    def dtype(self):
        return np.dtype(self._values.dtype)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def size(self) -> int:
        return int(np.prod(self._shape)) if self._shape else 0

    @property
    def context(self) -> Context:
        return NDArray(self._values).context

    ctx = context

    @property
    def grad(self):
        return None

    def wait_to_read(self):
        jax.block_until_ready(self._values)
        return self

    def asnumpy(self) -> np.ndarray:
        return np.asarray(jax.device_get(self._dense()))

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype else a

    def astype(self, dtype):
        out = self.copy()
        out._values = out._values.astype(dtype_np(dtype))
        return out

    def tostype(self, stype: str):
        return cast_storage(self, stype)

    def todense(self) -> NDArray:
        return NDArray(self._dense())

    def __repr__(self):
        return (f"\n<{type(self).__name__} {self._shape} "
                f"dtype={self.dtype.name}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse: a subset of rows is stored; absent rows are zero.

    ``.indices`` → NDArray of sorted unique row ids, ``.data`` → NDArray of the
    stored rows (mx.nd.sparse.RowSparseNDArray surface).
    """

    stype = "row_sparse"

    def __init__(self, indices, values, shape):
        self._indices = jnp.asarray(
            indices.data if isinstance(indices, NDArray) else indices, _INT)
        self._values = jnp.asarray(
            values.data if isinstance(values, NDArray) else values)
        self._shape = tuple(int(s) for s in shape)
        # producers that GUARANTEE sorted-unique ids (dedup outputs, wire
        # ingest) construct via _trusted(); consumers like the fused lazy
        # optimizer path then skip their defensive duplicate-row merge
        self._rows_trusted_unique = False
        if self._values.ndim != len(self._shape):
            raise ValueError(
                f"row_sparse values ndim {self._values.ndim} != shape ndim "
                f"{len(self._shape)} (values carry the full row shape)")

    @classmethod
    def _trusted(cls, indices, values, shape) -> "RowSparseNDArray":
        """Construct from indices the CALLER guarantees are sorted-unique
        (dedup output, host-deduped wire rows) — marks the invariant so the
        lazy optimizer path can skip its defensive merge."""
        out = cls(indices, values, shape)
        out._rows_trusted_unique = True
        return out

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values)

    @property
    def num_rows(self) -> int:
        return int(self._indices.shape[0])

    def _dense(self):
        out = jnp.zeros(self._shape, self._values.dtype)
        return out.at[self._indices].set(self._values)

    def copy(self) -> "RowSparseNDArray":
        return RowSparseNDArray(jnp.array(self._indices, copy=True),
                                jnp.array(self._values, copy=True), self._shape)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._indices, other._values = self._indices, self._values
            return other
        if isinstance(other, NDArray):
            other._set_data(self._dense().astype(other.dtype))
            return other
        raise TypeError(f"copyto: unsupported target {type(other)}")

    def retain(self, indices) -> "RowSparseNDArray":
        return retain(self, indices)

    def __add__(self, other):
        return add(self, other)

    __radd__ = __add__


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix: ``data``/``indices``/``indptr`` (2-D only)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self._values = jnp.asarray(data.data if isinstance(data, NDArray) else data)
        self._indices = jnp.asarray(
            indices.data if isinstance(indices, NDArray) else indices, _INT)
        self._indptr = jnp.asarray(
            indptr.data if isinstance(indptr, NDArray) else indptr, _INT)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise ValueError("CSRNDArray is 2-D (reference cast_storage parity)")

    @property
    def data(self) -> NDArray:
        return NDArray(self._values)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._indptr)

    @property
    def nnz(self) -> int:
        return int(self._values.shape[0])

    def _row_ids(self):
        """Per-nonzero row id, from indptr (the CSR→COO expansion)."""
        nnz = self.nnz
        return jnp.asarray(
            np.repeat(np.arange(self._shape[0]),
                      np.diff(np.asarray(jax.device_get(self._indptr)))), _INT) \
            if nnz else jnp.zeros((0,), _INT)

    def _dense(self):
        out = jnp.zeros(self._shape, self._values.dtype)
        if self.nnz == 0:
            return out
        return out.at[self._row_ids(), self._indices].set(self._values)

    def copy(self) -> "CSRNDArray":
        return CSRNDArray(jnp.array(self._values, copy=True),
                          jnp.array(self._indices, copy=True),
                          jnp.array(self._indptr, copy=True), self._shape)

    def asscipy(self):
        import scipy.sparse as sps
        return sps.csr_matrix(
            (np.asarray(jax.device_get(self._values)),
             np.asarray(jax.device_get(self._indices)),
             np.asarray(jax.device_get(self._indptr))), shape=self._shape)

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._shape[0])
            if step != 1:
                raise ValueError("csr slicing supports contiguous row ranges")
            ptr = self._indptr[start:stop + 1]
            lo, hi = int(ptr[0]), int(ptr[-1])
            return CSRNDArray(self._values[lo:hi], self._indices[lo:hi],
                              ptr - lo, (stop - start, self._shape[1]))
        raise TypeError("csr indexing supports row slices")


# ---------------------------------------------------------------------------
# constructors (mx.nd.sparse.row_sparse_array / csr_matrix parity)
# ---------------------------------------------------------------------------


def row_sparse_array(arg, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    """From ``(data, indices)``, a dense array/NDArray, or another RowSparseNDArray."""
    if isinstance(arg, RowSparseNDArray):
        return arg.copy() if shape is None else RowSparseNDArray(
            arg._indices, arg._values, shape)
    if isinstance(arg, tuple) and all(isinstance(d, (int, np.integer)) for d in arg):
        # shape tuple → empty sparse array (reference row_sparse_array(shape))
        return zeros("row_sparse", arg, ctx=ctx, dtype=dtype or "float32")
    if isinstance(arg, tuple) and len(arg) == 2:
        values, indices = arg
        values = jnp.asarray(np.asarray(values),
                             dtype=dtype_np(dtype) if dtype else None)
        if shape is None:
            indices_np = np.asarray(indices)
            nrows = int(indices_np.max()) + 1 if indices_np.size else 0
            shape = (nrows,) + tuple(values.shape[1:])
        return RowSparseNDArray(jnp.asarray(np.asarray(indices), _INT), values, shape)
    # dense input
    dense = arg.data if isinstance(arg, NDArray) else jnp.asarray(
        np.asarray(arg), dtype=dtype_np(dtype) if dtype else None)
    return _dense_to_rsp(dense)


def csr_matrix(arg, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """From ``(data, indices, indptr)``, scipy.sparse, dense, or (data,(row,col))."""
    if isinstance(arg, tuple) and all(isinstance(d, (int, np.integer)) for d in arg):
        return zeros("csr", arg, ctx=ctx, dtype=dtype or "float32")
    try:
        import scipy.sparse as sps
        if sps.issparse(arg):
            m = arg.tocsr()
            return CSRNDArray(m.data, m.indices, m.indptr, m.shape)
    except ImportError:
        pass
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        if shape is None:
            raise ValueError("csr_matrix((data, indices, indptr)) requires shape=")
        return CSRNDArray(jnp.asarray(np.asarray(data),
                                      dtype=dtype_np(dtype) if dtype else None),
                          np.asarray(indices), np.asarray(indptr), shape)
    if isinstance(arg, tuple) and len(arg) == 2 and isinstance(arg[1], tuple):
        data, (row, col) = arg
        import scipy.sparse as sps
        m = sps.coo_matrix((np.asarray(data), (np.asarray(row), np.asarray(col))),
                           shape=shape).tocsr()
        return CSRNDArray(m.data, m.indices, m.indptr, m.shape)
    dense = arg.data if isinstance(arg, NDArray) else jnp.asarray(
        np.asarray(arg), dtype=dtype_np(dtype) if dtype else None)
    return _dense_to_csr(dense)


def zeros(stype: str, shape, ctx=None, dtype="float32"):
    """mx.nd.sparse.zeros parity: an empty sparse array."""
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    dt = dtype_np(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,), _INT),
                                jnp.zeros((0,) + shape[1:], dt), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((0,), _INT),
                          jnp.zeros((shape[0] + 1,), _INT), shape)
    if stype == "default":
        return NDArray(jnp.zeros(shape, dt))
    raise ValueError(f"unknown stype {stype!r}")


# ---------------------------------------------------------------------------
# cast_storage (src/operator/tensor/cast_storage-inl.h parity)
# ---------------------------------------------------------------------------


def _dense_to_rsp(dense) -> RowSparseNDArray:
    host = np.asarray(jax.device_get(dense))
    nz_rows = np.nonzero(host.reshape(host.shape[0], -1).any(axis=1))[0]
    return RowSparseNDArray(jnp.asarray(nz_rows, _INT),
                            jnp.asarray(host[nz_rows]), host.shape)


def _dense_to_csr(dense) -> CSRNDArray:
    host = np.asarray(jax.device_get(dense))
    if host.ndim != 2:
        raise ValueError("cast_storage to csr requires a 2-D array")
    rows, cols = np.nonzero(host)
    indptr = np.zeros(host.shape[0] + 1, np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr)
    return CSRNDArray(jnp.asarray(host[rows, cols]), jnp.asarray(cols, _INT),
                      jnp.asarray(indptr, _INT), host.shape)


def cast_storage(arr, stype: str):
    """Convert between default/row_sparse/csr storage."""
    cur = getattr(arr, "stype", "default")
    if cur == stype:
        return arr
    if stype == "default":
        return arr.todense()
    dense = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr._dense())
    if stype == "row_sparse":
        return _dense_to_rsp(dense)
    if stype == "csr":
        return _dense_to_csr(dense)
    raise ValueError(f"unknown stype {stype!r}")


# ---------------------------------------------------------------------------
# sparse ops (dot-inl.h, sparse_retain, elemwise)
# ---------------------------------------------------------------------------


def dot(lhs, rhs, transpose_a: bool = False, transpose_b: bool = False):
    """Sparse dot (mx.nd.sparse.dot parity, src/operator/tensor/dot-inl.h):

    * ``dot(csr, dense)`` → dense — one ``segment_sum`` over the COO expansion.
    * ``dot(csr, dense, transpose_a=True)`` → **row_sparse** touching only columns
      referenced by the csr (DotCsrTransDnsRsp parity — the sparse-linear backward).
    * dense×dense falls through to the registered dense op.
    """
    if isinstance(lhs, CSRNDArray):
        if transpose_b:
            raise NotImplementedError("dot(csr, dense, transpose_b=True)")
        rhs_raw = rhs.data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        row_ids = lhs._row_ids()
        if not transpose_a:
            # out[i] = Σ_nz data * rhs[col]   (segment over row ids)
            contrib = lhs._values[:, None] * rhs_raw[lhs._indices]
            out = jax.ops.segment_sum(contrib, row_ids,
                                      num_segments=lhs._shape[0])
            return NDArray(out.astype(rhs_raw.dtype))
        # transpose_a: out[col] += data * rhs[row]; only touched cols stored
        contrib = lhs._values[:, None] * rhs_raw[row_ids]
        raw = RawRowSparse(lhs._indices, contrib,
                           (lhs._shape[1],) + tuple(rhs_raw.shape[1:]))
        uniq, vals = raw.dedup()
        return RowSparseNDArray._trusted(uniq, vals.astype(rhs_raw.dtype),
                                         raw.shape)
    if isinstance(lhs, RowSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        raise NotImplementedError(
            "sparse dot supports csr×dense (optionally transpose_a) — "
            "densify other operand combinations explicitly with .todense()")
    from ..ops import registry as _reg
    return _reg.invoke(_reg.get_op("dot"), lhs, rhs, transpose_a=transpose_a,
                       transpose_b=transpose_b)


def retain(rsp: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Keep only the requested rows (sparse_retain op parity)."""
    want = np.asarray(indices.asnumpy() if hasattr(indices, "asnumpy")
                      else indices).astype(np.int64)
    have = np.asarray(jax.device_get(rsp._indices))
    mask = np.isin(have, want)
    keep = np.nonzero(mask)[0]
    return RowSparseNDArray(rsp._indices[jnp.asarray(keep)],
                            rsp._values[jnp.asarray(keep)], rsp._shape)


def add(lhs, rhs):
    """elemwise add: rsp+rsp → rsp; any dense operand → dense."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        if lhs._shape != rhs._shape:
            raise ValueError(f"shape mismatch {lhs._shape} vs {rhs._shape}")
        raw = RawRowSparse(jnp.concatenate([lhs._indices, rhs._indices]),
                           jnp.concatenate([lhs._values, rhs._values]), lhs._shape)
        uniq, vals = raw.dedup()
        return RowSparseNDArray._trusted(uniq, vals, lhs._shape)
    l = lhs._dense() if isinstance(lhs, BaseSparseNDArray) else (
        lhs.data if isinstance(lhs, NDArray) else jnp.asarray(lhs))
    r = rhs._dense() if isinstance(rhs, BaseSparseNDArray) else (
        rhs.data if isinstance(rhs, NDArray) else jnp.asarray(rhs))
    return NDArray(l + r)


elemwise_add = add


# ---------------------------------------------------------------------------
# sparse elementwise family (src/operator/tensor/elemwise_binary_op_basic.cc
# FComputeEx sparse kernels; unsupported storage combinations fall back to
# dense exactly like the reference's StorageFallbackOpExecutor,
# attach_op_execs_pass.cc:46-223)
# ---------------------------------------------------------------------------


def negate(arr):
    if isinstance(arr, RowSparseNDArray):
        return RowSparseNDArray(arr._indices, -arr._values, arr._shape)
    if isinstance(arr, CSRNDArray):
        return CSRNDArray(-arr._values, arr._indices, arr._indptr, arr._shape)
    return NDArray(-(arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)))


def subtract(lhs, rhs):
    """elemwise sub: rsp-rsp -> rsp; csr-csr -> csr; dense operand -> dense."""
    return add(lhs, negate(rhs)) if isinstance(rhs, BaseSparseNDArray) else \
        add(lhs, NDArray(-(rhs.data if isinstance(rhs, NDArray)
                           else jnp.asarray(rhs))))


def multiply(lhs, rhs):
    """elemwise mul. rsp*rsp keeps the row intersection; rsp*scalar and
    csr*scalar stay sparse (zero is absorbing, unlike add); rsp*dense keeps
    the stored rows; anything else densifies."""
    if isinstance(lhs, (int, float)):
        lhs, rhs = rhs, lhs
    if isinstance(rhs, (int, float)):
        if isinstance(lhs, RowSparseNDArray):
            return RowSparseNDArray(lhs._indices, lhs._values * rhs, lhs._shape)
        if isinstance(lhs, CSRNDArray):
            return CSRNDArray(lhs._values * rhs, lhs._indices, lhs._indptr,
                              lhs._shape)
        return NDArray(lhs.data * rhs)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        if lhs._shape != rhs._shape:
            raise ValueError(f"shape mismatch {lhs._shape} vs {rhs._shape}")
        li = np.asarray(jax.device_get(lhs._indices))
        ri = np.asarray(jax.device_get(rhs._indices))
        common, lpos, rpos = np.intersect1d(li, ri, return_indices=True)
        return RowSparseNDArray(
            jnp.asarray(common, _INT),
            lhs._values[jnp.asarray(lpos)] * rhs._values[jnp.asarray(rpos)],
            lhs._shape)
    if isinstance(lhs, RowSparseNDArray):
        dense = rhs.data if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        if tuple(dense.shape) != lhs._shape:
            raise ValueError(
                f"shape mismatch {lhs._shape} vs {tuple(dense.shape)}")
        return RowSparseNDArray(lhs._indices,
                                lhs._values * dense[lhs._indices], lhs._shape)
    if isinstance(rhs, RowSparseNDArray):
        return multiply(rhs, lhs)
    l = lhs._dense() if isinstance(lhs, BaseSparseNDArray) else (
        lhs.data if isinstance(lhs, NDArray) else jnp.asarray(lhs))
    r = rhs._dense() if isinstance(rhs, BaseSparseNDArray) else (
        rhs.data if isinstance(rhs, NDArray) else jnp.asarray(rhs))
    return NDArray(l * r)


def _csr_binop(lhs, rhs, op):
    """csr (+|-) csr through scipy on host (keeps sparsity; reference uses its
    own CPU CSR kernels for the same combos)."""
    import scipy.sparse as sps
    out = op(lhs.asscipy(), rhs.asscipy()).tocsr()
    out.sort_indices()
    return CSRNDArray(jnp.asarray(out.data), jnp.asarray(out.indices, _INT),
                      jnp.asarray(out.indptr, _INT), lhs._shape)


_rsp_add_orig = add


def add(lhs, rhs):  # noqa: F811 — extend the existing dispatcher with csr+csr
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        if lhs._shape != rhs._shape:
            raise ValueError(f"shape mismatch {lhs._shape} vs {rhs._shape}")
        return _csr_binop(lhs, rhs, lambda a, b: a + b)
    return _rsp_add_orig(lhs, rhs)


elemwise_add = add
elemwise_sub = subtract
elemwise_mul = multiply


def _install_operators():
    RowSparseNDArray.__sub__ = lambda s, o: subtract(s, o)
    RowSparseNDArray.__mul__ = lambda s, o: multiply(s, o)
    RowSparseNDArray.__rmul__ = lambda s, o: multiply(s, o)
    RowSparseNDArray.__neg__ = lambda s: negate(s)
    CSRNDArray.__add__ = lambda s, o: add(s, o)
    CSRNDArray.__radd__ = lambda s, o: add(s, o)
    CSRNDArray.__sub__ = lambda s, o: subtract(s, o)
    CSRNDArray.__mul__ = lambda s, o: multiply(s, o)
    CSRNDArray.__rmul__ = lambda s, o: multiply(s, o)
    CSRNDArray.__neg__ = lambda s: negate(s)


_install_operators()
