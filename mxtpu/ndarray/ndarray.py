"""NDArray — the imperative tensor handle.

Capability parity with the reference NDArray (``include/mxnet/ndarray.h:82``,
``src/ndarray/``): eager ops with async semantics, device placement, in-place mutation,
views, autograd attachment, serialization. The re-design (SURVEY.md §7 hard-parts):

* The reference pairs every NDArray with an engine variable for dependency tracking;
  ops are closures pushed onto the ThreadedEngine. **JAX's dispatch already is that
  engine** — ops on ``jax.Array`` values are issued asynchronously and ordered by data
  dependence, so ``NDArray`` is a thin *mutable handle* over an immutable ``jax.Array``.
* Mutation (``+=``, ``x[i] = v``, ``out=`` kwargs, optimizer updates) is modeled by
  swapping the handle's underlying buffer (functionally updated via ``.at[]``); views
  (``Slice/Reshape``, ndarray.h views) write through to their base handle the same way.
  WAR/WAW hazards cannot occur because buffers are immutable — the handle swap is the
  only "write", and it happens on the issuing (Python) thread in program order.
* ``WaitToRead``/``WaitToWrite`` (ndarray.h:315-323) collapse to
  ``jax.block_until_ready``; ``asnumpy`` is the implicit sync point exactly like the
  reference.
"""

from __future__ import annotations

import struct
import threading
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype_np, dtype_name
from ..context import Context, cpu, current_context
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "empty", "concatenate", "waitall", "save", "load",
           "from_numpy", "from_dlpack", "to_dlpack"]


def _wrap_out(raw) -> "NDArray":
    return NDArray(raw)


# Read-capture hook: while a capture list is pushed, every NDArray whose buffer is
# read is appended. Control-flow ops (ops/control_flow.py) use this to discover
# handles their body closes over (e.g. RNN-cell weights) so gradients flow to them
# — the imperative analogue of the reference's subgraph input capture
# (control_flow.cc `_foreach` collecting the body CachedOp's inputs).
_capture_tls = threading.local()  # per-thread: other threads' reads must not leak in


def _captures() -> List[list]:
    stack = getattr(_capture_tls, "stack", None)
    if stack is None:
        stack = _capture_tls.stack = []
    return stack


def _push_capture(lst: list):
    _captures().append(lst)


def _pop_capture():
    _captures().pop()


# Donation-sanitizer read hook: None (a single global-load + is-None check on
# the hot path) unless MXTPU_SANITIZE=donation armed it, in which case
# mxtpu.analysis.sanitize installs its poison check here — a read of a buffer
# a donate_argnums step consumed raises a named DonationError instead of
# XLA's opaque "Array has been deleted" (or, on CPU, silently reading stale
# data because XLA skips donation there).
_sanitize_data_hook = None


class NDArray:
    """Mutable tensor handle over an immutable ``jax.Array``."""

    __slots__ = ("_data", "_grad", "_grad_entry", "_base", "_index", "_version",
                 "_base_version_seen", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None,
                 _base: Optional["NDArray"] = None, _index=None):
        if isinstance(data, NDArray):
            data = data.data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(np.asarray(data), dtype=dtype_np(dtype) if dtype else None)
        elif dtype is not None:
            data = data.astype(dtype_np(dtype))
        if ctx is not None:
            data = jax.device_put(data, ctx.jax_device)
        self._data = data
        self._grad: Optional["NDArray"] = None
        self._grad_entry = None  # autograd: VariableEntry | (TapeNode, out_index)
        self._base = _base       # view support: immediate parent handle
        self._index = _index     # view support: index into the parent
        self._version = 0
        self._base_version_seen = _base._version if _base is not None else 0

    # -- buffer access ----------------------------------------------------
    @property
    def data(self):
        """Current buffer; views re-slice lazily if the base was mutated since."""
        self._sync()
        if _sanitize_data_hook is not None:
            _sanitize_data_hook(self._data)
        stack = getattr(_capture_tls, "stack", None)
        if stack:  # control-flow subgraph input discovery (see ops/control_flow.py)
            stack[-1].append(self)
        return self._data

    def _sync(self):
        if self._base is not None:
            self._base._sync()
            if self._base_version_seen != self._base._version:
                self._data = self._base._data[self._index]
                self._base_version_seen = self._base._version

    def _set_data(self, new_data):
        """The single mutation point (handle swap). Views write through to the
        parent chain, which composes chained-view indices correctly."""
        if not isinstance(new_data, jax.Array):
            new_data = jnp.asarray(new_data)
        if self._base is not None:
            self._base._sync()
            self._base._set_data(self._base._data.at[self._index].set(
                jnp.asarray(new_data, dtype=self._base._data.dtype)))
            self._data = self._base._data[self._index]
            self._base_version_seen = self._base._version
        else:
            self._data = new_data
        self._version += 1

    # -- metadata ---------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(self._data.size)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        try:
            dev = self._data.devices().pop()
            plat = dev.platform
        except Exception:
            return cpu(0)
        kind = {"cpu": "cpu", "gpu": "gpu", "tpu": "tpu"}.get(plat, "tpu")
        return Context(kind, dev.id)

    ctx = context

    @property
    def stype(self) -> str:
        return "default"  # dense; row_sparse/csr live in ndarray.sparse

    def tostype(self, stype: str):
        """Convert storage type (mx.nd.NDArray.tostype parity)."""
        from . import sparse as _sparse
        return _sparse.cast_storage(self, stype)

    # -- sync -------------------------------------------------------------
    def wait_to_read(self):
        jax.block_until_ready(self.data)
        return self

    wait_to_write = wait_to_read

    def asnumpy(self) -> np.ndarray:
        out = np.asarray(jax.device_get(self.data))
        return out

    def asscalar(self):
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype else a

    def __dlpack__(self, **kwargs):
        return self.data.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self.data.__dlpack_device__()

    # -- conversions / movement ------------------------------------------
    def astype(self, dtype, copy: bool = True) -> "NDArray":
        return NDArray(self.data.astype(dtype_np(dtype)))

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        """Parity with NDArray::CopyFromTo (src/ndarray/ndarray.cc:1096)."""
        if isinstance(other, Context):
            return NDArray(jax.device_put(self.data, other.jax_device))
        other._set_data(jnp.asarray(self._data, dtype=other.dtype).reshape(other.shape))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        return NDArray(jax.device_put(self.data, ctx.jax_device))

    as_in_ctx = as_in_context

    def copy(self) -> "NDArray":
        # Deep-copy parity (NDArray::Copy). A materialized buffer (not an alias) is
        # required: optimizers donate weight buffers to their fused update kernels
        # (optimizer.py donate_argnums), which invalidates any aliasing handle.
        return NDArray(jnp.array(self.data, copy=True))

    def detach(self) -> "NDArray":
        # Also materialized — a detached handle must survive donation of the source
        # buffer by a later in-place optimizer step.
        return NDArray(jnp.array(self.data, copy=True))

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        from .. import autograd
        autograd._mark_variable(self, grad_req)

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    def backward(self, out_grad=None, retain_graph: bool = False,
                 train_mode: bool = True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- shape ops (view-producing in the reference; functional here) ------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _reg.invoke(_reg.get_op("reshape"), self, shape=shape,
                           reverse=kwargs.get("reverse", False))

    def reshape_like(self, other) -> "NDArray":
        return _reg.invoke(_reg.get_op("reshape_like"), self, other)

    def flatten(self) -> "NDArray":
        return _reg.invoke(_reg.get_op("flatten"), self)

    def expand_dims(self, axis) -> "NDArray":
        return _reg.invoke(_reg.get_op("expand_dims"), self, axis=axis)

    def squeeze(self, axis=None) -> "NDArray":
        return _reg.invoke(_reg.get_op("squeeze"), self, axis=axis)

    def transpose(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _reg.invoke(_reg.get_op("transpose"), self, axes=axes or None)

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    def swapaxes(self, dim1, dim2) -> "NDArray":
        return _reg.invoke(_reg.get_op("swapaxes"), self, dim1=dim1, dim2=dim2)

    def broadcast_to(self, shape) -> "NDArray":
        return _reg.invoke(_reg.get_op("broadcast_to"), self, shape=shape)

    def broadcast_like(self, other) -> "NDArray":
        return _reg.invoke(_reg.get_op("broadcast_like"), self, other)

    def tile(self, reps) -> "NDArray":
        return _reg.invoke(_reg.get_op("tile"), self, reps=reps)

    def repeat(self, repeats, axis=None) -> "NDArray":
        return _reg.invoke(_reg.get_op("repeat"), self, repeats=repeats, axis=axis)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _reg.invoke(_reg.get_op("split"), self, num_outputs=num_outputs,
                           axis=axis, squeeze_axis=squeeze_axis)

    def slice(self, begin, end, step=()):
        return _reg.invoke(_reg.get_op("slice"), self, begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end):
        return _reg.invoke(_reg.get_op("slice_axis"), self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return _reg.invoke(_reg.get_op("take"), self, indices, axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False):
        return _reg.invoke(_reg.get_op("pick"), self, index, axis=axis, keepdims=keepdims)

    def one_hot(self, depth, **kw):
        return _reg.invoke(_reg.get_op("one_hot"), self, depth=depth, **kw)

    def clip(self, a_min, a_max):
        return _reg.invoke(_reg.get_op("clip"), self, a_min=a_min, a_max=a_max)

    def abs(self):
        return _reg.invoke(_reg.get_op("abs"), self)

    def sign(self):
        return _reg.invoke(_reg.get_op("sign"), self)

    def sqrt(self):
        return _reg.invoke(_reg.get_op("sqrt"), self)

    def square(self):
        return _reg.invoke(_reg.get_op("square"), self)

    def exp(self):
        return _reg.invoke(_reg.get_op("exp"), self)

    def log(self):
        return _reg.invoke(_reg.get_op("log"), self)

    def relu(self):
        return _reg.invoke(_reg.get_op("relu"), self)

    def sigmoid(self):
        return _reg.invoke(_reg.get_op("sigmoid"), self)

    def tanh(self):
        return _reg.invoke(_reg.get_op("tanh"), self)

    def softmax(self, axis=-1):
        return _reg.invoke(_reg.get_op("softmax"), self, axis=axis)

    def log_softmax(self, axis=-1):
        return _reg.invoke(_reg.get_op("log_softmax"), self, axis=axis)

    def astype_like(self, other):
        return self.astype(other.dtype)

    # -- reductions --------------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return _reg.invoke(_reg.get_op("sum"), self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return _reg.invoke(_reg.get_op("mean"), self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return _reg.invoke(_reg.get_op("prod"), self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return _reg.invoke(_reg.get_op("max"), self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return _reg.invoke(_reg.get_op("min"), self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None):
        return _reg.invoke(_reg.get_op("argmax"), self, axis=axis)

    def argmin(self, axis=None):
        return _reg.invoke(_reg.get_op("argmin"), self, axis=axis)

    def norm(self, ord=2, axis=None, keepdims=False):
        return _reg.invoke(_reg.get_op("norm"), self, ord=ord, axis=axis, keepdims=keepdims)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _reg.invoke(_reg.get_op("dot"), self, other,
                           transpose_a=transpose_a, transpose_b=transpose_b)

    # -- python protocol ---------------------------------------------------
    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of 0-d NDArray")
        return self.shape[0]

    def __bool__(self) -> bool:
        if self.size != 1:
            raise ValueError("truth value of multi-element NDArray is ambiguous")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self) -> str:
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- indexing ----------------------------------------------------------
    def _norm_index(self, key):
        if isinstance(key, NDArray):
            return key.data.astype(jnp.int32)
        if isinstance(key, tuple):
            return tuple(self._norm_index(k) for k in key)
        return key

    def __getitem__(self, key) -> "NDArray":
        idx = self._norm_index(key)
        if _is_basic_index(idx):
            # basic slicing returns a *view* (reference Slice semantics, ndarray.h
            # Slice/At): chained views parent-chain, so writes compose through
            # _set_data recursion and reads re-sync via _sync().
            return NDArray(self.data[idx], _base=self, _index=idx)
        return NDArray(self.data[idx])

    def __setitem__(self, key, value):
        idx = self._norm_index(key)
        if isinstance(value, NDArray):
            value = value.data
        self._sync()
        self._set_data(self._data.at[idx].set(
            jnp.asarray(value, dtype=self._data.dtype)
            if not isinstance(value, jax.Array) else value.astype(self._data.dtype)))

    # -- arithmetic --------------------------------------------------------
    def _binop(self, name, other, reverse=False):
        op = _reg.get_op(name)
        if reverse:
            return _reg.invoke(op, other, self)
        return _reg.invoke(op, self, other)

    def __add__(self, o):
        return self._binop("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("subtract", o)

    def __rsub__(self, o):
        return self._binop("subtract", o, reverse=True)

    def __mul__(self, o):
        return self._binop("multiply", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("divide", o)

    def __rtruediv__(self, o):
        return self._binop("divide", o, reverse=True)

    def __mod__(self, o):
        return self._binop("mod", o)

    def __rmod__(self, o):
        return self._binop("mod", o, reverse=True)

    def __pow__(self, o):
        return self._binop("power", o)

    def __rpow__(self, o):
        return self._binop("power", o, reverse=True)

    def __neg__(self):
        return _reg.invoke(_reg.get_op("negative"), self)

    def __abs__(self):
        return _reg.invoke(_reg.get_op("abs"), self)

    def __eq__(self, o):
        return self._binop("equal", o)

    def __ne__(self, o):
        return self._binop("not_equal", o)

    def __gt__(self, o):
        return self._binop("greater", o)

    def __ge__(self, o):
        return self._binop("greater_equal", o)

    def __lt__(self, o):
        return self._binop("lesser", o)

    def __le__(self, o):
        return self._binop("lesser_equal", o)

    def __hash__(self):
        return id(self)

    # in-place: swap the handle's buffer (the reference mutates the chunk through
    # engine write-deps; here program order on the issuing thread gives the same
    # serialization for free).
    def _iop(self, name, other):
        res = self._binop(name, other)
        self._set_data(res._data.astype(self._data.dtype))
        return self

    def __iadd__(self, o):
        return self._iop("add", o)

    def __isub__(self, o):
        return self._iop("subtract", o)

    def __imul__(self, o):
        return self._iop("multiply", o)

    def __itruediv__(self, o):
        return self._iop("divide", o)


def _is_basic_index(idx) -> bool:
    basic = (int, slice, type(None), type(Ellipsis))
    if isinstance(idx, basic):
        return True
    if isinstance(idx, tuple):
        return all(isinstance(i, basic) for i in idx)
    return False


# ---------------------------------------------------------------------------
# creation / io helpers
# ---------------------------------------------------------------------------


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        return NDArray(source.data, ctx=ctx, dtype=dtype)
    keep_dtype = isinstance(source, (np.ndarray, jax.Array)) or np.isscalar(source)
    arr = np.asarray(source, dtype=dtype_np(dtype) if dtype else None)
    if dtype is None and (arr.dtype == np.float64 or not keep_dtype):
        # reference semantics (python/mxnet/ndarray/utils.py array): python lists
        # default to float32; numpy arrays keep their dtype (float64 narrowed).
        arr = arr.astype(np.float32)
    return NDArray(arr, ctx=ctx)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return NDArray(jnp.zeros(shape if not isinstance(shape, int) else (shape,),
                             dtype_np(dtype)), ctx=ctx)


def from_numpy(a: np.ndarray, zero_copy: bool = False) -> NDArray:
    return NDArray(jnp.asarray(a))


def from_dlpack(ext) -> NDArray:
    """Accepts any object implementing the dlpack protocol (dlpack parity, §2.7)."""
    return NDArray(jnp.from_dlpack(ext))


def to_dlpack(arr: NDArray):
    """Return the dlpack-capable device array (consumers call __dlpack__ on it)."""
    return arr.data


def concatenate(arrays: Sequence[NDArray], axis: int = 0) -> NDArray:
    return _reg.invoke(_reg.get_op("concat"), *arrays, dim=axis)


def waitall():
    """Parity with mx.nd.waitall — drain all outstanding async work."""
    jax.effects_barrier()


# ---------------------------------------------------------------------------
# serialization — reference NDArray::Save/Load capability (ndarray.cc:1537,1650)
# with a format native to this framework (npz container, names preserved).
# ---------------------------------------------------------------------------


_SAVE_FORMAT_KEY = "__mxtpu_format__"  # reserved npz entry: b"list" | b"dict"


def _encode_entry(payload, key, v):
    """One array into the npz payload; sparse storage serializes by component
    (NDArray::Save handles row_sparse/csr the same way, ndarray.cc:1537)."""
    stype = getattr(v, "stype", "default")
    if stype == "row_sparse":
        payload[f"{key}::rsp::indices"] = np.asarray(v.indices.asnumpy())
        payload[f"{key}::rsp::values"] = np.asarray(v.data.asnumpy())
        payload[f"{key}::rsp::shape"] = np.asarray(v.shape, np.int64)
    elif stype == "csr":
        payload[f"{key}::csr::data"] = np.asarray(v.data.asnumpy())
        payload[f"{key}::csr::indices"] = np.asarray(v.indices.asnumpy())
        payload[f"{key}::csr::indptr"] = np.asarray(v.indptr.asnumpy())
        payload[f"{key}::csr::shape"] = np.asarray(v.shape, np.int64)
    else:
        payload[key] = v.asnumpy()


def save(fname: str, data, fmt: str = "npz"):
    """Save an NDArray (dense or sparse), list, or dict of name→NDArray
    (mx.nd.save parity incl. row_sparse/csr, ndarray.cc:1537).

    ``fmt='npz'`` (default) writes the native npz container with an explicit
    format marker, so a dict whose keys happen to look like ``arr_<i>``
    round-trips correctly (list-vs-dict is never inferred from key names).
    ``fmt='reference'`` emits the reference's NDARRAY_V2 binary format
    (legacy_io.py; ndarray.cc:1532-1653) so the artifact loads in the
    reference framework and its other language bindings.
    """
    # all writes are tempfile + fsync + os.replace (checkpoint.atomic_io):
    # a mid-write SIGKILL leaves the previous file intact, never a torn one
    from ..checkpoint import atomic_io
    if fmt == "reference":
        from . import legacy_io
        atomic_io.atomic_write_bytes(fname, legacy_io.save_bytes(data))
        return
    if fmt != "npz":
        raise ValueError(f"unknown save format {fmt!r}: use 'npz' or 'reference'")
    payload = {}
    if isinstance(data, dict):
        if _SAVE_FORMAT_KEY in data:
            raise ValueError(f"key {_SAVE_FORMAT_KEY!r} is reserved")
        for k in data:
            parts = k.rsplit("::", 2)
            if len(parts) == 3 and parts[1] in ("rsp", "csr"):
                raise ValueError(
                    f"key {k!r} matches the reserved '<name>::rsp/csr::<comp>' "
                    "sparse-component pattern")
        for k, v in data.items():
            _encode_entry(payload, k, v)
        fmt = "dict"
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            _encode_entry(payload, f"arr_{i}", v)
        fmt = "list"
    elif hasattr(data, "asnumpy"):
        _encode_entry(payload, "arr_0", data)
        fmt = "list"
    else:
        raise TypeError(f"cannot save {type(data)}")
    payload[_SAVE_FORMAT_KEY] = np.frombuffer(fmt.encode(), dtype=np.uint8)
    atomic_io.atomic_write(fname, lambda f: np.savez(f, **payload))


def _decode_entries(z, keys):
    """Reassemble logical entries (dense or sparse-by-component) from npz."""
    from . import sparse as _sparse
    out = {}
    logical = {}
    for k in keys:
        parts = k.rsplit("::", 2)  # user keys may themselves contain '::'
        if len(parts) == 3 and parts[1] in ("rsp", "csr"):
            name, stype, comp = parts
            logical.setdefault((name, stype), {})[comp] = z[k]
        else:
            out[k] = NDArray(z[k])
    for (name, stype), comps in logical.items():
        if stype == "rsp":
            out[name] = _sparse.RowSparseNDArray(
                comps["indices"], comps["values"], tuple(comps["shape"]))
        else:
            out[name] = _sparse.CSRNDArray(
                comps["data"], comps["indices"], comps["indptr"],
                tuple(comps["shape"]))
    return out


def load(fname: str):
    """Load from ``save``; returns dict if named, else list (mx.nd.load parity).
    Sparse entries come back as RowSparseNDArray/CSRNDArray.

    The format is sniffed: files starting with the reference's dmlc list magic
    (0x112) parse as reference NDARRAY_V1/V2 binaries (legacy_io.py) — a
    trained reference ``.params`` artifact loads directly."""
    with open(fname, "rb") as f:
        head = f.read(8)
    from . import legacy_io
    if legacy_io.is_reference_file(head):
        with open(fname, "rb") as f:
            return legacy_io.load_bytes(f.read())
    with open(fname, "rb") as f:
        with np.load(f, allow_pickle=False) as z:
            keys = [k for k in z.keys() if k != _SAVE_FORMAT_KEY]
            if _SAVE_FORMAT_KEY in z.keys():
                fmt = bytes(z[_SAVE_FORMAT_KEY]).decode()
            else:  # pre-marker files: fall back to the key-name heuristic
                fmt = "list" if all(k.startswith("arr_") for k in keys) else "dict"
            entries = _decode_entries(z, keys)
            if fmt == "list":
                return [entries[f"arr_{i}"] for i in range(len(entries))]
            return entries
