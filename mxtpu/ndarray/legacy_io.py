"""Reference binary ``.params`` interop — NDARRAY_V1/V2 reader + V2 writer.

The reference's NDArray file is a defined binary contract
(src/ndarray/ndarray.cc:1532-1653, 1733-1762): a dmlc stream holding

    uint64 kMXAPINDArrayListMagic (0x112) | uint64 reserved
    vector<NDArray>   (uint64 count, then each array)
    vector<string>    (uint64 count, then uint64 len + bytes per name)

and each array (NDArray::Save, ndarray.cc:1537):

    uint32 NDARRAY_V2_MAGIC (0xF993fac9)
    int32  storage type (0 dense / 1 row_sparse / 2 csr)
    [sparse] storage shape        (TShape: uint32 ndim + int64 × ndim)
    TShape shape
    int32 dev_type | int32 dev_id (Context::Save, include/mxnet/base.h:188)
    int32  type flag              (mshadow: 0 f32, 1 f64, 2 f16, 3 u8,
                                   4 i32, 5 i8, 6 i64)
    [sparse] per aux: int32 aux type flag + TShape aux shape
    raw data bytes (C-order, storage shape for sparse)
    [sparse] raw aux bytes

Legacy arrays (NDArray::LegacyLoad, ndarray.cc:1605): magic is either
NDARRAY_V1_MAGIC (int64 TShape follows) or the raw ndim of a uint32 TShape —
no storage type, dense only.

This module is an independent implementation of that layout (struct/numpy) so
a trained reference artifact loads directly and models train/predict on from
it; ``ndarray.save(..., fmt='reference')`` emits V2 for the reverse trip. The
npz container (ndarray.py) stays the native format.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
LIST_MAGIC = 0x112

_TYPE_FLAG_TO_DTYPE = {
    0: np.dtype(np.float32), 1: np.dtype(np.float64), 2: np.dtype(np.float16),
    3: np.dtype(np.uint8), 4: np.dtype(np.int32), 5: np.dtype(np.int8),
    6: np.dtype(np.int64),
}
_DTYPE_TO_TYPE_FLAG = {v: k for k, v in _TYPE_FLAG_TO_DTYPE.items()}

_STYPE_DENSE, _STYPE_ROW_SPARSE, _STYPE_CSR = 0, 1, 2
_KCPU = 1          # Context dev_type enum (include/mxnet/base.h kCPU)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf, self.pos = buf, 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated reference NDArray file")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.read(4))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.read(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.read(8))[0]

    def shape64(self) -> Tuple[int, ...]:
        ndim = self.u32()
        return struct.unpack(f"<{ndim}q", self.read(8 * ndim))


def _read_array(r: _Reader):
    """One NDArray (V2, V1, or uint32-TShape legacy). Returns a framework
    array (NDArray / RowSparseNDArray / CSRNDArray)."""
    from .ndarray import NDArray
    from . import sparse as _sparse

    magic = r.u32()
    if magic == NDARRAY_V2_MAGIC:
        stype = r.i32()
        nad = {_STYPE_DENSE: 0, _STYPE_ROW_SPARSE: 1, _STYPE_CSR: 2}.get(stype)
        if nad is None:
            raise ValueError(f"unknown storage type {stype}")
        sshape = r.shape64() if nad else None
        shape = r.shape64()
        if len(shape) == 0:
            return NDArray(np.zeros((), np.float32))   # none array placeholder
        r.i32(); r.i32()                               # context: restored host-side
        dtype = _TYPE_FLAG_TO_DTYPE[r.i32()]
        aux = []
        for _ in range(nad):
            aux_dtype = _TYPE_FLAG_TO_DTYPE[r.i32()]
            aux.append((aux_dtype, r.shape64()))
        data_shape = sshape if nad else shape
        n = int(np.prod(data_shape)) if data_shape else 1
        data = np.frombuffer(r.read(n * dtype.itemsize), dtype).reshape(data_shape)
        aux_arrays = []
        for aux_dtype, ashape in aux:
            an = int(np.prod(ashape)) if ashape else 1
            aux_arrays.append(np.frombuffer(
                r.read(an * aux_dtype.itemsize), aux_dtype).reshape(ashape))
        if stype == _STYPE_ROW_SPARSE:
            return _sparse.RowSparseNDArray(aux_arrays[0], data, shape)
        if stype == _STYPE_CSR:
            indptr, indices = aux_arrays
            return _sparse.CSRNDArray(data, indices, indptr, shape)
        return NDArray(data.copy())

    # legacy: V1 (int64 TShape) or ancient (magic IS ndim, uint32 dims)
    if magic == NDARRAY_V1_MAGIC:
        shape = r.shape64()
    else:
        ndim = magic
        if ndim > 32:
            raise ValueError(f"bad NDArray magic 0x{magic:x}")
        shape = struct.unpack(f"<{ndim}I", r.read(4 * ndim))
    if len(shape) == 0:
        return NDArray(np.zeros((), np.float32))
    r.i32(); r.i32()                                   # context
    dtype = _TYPE_FLAG_TO_DTYPE[r.i32()]
    n = int(np.prod(shape))
    data = np.frombuffer(r.read(n * dtype.itemsize), dtype).reshape(shape)
    return NDArray(data.copy())


def _to_numpy(v) -> np.ndarray:
    arr = np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
    if arr.dtype.name == "bfloat16" or arr.dtype not in _DTYPE_TO_TYPE_FLAG:
        # the reference's mshadow type table has no bfloat16: widen to f32
        arr = arr.astype(np.float32)
    return np.ascontiguousarray(arr)


def _write_shape(out: List[bytes], shape: Sequence[int]):
    out.append(struct.pack("<I", len(shape)))
    out.append(struct.pack(f"<{len(shape)}q", *shape))


def _write_array(out: List[bytes], v):
    stype = getattr(v, "stype", "default")
    out.append(struct.pack("<I", NDARRAY_V2_MAGIC))
    if stype == "row_sparse":
        vals = _to_numpy(v.data)
        idx = np.ascontiguousarray(np.asarray(v.indices.asnumpy()), np.int64)
        out.append(struct.pack("<i", _STYPE_ROW_SPARSE))
        _write_shape(out, vals.shape)                   # storage shape
        _write_shape(out, v.shape)
        out.append(struct.pack("<ii", _KCPU, 0))
        out.append(struct.pack("<i", _DTYPE_TO_TYPE_FLAG[vals.dtype]))
        out.append(struct.pack("<i", 6))                # aux: int64 row ids
        _write_shape(out, idx.shape)
        out.append(vals.tobytes())
        out.append(idx.tobytes())
        return
    if stype == "csr":
        vals = _to_numpy(v.data)
        indptr = np.ascontiguousarray(np.asarray(v.indptr.asnumpy()), np.int64)
        indices = np.ascontiguousarray(np.asarray(v.indices.asnumpy()), np.int64)
        out.append(struct.pack("<i", _STYPE_CSR))
        _write_shape(out, vals.shape)
        _write_shape(out, v.shape)
        out.append(struct.pack("<ii", _KCPU, 0))
        out.append(struct.pack("<i", _DTYPE_TO_TYPE_FLAG[vals.dtype]))
        out.append(struct.pack("<i", 6))                # indptr
        _write_shape(out, indptr.shape)
        out.append(struct.pack("<i", 6))                # indices
        _write_shape(out, indices.shape)
        out.append(vals.tobytes())
        out.append(indptr.tobytes())
        out.append(indices.tobytes())
        return
    arr = _to_numpy(v)
    out.append(struct.pack("<i", _STYPE_DENSE))
    _write_shape(out, arr.shape)
    out.append(struct.pack("<ii", _KCPU, 0))
    out.append(struct.pack("<i", _DTYPE_TO_TYPE_FLAG[arr.dtype]))
    out.append(arr.tobytes())


def is_reference_file(head: bytes) -> bool:
    """Sniff the dmlc list magic (first 8 bytes, little-endian 0x112)."""
    return len(head) >= 8 and struct.unpack("<Q", head[:8])[0] == LIST_MAGIC


def save_bytes(data) -> bytes:
    """Serialize like the reference's MXNDArraySave (ndarray.cc:1735):
    dict → arrays + names, list/single → arrays with no names."""
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    elif isinstance(data, (list, tuple)):
        names, arrays = [], list(data)
    else:
        names, arrays = [], [data]
    out: List[bytes] = [struct.pack("<QQ", LIST_MAGIC, 0),
                        struct.pack("<Q", len(arrays))]
    for v in arrays:
        _write_array(out, v)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    return b"".join(out)


def load_bytes(buf: bytes):
    """Parse a reference NDArray file: dict when names are present, else list
    (NDArray::Load, ndarray.cc:1745)."""
    r = _Reader(buf)
    if r.u64() != LIST_MAGIC:
        raise ValueError("not a reference NDArray file (bad list magic)")
    r.u64()                                            # reserved
    arrays = [_read_array(r) for _ in range(r.u64())]
    n_names = r.u64()
    names = [r.read(r.u64()).decode() for _ in range(n_names)]
    if names and len(names) != len(arrays):
        raise ValueError("name/array count mismatch in reference file")
    if names:
        return dict(zip(names, arrays))
    return arrays
