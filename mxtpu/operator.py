"""Custom-operator escape hatch — capability parity with
``python/mxnet/operator.py:426-692`` (``CustomOp``/``CustomOpProp``/
``mx.operator.register``) and ``src/operator/custom/custom-inl.h:50-170``.

The reference executes frontend-defined ops through an ``MXCallbackList``
dispatched on a dedicated thread pool inside the engine. The TPU-native
equivalent: the user's Python ``forward``/``backward`` run on the **host** via
``jax.pure_callback`` while the surrounding graph stays compiled — so a Custom
op works inside ``hybridize()``d blocks, under ``Module.fit``, and under
``jax.jit`` generally. Gradients route through ``jax.custom_vjp`` whose
backward is itself a host callback into ``CustomOp.backward``.

Shape/type inference comes from ``CustomOpProp.infer_shape``/``infer_type``
exactly as in the reference (needed here to declare the callback's result
avals before tracing proceeds).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]


class CustomOp:
    """Base class for custom imperative operators (operator.py:426 parity).

    Subclasses implement ``forward(is_train, req, in_data, out_data, aux)`` and
    ``backward(req, out_grad, in_data, out_data, in_grad, aux)``, writing
    results with ``self.assign``. Tensors are host NDArrays (numpy-backed
    views) — arbitrary Python/numpy/scipy code is allowed here."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """operator.py:449 assign parity: honor the write/add/null req."""
        if req in ("null", 0):
            return
        src = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
        if req in ("add", "add_to", 3):
            dst[:] = dst.asnumpy() + src
        else:
            dst[:] = src


class CustomOpProp:
    """Op metadata provider (operator.py:526 CustomOpProp parity)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad
        self.kwargs: Dict[str, str] = {}

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        t = in_type[0]
        return ([t] * len(in_type),
                [t] * len(self.list_outputs()),
                [t] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        return out_grad + in_data + out_data

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[CustomOpProp]] = {}


def register(reg_name: str):
    """``mx.operator.register`` parity: class decorator for CustomOpProp."""

    def _wrap(prop_cls: Type[CustomOpProp]):
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _wrap


def get_prop(op_type: str) -> Type[CustomOpProp]:
    if op_type not in _REGISTRY:
        raise KeyError(f"custom op {op_type!r} not registered "
                       f"(available: {sorted(_REGISTRY)})")
    return _REGISTRY[op_type]


class _HostND:
    """Minimal host NDArray handed to CustomOp code inside callbacks: supports
    .asnumpy(), .shape/.dtype, slicing assignment — enough for the reference's
    documented CustomOp idioms."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = np.asarray(arr)

    def asnumpy(self):
        return self.arr

    @property
    def shape(self):
        return self.arr.shape

    @property
    def dtype(self):
        return self.arr.dtype

    def __getitem__(self, k):
        return self.arr[k]

    def __setitem__(self, k, v):
        self.arr[k] = np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)

    def __array__(self, dtype=None):
        return self.arr if dtype is None else self.arr.astype(dtype)


def _build_custom_fn(op_type: str, num_inputs: int, kwargs: Dict[str, str],
                     is_train: bool):
    """Build the jittable (custom_vjp-wrapped, pure_callback-backed) function
    for one Custom invocation signature."""
    prop_cls = get_prop(op_type)
    prop = prop_cls(**kwargs)
    prop.kwargs = kwargs

    n_out = len(prop.list_outputs())

    def _shapes(raw_shapes, raw_dtypes):
        in_shapes, out_shapes, _aux = prop.infer_shape(
            [list(s) for s in raw_shapes])
        _in_t, out_types, _aux_t = prop.infer_type(list(raw_dtypes))
        return [tuple(s) for s in out_shapes], out_types

    def _make_op(raw):
        return prop.create_operator(None, [list(x.shape) for x in raw],
                                    [x.dtype for x in raw])

    def _fwd_host(*raw):
        op = _make_op(raw)
        in_data = [_HostND(x) for x in raw]
        out_shapes, out_types = _shapes([x.shape for x in raw],
                                        [x.dtype for x in raw])
        out_data = [_HostND(np.zeros(s, t)) for s, t in zip(out_shapes, out_types)]
        op.forward(is_train, ["write"] * n_out, in_data, out_data, [])
        outs = tuple(o.arr for o in out_data)
        return outs if n_out > 1 else outs[0]

    def _bwd_host(*args):
        raw_in = args[:num_inputs]
        raw_out = args[num_inputs:num_inputs + n_out]
        raw_og = args[num_inputs + n_out:]
        op = _make_op(raw_in)
        in_data = [_HostND(x) for x in raw_in]
        out_data = [_HostND(x) for x in raw_out]
        out_grad = [_HostND(x) for x in raw_og]
        in_grad = [_HostND(np.zeros_like(x.arr)) for x in in_data]
        op.backward(["write"] * num_inputs, out_grad, in_data, out_data,
                    in_grad, [])
        grads = tuple(g.arr for g in in_grad)
        return grads if num_inputs > 1 else grads[0]

    @jax.custom_vjp
    def custom_fn(*raw):
        out_shapes, out_types = _shapes([x.shape for x in raw],
                                        [x.dtype for x in raw])
        result_avals = tuple(jax.ShapeDtypeStruct(s, t)
                             for s, t in zip(out_shapes, out_types))
        if n_out == 1:
            result_avals = result_avals[0]
        return jax.pure_callback(_fwd_host, result_avals, *raw)

    def custom_fwd(*raw):
        outs = custom_fn(*raw)
        return outs, (raw, outs if n_out > 1 else (outs,))

    def custom_bwd(res, g):
        raw, outs = res
        gs = g if n_out > 1 else (g,)
        grad_avals = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in raw)
        if num_inputs == 1:
            grad_avals = grad_avals[0]
        grads = jax.pure_callback(_bwd_host, grad_avals, *raw, *outs, *gs)
        return grads if num_inputs > 1 else (grads,)

    custom_fn.defvjp(custom_fwd, custom_bwd)
    return custom_fn


def _custom_impl(*raw, op_type: str, is_train: bool, **kwargs):
    """The ``Custom`` op body (src/operator/custom/custom.cc parity): builds
    (per signature) the callback-backed function and applies it."""
    fn = _build_custom_fn(op_type, len(raw),
                          {k: str(v) for k, v in kwargs.items()}, is_train)
    return fn(*raw)


def _register_custom_op():
    from .ops.registry import register as op_register

    def _resolve(kwargs):
        # bake the ambient train mode into the recorded kwargs so a tape
        # replay under jax.vjp reproduces the same host callback
        if "_is_train" not in kwargs:
            from . import autograd
            kwargs["_is_train"] = bool(autograd.is_training())
        return kwargs

    @op_register("Custom", num_outputs=-1, aliases=("custom",),
                 resolve_kwargs=_resolve)
    def _custom(*raw, op_type: str = "", _is_train: bool = False, **kwargs):
        return _custom_impl(*raw, op_type=op_type, is_train=_is_train, **kwargs)


_register_custom_op()
