"""Checkpoint helpers — parity with ``python/mxnet/model.py`` save_checkpoint/
load_checkpoint (:384-414). The symbol-JSON slot stores a block-class descriptor
(the graph itself is re-traced from code; StableHLO export covers the portable-graph
capability, jit.export_stablehlo)."""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

from . import ndarray as nd
from .ndarray.ndarray import NDArray


def save_checkpoint(prefix: str, epoch: int, symbol=None, arg_params: Dict = None,
                    aux_params: Dict = None, remove_amp_cast: bool = True):
    """``prefix-symbol.json`` + ``prefix-####.params`` layout parity (model.py:384).

    A real Symbol serializes its graph (Symbol.tojson) and round-trips through
    ``load_checkpoint`` → ``Module(symbol)``; non-symbol blocks store a descriptor
    (their graph is re-traced from code; jit.export_stablehlo is the portable form).
    """
    if symbol is not None:
        with open(f"{prefix}-symbol.json", "w") as f:
            if hasattr(symbol, "tojson"):
                f.write(symbol.tojson())
            else:
                json.dump({"framework": "mxtpu", "block": type(symbol).__name__,
                           "repr": repr(symbol)}, f)
    payload = {}
    for k, v in (arg_params or {}).items():
        payload[f"arg:{k}"] = v
    for k, v in (aux_params or {}).items():
        payload[f"aux:{k}"] = v
    nd.save(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix: str, epoch: int):
    """Returns (symbol_descriptor|None, arg_params, aux_params) (model.py:414)."""
    symbol = None
    sym_file = f"{prefix}-symbol.json"
    if os.path.exists(sym_file):
        with open(sym_file) as f:
            raw = f.read()
        try:
            from .symbol import load_json
            symbol = load_json(raw)
        except Exception:
            symbol = json.loads(raw)  # legacy block descriptor
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params
