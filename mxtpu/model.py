"""Checkpoint helpers — parity with ``python/mxnet/model.py`` save_checkpoint/
load_checkpoint (:384-414). The symbol-JSON slot stores a block-class descriptor
(the graph itself is re-traced from code; StableHLO export covers the portable-graph
capability, jit.export_stablehlo)."""

from __future__ import annotations

import json
import os
import warnings
from typing import Dict, Optional, Tuple

from . import ndarray as nd
from .ndarray.ndarray import NDArray


def save_checkpoint(prefix: str, epoch: int, symbol=None, arg_params: Dict = None,
                    aux_params: Dict = None, remove_amp_cast: bool = True):
    """``prefix-symbol.json`` + ``prefix-####.params`` layout parity (model.py:384).

    A real Symbol serializes its graph (Symbol.tojson) and round-trips through
    ``load_checkpoint`` → ``Module(symbol)``; non-symbol blocks store a descriptor
    (their graph is re-traced from code; jit.export_stablehlo is the portable form).

    Delegates to ``checkpoint.save_legacy`` — the one (atomic, fsynced) writer
    for this layout; ``remove_amp_cast`` strips amp_cast/amp_multicast nodes
    from the symbol graph before serialization, as the reference does.
    """
    from .checkpoint import save_legacy
    save_legacy(prefix, epoch, symbol=symbol, arg_params=arg_params,
                aux_params=aux_params, remove_amp_cast=remove_amp_cast)


def load_checkpoint(prefix: str, epoch: int):
    """Returns (symbol_descriptor|None, arg_params, aux_params) (model.py:414)."""
    symbol = None
    sym_file = f"{prefix}-symbol.json"
    if os.path.exists(sym_file):
        with open(sym_file) as f:
            raw = f.read()
        try:
            from .symbol import load_json
            symbol = load_json(raw)
        except Exception:
            symbol = json.loads(raw)  # legacy block descriptor
    loaded = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    unknown = []
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            unknown.append(k)
            arg_params[k] = v
    if unknown:
        warnings.warn(
            f"load_checkpoint({prefix!r}, {epoch}): {len(unknown)} key(s) "
            f"without an 'arg:'/'aux:' prefix (e.g. {unknown[0]!r}) were "
            "classified as arg_params", stacklevel=2)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy estimator over a Symbol (reference python/mxnet/model.py:452).

    Deprecated there in favor of Module; kept for API parity. This
    implementation delegates the training loop to ``mxtpu.module.Module`` —
    the capability owner — while preserving the FeedForward surface:
    numpy/NDArray ``X, y`` inputs auto-wrap in an ``NDArrayIter``
    (model.py:629 ``_init_iter``), ``**kwargs`` flow to the optimizer, and
    ``save``/``load``/``create`` use the prefix-epoch checkpoint layout.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        import warnings
        warnings.warn("mxtpu.model.FeedForward is the deprecated reference "
                      "surface; prefer mxtpu.module.Module",
                      DeprecationWarning, stacklevel=2)
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    # -- data plumbing (model.py:629 _init_iter) ---------------------------
    def _init_iter(self, X, y, is_train):
        import numpy as np
        from . import io as io_mod
        if isinstance(X, NDArray):
            X = X.asnumpy()
        if isinstance(X, np.ndarray):
            if y is None:
                if is_train:
                    raise ValueError("y must be specified when X is numpy")
                y = np.zeros(X.shape[0])
            if isinstance(y, NDArray):
                y = y.asnumpy()
            y = np.asarray(y)
            if y.ndim == 2 and y.shape[1] == 1:
                y = y.flatten()
            batch = min(X.shape[0], self.numpy_batch_size)
            return io_mod.NDArrayIter(X, y, batch, shuffle=is_train)
        return X

    def _get_module(self):
        from .module import Module
        if self._module is None:
            self._module = Module(self.symbol)
        return self._module

    def _ensure_ready(self, data):
        """Bind + load params for inference when the module hasn't been fit in
        this process (reference model.py:602 ``_init_predictor``)."""
        mod = self._get_module()
        if not mod.binded:
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
        if not mod.params_initialized:
            mod.init_params(initializer=self.initializer,
                            arg_params=self.arg_params,
                            aux_params=self.aux_params, allow_missing=False)
        return mod

    # -- estimator surface -------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """Delegates to ``Module.fit`` — which routes the train iterator
        through the device-feed input pipeline (``device_feed.DeviceFeed``:
        async prefetch of device-resident batches; opt-out
        ``MXTPU_DEVICE_FEED=0``), so the legacy estimator surface gets the
        overlapped host→device boundary for free — along with the unified
        step timeline: with ``MXTPU_TRACE=1`` (or
        ``profiler.set_state('run')``) every epoch's fused steps, feed
        transfers/stalls, and checkpoint writes land as spans in
        ``profiler.dump()``'s chrome-trace JSON, and the per-epoch log
        carries steps/s, p50/p99 step latency, MFU
        (``profiler.get_mfu_stats()``), and — under a sharded kvstore —
        the ZeRO stage's per-device param/grad/slot residency
        (``profiler.get_memory_stats()``)."""
        assert self.num_epoch is not None, "num_epoch required"
        data = self._init_iter(X, y, is_train=True)
        if isinstance(eval_data, (tuple, list)) and len(eval_data) == 2:
            eval_data = self._init_iter(eval_data[0], eval_data[1],
                                        is_train=False)
        mod = self._get_module()
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                eval_end_callback=eval_end_callback,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs or None,
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, allow_missing=True,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._init_iter(X, None, is_train=False)
        outs = self._ensure_ready(data).predict(data, num_batch=num_batch,
                                                reset=reset)
        if isinstance(outs, list):
            # Module.predict returns one already-concatenated NDArray per
            # graph output; multi-output nets return the list (reference
            # model.py predict: outputs[0] if single else list)
            if not outs:
                return outs
            arrs = [o.asnumpy() for o in outs]
            return arrs[0] if len(arrs) == 1 else arrs
        return outs.asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        data = self._init_iter(X, None, is_train=False)
        res = self._ensure_ready(data).score(data, eval_metric,
                                             num_batch=num_batch, reset=reset,
                                             batch_end_callback=batch_end_callback)
        return res[0][1]

    def save(self, prefix, epoch=None):
        epoch = epoch if epoch is not None else self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Train a new model from scratch and return it (model.py:895)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
