"""Live elasticity — in-place mesh resize mid-run (ROADMAP item 4).

PR 7's supervisor made preemption survivable by *restarting* ``fit`` from the
last committed checkpoint; this module removes the restart. An
:class:`ElasticRun` wraps one ``Module.fit`` call and, on a preemption signal
or an explicit scale event (:meth:`ElasticRun.request_resize`), pauses the
loop at the next step boundary and re-homes the live training state onto the
survivor/expanded mesh **inside the same fit call**:

1. build the new mesh over the surviving device prefix and make it the
   process default (``parallel.set_default_mesh``);
2. point the ``DeviceFeed`` staging boundary at it
   (:meth:`DeviceFeed.set_placement` — batches already staged on the old
   mesh are re-placed transparently by ``shard_batch``, so none are lost);
3. ``StepExecutor.adopt_mesh``: host-land the bucketed ZeRO optimizer slots,
   re-adopt them at the new data size via ``ZeroLayout.adopt_states`` (the
   SAME de-interleave/re-pack path a cold dp-N→dp-M checkpoint resume
   takes), re-place stage-3 resident params + their per-param slots, and
   drop the program cache so the next step traces once on the new mesh.

Update counters, the RNG stream, and the batch cursor are untouched, so the
post-resize trajectory is bit-exact with a cold checkpoint-resume taken at
the same step boundary onto the same mesh (``tests/test_elastic_guard.py``
pins this).

Failure containment: the whole resize runs under the ``elastic`` heartbeat
source — arm ``MXTPU_ELASTIC_STALL_S`` and a hung adoption becomes a
:class:`~.watchdog.StallReport` + emergency save instead of a silent wedge —
and behind the ``elastic.resize`` fault seam. Any error is wrapped in
:class:`ResizeError` after restoring the previous mesh, so
``supervisor.supervise`` can record the attempt as a ``restart_fallback``
and take the PR 7 restart path.

Knobs (the ``MXTPU_ELASTIC_*`` map, ``docs/resilience.md``):

* ``MXTPU_ELASTIC_STALL_S``  — deadline for one resize/drain (unset = no
  elastic watchdog; the step watchdog, if armed, is restored afterwards)
* ``MXTPU_ELASTIC_SIGNAL_DP`` — dp target a signal-triggered resize shrinks
  to (default: half the current data size, floor 1)
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal as signal_mod
import threading
import time
from typing import Callable, Optional, Union

from .faults import fault_point
from .watchdog import Watchdog, heartbeat

__all__ = ["ElasticRun", "ResizeError", "elastic_watchdog", "ENV_STALL",
           "ENV_SIGNAL_DP"]

ENV_STALL = "MXTPU_ELASTIC_STALL_S"
ENV_SIGNAL_DP = "MXTPU_ELASTIC_SIGNAL_DP"

_log = logging.getLogger("mxtpu.resilience")


class ResizeError(RuntimeError):
    """An in-place resize (or serving drain/adopt) failed. The previous mesh
    was restored before raising; ``supervisor.supervise`` classifies this as
    a restart fallback (``restart_fallbacks`` counter) and restarts from the
    last committed checkpoint."""


@contextlib.contextmanager
def elastic_watchdog():
    """Arm a deadline on the ``elastic`` heartbeat source for the duration
    of one resize/drain window when ``MXTPU_ELASTIC_STALL_S`` is set (no-op
    otherwise). Nested-arm safe: a step/serving watchdog armed outside is
    restored on exit (``Watchdog.stop`` hands back the previous active)."""
    raw = os.environ.get(ENV_STALL, "")
    if not raw:
        yield None
        return
    wd = Watchdog(deadline_s=float(raw), source="elastic").start()
    try:
        yield wd
    finally:
        wd.stop()


class ElasticRun:
    """Run one ``Module.fit`` with live mesh elasticity.

    ::

        er = ElasticRun(mod)
        er.install_signal_handler(signal.SIGTERM)       # preemption → shrink
        er.fit(train_iter, num_epoch=..., kvstore="device", ...)  # same args
        # ... or from any thread / a batch_end_callback:
        er.request_resize(4)                            # dp8 → dp4, live

    Requires a ZeRO/FSDP-engaged fit (``kvstore='device'``/``dist_sync`` with
    an elementwise optimizer) — that is the configuration whose state is
    re-bucketable in place; anything else has no mesh to resize and raises
    :class:`ResizeError` at the first resize attempt (the supervisor then
    falls back to a restart).

    ``mesh_factory(dp) -> Mesh`` customizes mesh construction for multi-axis
    (dp×fsdp×tp) runs; the default builds a 1-axis mesh with the current
    default mesh's first axis name over the first ``dp`` devices.
    """

    def __init__(self, module, mesh_factory: Optional[Callable] = None):
        self._module = module
        self._mesh_factory = mesh_factory
        self._lock = threading.Lock()
        self._pending: Optional[int] = None
        self._feed = None
        self.resizes = 0
        self.last_resize_ms: Optional[float] = None

    # -- triggers (any thread / signal handler) -----------------------------
    def request_resize(self, dp: Optional[int] = None) -> None:
        """Ask for a live resize to ``dp`` data-parallel devices at the next
        step boundary (idempotent until served; last writer wins). ``dp``
        None means "re-read ``jax.devices()``" — the scale-out case where
        the platform grew the pod."""
        with self._lock:
            self._pending = -1 if dp is None else int(dp)

    @property
    def pending_resize(self) -> bool:
        """True while a requested resize has not yet been served — actuators
        (e.g. the serving autoscaler) poll this to avoid stacking a second
        resize on one that is still in flight."""
        with self._lock:
            return self._pending is not None

    def install_signal_handler(self,
                               signum: int = signal_mod.SIGTERM,
                               dp: Union[None, int, Callable[[], int]] = None
                               ) -> None:
        """Route a preemption notice into :meth:`request_resize` (main
        thread only — Python signal contract). ``dp`` may be a fixed target,
        a callable resolved at signal time, or None for the default shrink
        (``MXTPU_ELASTIC_SIGNAL_DP``, else half the current data size)."""
        def _handler(_sig, _frm):
            target = dp() if callable(dp) else dp
            if target is None:
                raw = os.environ.get(ENV_SIGNAL_DP, "")
                if raw:
                    target = int(raw)
                else:
                    from ..parallel.mesh import data_size, get_default_mesh
                    target = max(1, data_size(get_default_mesh()) // 2)
            _log.warning("elastic: signal %d → live shrink to dp=%d",
                         _sig, target)
            self.request_resize(target)
        signal_mod.signal(signum, _handler)

    # -- the wrapped fit ----------------------------------------------------
    def fit(self, train_data, **fit_kwargs):
        """``Module.fit`` with the elastic boundary installed: the train
        iterator is pre-wrapped in a ``DeviceFeed`` placed on the current
        default mesh (so this controller owns the staging handle to re-place
        on resize), and a batch-end callback serves pending resize requests
        at step boundaries. All other arguments pass through unchanged."""
        from ..device_feed import DeviceFeed, maybe_device_feed
        from ..parallel.mesh import get_default_mesh
        feed = maybe_device_feed(train_data, placement=get_default_mesh())
        self._feed = feed if isinstance(feed, DeviceFeed) else None
        cbs = fit_kwargs.pop("batch_end_callback", None)
        cbs = list(cbs) if isinstance(cbs, (list, tuple)) \
            else ([cbs] if cbs is not None else [])
        cbs.append(self._on_batch_end)
        try:
            return self._module.fit(feed, batch_end_callback=cbs,
                                    **fit_kwargs)
        finally:
            self._feed = None

    def _on_batch_end(self, _param) -> None:
        with self._lock:
            target = self._pending
            self._pending = None
        if target is None:
            return
        self.resize_now(target if target > 0 else None)

    # -- the resize itself --------------------------------------------------
    def resize_now(self, dp: Optional[int] = None) -> None:
        """Perform the in-place resize immediately (caller must be at a step
        boundary — normally reached via :meth:`request_resize` + the batch
        callback). Raises :class:`ResizeError` on any failure, with the
        previous mesh restored."""
        import jax
        from ..observability import metrics, tracer
        from ..parallel.mesh import (data_size, get_default_mesh, make_mesh,
                                     set_default_mesh)
        old_mesh = get_default_mesh()
        if dp is None:
            dp = len(jax.devices())
        t0 = time.perf_counter()
        with tracer.span("resilience/resize", cat="resilience",
                         args={"from_dp": data_size(old_mesh), "to_dp": dp}):
            with elastic_watchdog():
                try:
                    heartbeat("elastic")
                    fault_point("elastic.resize")
                    exec_ = getattr(self._module, "_step_exec", None)
                    if exec_ is None or exec_._zero_mesh is None:
                        raise ResizeError(
                            "live resize needs a ZeRO/FSDP-engaged fused "
                            "step (kvstore device/dist_sync + elementwise "
                            "optimizer); none is active")
                    devices = jax.devices()
                    if dp < 1 or dp > len(devices):
                        raise ResizeError(
                            f"resize target dp={dp} outside the available "
                            f"{len(devices)} device(s)")
                    if self._mesh_factory is not None:
                        new_mesh = self._mesh_factory(dp)
                    elif len(old_mesh.axis_names) == 1:
                        new_mesh = make_mesh((dp,), old_mesh.axis_names,
                                             devices[:dp])
                    else:
                        raise ResizeError(
                            f"default mesh has axes {old_mesh.axis_names}; "
                            "a multi-axis resize needs mesh_factory")
                    set_default_mesh(new_mesh)
                    if self._feed is not None:
                        self._feed.set_placement(new_mesh)
                    exec_.adopt_mesh(new_mesh)
                    heartbeat("elastic")
                except ResizeError as e:
                    self._restore(old_mesh)
                    from ..observability import flight
                    flight.record("resize_error", to_dp=dp, error=str(e))
                    flight.dump("resize_error",
                                extra={"to_dp": dp, "error": str(e)})
                    raise
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:
                    # anything mid-adoption (injected fault, placement
                    # error, layout mismatch): restore the old mesh so the
                    # supervisor's fallback restart starts from sane state
                    self._restore(old_mesh)
                    from ..observability import flight
                    flight.record("resize_error", to_dp=dp, error=repr(e))
                    flight.dump("resize_error",
                                extra={"to_dp": dp, "error": repr(e)})
                    raise ResizeError(
                        f"in-place resize to dp={dp} failed: "
                        f"{type(e).__name__}: {e}") from e
        ms = (time.perf_counter() - t0) * 1e3
        self.resizes += 1
        self.last_resize_ms = ms
        metrics.record_resilience("live_resizes")
        metrics.record_resilience("resize_latency_ms_total", ms)
        metrics.record_resilience("resize_latency_ms_last", ms)
        _log.info("elastic: live resize %d → %d devices in %.1f ms "
                  "(no restart, 0 steps lost)",
                  data_size(old_mesh), dp, ms)

    def _restore(self, old_mesh) -> None:
        from ..parallel.mesh import set_default_mesh
        set_default_mesh(old_mesh)
        if self._feed is not None:
            self._feed.set_placement(old_mesh)
