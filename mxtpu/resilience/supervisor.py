"""Elastic resume supervisor — restart-from-last-commit as a library call.

``supervise(fit_fn, ...)`` owns the restart loop every elastic trainer
hand-rolls: run training, and when it dies (transient-turned-fatal error,
injected fault, watchdog abort, preemption, SIGKILL) start it again resuming
from the latest *committed* checkpoint step — at whatever dp size is
available for the new attempt. The dp-N→dp-M leg is exactly the
``ZeroLayout.adopt_states`` + DeviceFeed re-bucketing path the checkpoint
subsystem already supports; the supervisor is what exercises it end to end
without a human in the loop (ROADMAP item 4's "elasticity today means a
human restarts at a different dp size").

Two modes:

* ``mode="inline"`` (default) — ``fit_fn`` runs in this process inside the
  restart loop. Survives raised failures (injected faults, writer errors,
  collective flakes) but by nature not process death; cheap enough for
  tier-1 and the bench's resilience leg.
* ``mode="process"`` — each attempt is a fresh ``multiprocessing``
  *spawn* child (fork after JAX init is hazardous), so SIGKILL / preemption
  / watchdog ``os._exit(87)`` are all survivable. ``fit_fn`` must be a
  module-level (picklable) callable. The child inherits ``os.environ`` at
  spawn time: the supervisor sets ``MXTPU_RESTART_ATTEMPT`` (fault-plan
  ``attempt=`` gating), ``MXTPU_PROGRESS_BEACON`` (steps-lost accounting
  across SIGKILL), and — when a ``dp_schedule`` is given — rewrites the
  ``--xla_force_host_platform_device_count`` flag so the child boots with
  that attempt's device count.

``fit_fn`` receives a :class:`RestartContext` telling it which attempt this
is and where to resume from; the contract is that it passes
``ctx.resume_from()`` to ``Module.fit`` (a no-op fresh start when nothing
is committed yet, per ``fit``'s resume semantics).

Restarts, steps lost since the last commit, and restart latency all land in
``profiler.get_resilience_stats()``; each restart is a ``resilience/restart``
instant on the trace timeline.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Union

from . import faults, watchdog
from .retry import classify_error

__all__ = ["supervise", "RestartContext", "SuperviseResult", "GiveUpError"]

_log = logging.getLogger("mxtpu.resilience")

ENV_MAX_RESTARTS = "MXTPU_MAX_RESTARTS"


class GiveUpError(RuntimeError):
    """The restart budget is spent; the last failure is ``__cause__`` (inline
    mode) or summarized in the message (process mode)."""


@dataclass
class RestartContext:
    """What one attempt needs to know. Picklable (process mode ships it to
    the spawn child), so the manager handle is inline-only — process-mode
    ``fit_fn`` builds its own manager at ``directory``."""
    attempt: int                      # 1-based; attempt 1 is the first run
    directory: Optional[str]          # checkpoint root (shared across attempts)
    resume_step: Optional[int]        # latest committed step at attempt start
    dp: Optional[int] = None          # device count this attempt runs at
    prev_error: Optional[str] = None  # why the previous attempt died
    manager: Optional[object] = None  # inline mode: the live CheckpointManager
    elastic: Optional[object] = None  # inline mode: the live ElasticRun, so
    #   fit_fn can route train_data through it (live resize before restart)

    @property
    def restarts(self) -> int:
        return self.attempt - 1

    def resume_from(self):
        """The value to pass to ``Module.fit(resume_from=...)``: the manager
        (inline) or the directory, or None when there is nothing to resume."""
        if self.resume_step is None:
            return None
        return self.manager if self.manager is not None else self.directory


@dataclass
class SuperviseResult:
    result: object = None             # fit_fn return value (inline mode)
    attempts: int = 0
    restarts: int = 0
    steps_lost: int = 0
    exit_codes: List[int] = field(default_factory=list)  # process mode
    errors: List[str] = field(default_factory=list)


def _latest_committed(manager, directory: Optional[str]) -> Optional[int]:
    if manager is not None:
        return manager.latest_step()
    if directory and os.path.isdir(directory):
        from ..checkpoint import atomic_io
        steps = atomic_io.committed_steps(directory, "step")
        return steps[-1] if steps else None
    return None


def _dp_for_attempt(dp_schedule, attempt: int) -> Optional[int]:
    if dp_schedule is None:
        return None
    if callable(dp_schedule):
        return dp_schedule(attempt)
    seq: Sequence[int] = dp_schedule
    if not seq:
        return None
    return int(seq[min(attempt - 1, len(seq) - 1)])


def _xla_flags_with_device_count(flags: str, n: int) -> str:
    kept = [f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count=")]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(kept)


class _EnvScope:
    """Set env vars for the duration of a with-block, restoring prior values
    (the spawn child snapshots ``os.environ`` at ``Process.start()``)."""

    def __init__(self, updates: dict):
        self.updates = updates
        self._saved: dict = {}

    def __enter__(self):
        for k, v in self.updates.items():
            self._saved[k] = os.environ.get(k)
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False


def _record_restart(reason: str, latency_ms: float, lost: int) -> None:
    from ..observability import metrics, tracer
    metrics.record_resilience("restarts")
    metrics.record_resilience("restart_latency_ms_total", latency_ms)
    metrics.record_resilience("restart_latency_ms_last", latency_ms)
    if lost > 0:
        metrics.record_resilience("steps_lost", lost)
    tracer.instant("resilience/restart", cat="resilience",
                   args={"reason": reason[:200],
                         "latency_ms": round(latency_ms, 3),
                         "steps_lost": lost})


def supervise(fit_fn: Callable[[RestartContext], object],
              manager=None,
              directory: Optional[str] = None,
              mode: str = "inline",
              max_restarts: Optional[int] = None,
              dp_schedule: Union[None, Sequence[int],
                                 Callable[[int], Optional[int]]] = None,
              restart_backoff_s: float = 0.1,
              attempt_timeout_s: Optional[float] = None,
              elastic=None) -> SuperviseResult:
    """Run ``fit_fn`` under the elastic restart loop.

    ``manager``/``directory`` name the checkpoint root resumption reads from
    (one of them is required for resume to mean anything; with neither, every
    restart is a fresh start). ``max_restarts`` bounds restarts beyond the
    first attempt (env ``MXTPU_MAX_RESTARTS``, default 3); exhaustion raises
    :class:`GiveUpError`. ``attempt_timeout_s`` (process mode) kills a child
    that outlives it — a last-resort backstop under the watchdog.

    ``elastic`` (inline mode) is an :class:`~.elastic.ElasticRun` handed to
    each attempt via ``ctx.elastic``: resizes are served live in place, and
    only a :class:`~.elastic.ResizeError` — live adoption failed — falls
    through to this restart loop (counted as a ``restart_fallback``)."""
    if mode not in ("inline", "process"):
        raise ValueError(f"mode must be 'inline' or 'process', got {mode!r}")
    if max_restarts is None:
        try:
            max_restarts = int(os.environ.get(ENV_MAX_RESTARTS, "3"))
        except ValueError:
            max_restarts = 3
    if manager is not None and directory is None:
        directory = manager.directory
    watchdog.ensure_commit_hook()
    if mode == "inline":
        return _supervise_inline(fit_fn, manager, directory, max_restarts,
                                 dp_schedule, restart_backoff_s, elastic)
    if elastic is not None:
        raise ValueError("elastic= is inline-only (an ElasticRun holds live "
                         "module state and cannot ship to a spawn child)")
    return _supervise_process(fit_fn, directory, max_restarts, dp_schedule,
                              restart_backoff_s, attempt_timeout_s)


# -- inline mode -------------------------------------------------------------

def _supervise_inline(fit_fn, manager, directory, max_restarts, dp_schedule,
                      backoff_s, elastic=None) -> SuperviseResult:
    from ..observability import tracer
    res = SuperviseResult()
    prev_error: Optional[str] = None
    # steps-lost baseline: heartbeat counters are process-cumulative, so any
    # steps run BEFORE this supervise() call must not count as "lost"
    base_steps = watchdog.progress_snapshot()["steps"]
    attempt = 0
    while True:
        attempt += 1
        res.attempts = attempt
        ctx = RestartContext(attempt=attempt, directory=directory,
                             resume_step=_latest_committed(manager, directory),
                             dp=_dp_for_attempt(dp_schedule, attempt),
                             prev_error=prev_error, manager=manager,
                             elastic=elastic)
        with _EnvScope({faults.ENV_ATTEMPT: attempt}):
            try:
                with tracer.span("resilience/attempt", cat="resilience",
                                 args={"attempt": attempt, "mode": "inline",
                                       "resume_step": ctx.resume_step}):
                    res.result = fit_fn(ctx)
                return res
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as exc:
                prev_error = f"{type(exc).__name__}: {exc}"
                res.errors.append(prev_error)
                from .elastic import ResizeError
                if isinstance(exc, ResizeError):
                    # live in-place adoption failed — this restart is the
                    # fallback path, and the scoreboard should say so
                    from ..observability import metrics
                    metrics.record_resilience("restart_fallbacks")
                    _log.warning("supervise[inline]: live resize failed "
                                 "(%s) — falling back to restart", exc)
                snap = watchdog.progress_snapshot()
                lost = max(0, snap["steps"]
                           - max(snap["committed_steps"], base_steps))
                if res.restarts >= max_restarts:
                    raise GiveUpError(
                        f"giving up after {attempt} attempts "
                        f"({max_restarts} restarts): {prev_error}") from exc
                res.restarts += 1
                res.steps_lost += lost
                _log.warning(
                    "supervise[inline]: attempt %d died (%s; transient=%s, "
                    "~%d steps since last commit) — restarting from step %s",
                    attempt, prev_error, classify_error(exc), lost,
                    _latest_committed(manager, directory))
        t_death = time.perf_counter()
        time.sleep(backoff_s)
        _record_restart(prev_error, (time.perf_counter() - t_death) * 1e3,
                        lost)


# -- process mode ------------------------------------------------------------

def _child_main(fit_fn, ctx: RestartContext) -> None:
    """Spawn-child entry: arm the watchdog when a deadline is configured,
    run the attempt, exit 0/1. (Beacon + commit hook arm at import via
    ``MXTPU_PROGRESS_BEACON``, which the parent set before spawning.)"""
    wd = None
    if os.environ.get(watchdog.ENV_DEADLINE):
        wd = watchdog.Watchdog().start()
    try:
        fit_fn(ctx)
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException:  # mxtpu: ignore[R005] — KI/SE re-raised above; any
        # other death must become a nonzero exit the parent can classify
        traceback.print_exc()
        sys.stderr.flush()
        sys.exit(1)
    finally:
        if wd is not None:
            wd.stop()
    sys.exit(0)


def _describe_exit(code: Optional[int]) -> str:
    if code is None:
        return "still alive?"
    if code == watchdog.WATCHDOG_EXIT_CODE:
        return f"watchdog abort (exit {code})"
    if code < 0:
        try:
            name = signal.Signals(-code).name
        except ValueError:
            name = f"signal {-code}"
        return f"killed by {name}"
    return f"exit {code}"


def _supervise_process(fit_fn, directory, max_restarts, dp_schedule,
                       backoff_s, attempt_timeout_s) -> SuperviseResult:
    import multiprocessing
    from ..observability import tracer
    mp = multiprocessing.get_context("spawn")
    res = SuperviseResult()
    beacon_path = os.path.join(directory, ".progress-beacon") if directory \
        else None
    prev_error: Optional[str] = None
    attempt = 0
    t_death: Optional[float] = None
    while True:
        attempt += 1
        res.attempts = attempt
        dp = _dp_for_attempt(dp_schedule, attempt)
        ctx = RestartContext(attempt=attempt, directory=directory,
                             resume_step=_latest_committed(None, directory),
                             dp=dp, prev_error=prev_error)
        env = {faults.ENV_ATTEMPT: attempt}
        if beacon_path:
            env[watchdog.ENV_BEACON] = beacon_path
        if dp is not None:
            env["XLA_FLAGS"] = _xla_flags_with_device_count(
                os.environ.get("XLA_FLAGS", ""), dp)
        with _EnvScope(env):
            child = mp.Process(target=_child_main, args=(fit_fn, ctx),
                               name=f"mxtpu-supervised-{attempt}")
            child.start()
        if t_death is not None:  # restart latency: death → new child running
            latency_ms = (time.perf_counter() - t_death) * 1e3
            lost = 0
            if beacon_path:
                beacon = watchdog.read_beacon(beacon_path)
                if beacon:
                    lost = max(0, int(beacon.get("steps", 0))
                               - int(beacon.get("committed_steps", 0)))
            res.steps_lost += lost
            _record_restart(prev_error or "?", latency_ms, lost)
        child.join(attempt_timeout_s)
        if child.is_alive():
            _log.error("supervise[process]: attempt %d exceeded %.1fs — "
                       "killing", attempt, attempt_timeout_s)
            child.terminate()
            child.join(10)
            if child.is_alive():
                child.kill()
                child.join(10)
        code = child.exitcode
        res.exit_codes.append(code if code is not None else -255)
        if code == 0:
            return res
        t_death = time.perf_counter()
        prev_error = _describe_exit(code)
        res.errors.append(prev_error)
        tracer.instant("resilience/child_exit", cat="resilience",
                       args={"attempt": attempt, "exit": prev_error})
        if res.restarts >= max_restarts:
            raise GiveUpError(
                f"giving up after {attempt} attempts ({max_restarts} "
                f"restarts): last child death: {prev_error}")
        res.restarts += 1
        _log.warning(
            "supervise[process]: attempt %d died (%s) — restarting from "
            "step %s at dp=%s", attempt, prev_error,
            _latest_committed(None, directory),
            _dp_for_attempt(dp_schedule, attempt + 1))
        time.sleep(backoff_s)
