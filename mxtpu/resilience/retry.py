"""``retry_transient`` — THE retry policy (one implementation, many callers).

ROADMAP item 4's motivating incident: a single transient backend
``UNAVAILABLE`` erased an entire bench round because nothing between the
raise and the harness knew the difference between "try again in a second"
and "your program is wrong". This module is that knowledge:

* :func:`classify_error` — transient (backend UNAVAILABLE / init races /
  fs hiccups / connection flakes) vs logic errors (TypeError & friends
  escalate immediately; retrying those only buries the traceback).
* :func:`retry_transient` — bounded exponential backoff with deterministic
  jitter around any callable. Adopted by ``dist.initialize``, the checkpoint
  writer's shard-write/commit path, and ``bench.py run_leg`` (replacing its
  ad-hoc one-retry).

Knobs: ``MXTPU_RETRY_MAX`` (retries after the first attempt, default 3),
``MXTPU_RETRY_BACKOFF_S`` (base delay, default 0.5, doubling per retry,
capped at ``MXTPU_RETRY_BACKOFF_MAX_S`` default 30). Jitter is a
deterministic per-process sequence so runs are reproducible.

Every retry lands in ``profiler.get_resilience_stats()`` (``retries`` /
``retries_exhausted`` / ``escalations``) and on the chrome-trace timeline as
a ``resilience/retry`` span covering the backoff sleep.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional, Tuple

from .faults import InjectedFault

__all__ = ["classify_error", "is_transient", "retry_transient", "RetryError"]

#: substrings marking a transient backend/transport/init failure (matched
#: case-insensitively against "ExcType: message")
TRANSIENT_MARKERS: Tuple[str, ...] = (
    "unavailable", "deadline exceeded", "deadline_exceeded",
    "resource exhausted", "resource_exhausted", "aborted",
    "temporarily", "connection reset", "connection refused",
    "broken pipe", "socket closed", "handshake",
    "unable to initialize", "failed to initialize",
    "stale file handle", "try again",
)

#: exception families that are never worth retrying — a second attempt runs
#: the same wrong code
_LOGIC_TYPES = (TypeError, ValueError, KeyError, IndexError, AttributeError,
                AssertionError, NotImplementedError, ArithmeticError,
                ImportError, NameError)

#: OS-level families that usually mean "the world hiccuped, not the program"
_TRANSIENT_TYPES = (ConnectionError, TimeoutError, BlockingIOError,
                    InterruptedError)


class RetryError(RuntimeError):
    """Wrapper raised when a *transient* error survives every allowed retry —
    callers distinguishing "gave up retrying" from "logic error" catch this;
    the original failure is ``__cause__``."""

    def __init__(self, label: str, attempts: int, last: BaseException):
        self.label = label
        self.attempts = attempts
        super().__init__(
            f"{label}: transient error persisted through {attempts} attempts: "
            f"{type(last).__name__}: {last}")


def classify_error(exc: BaseException) -> bool:
    """True when ``exc`` looks transient (worth retrying)."""
    if isinstance(exc, InjectedFault):
        return exc.transient
    if isinstance(exc, _LOGIC_TYPES):
        return False
    if isinstance(exc, _TRANSIENT_TYPES):
        return True
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(marker in text for marker in TRANSIENT_MARKERS)


is_transient = classify_error  # alias, reads better at some call sites


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


# Deterministic jitter: a fixed-seed stream (overridable for tests) so two
# runs with the same fault plan sleep the same schedule.
_jitter_rng = random.Random(20260804)


def _backoff_s(attempt: int, base: float, cap: float) -> float:
    delay = min(base * (2.0 ** attempt), cap)
    return delay * (1.0 + 0.25 * _jitter_rng.random())


def retry_transient(fn: Callable, *args,
                    label: str = "op",
                    max_retries: Optional[int] = None,
                    base_backoff_s: Optional[float] = None,
                    max_backoff_s: Optional[float] = None,
                    classify: Optional[Callable[[BaseException], bool]] = None,
                    on_retry: Optional[Callable[[BaseException, int], None]] = None,
                    **kwargs):
    """Call ``fn(*args, **kwargs)``; retry transient failures with bounded
    exponential backoff.

    Non-transient errors propagate unchanged on the first occurrence.
    Transient errors are retried up to ``max_retries`` times
    (``MXTPU_RETRY_MAX``, default 3); exhaustion raises :class:`RetryError`
    from the last failure. ``on_retry(exc, attempt)`` runs before each
    backoff sleep (loggers, counters)."""
    retries = _env_int("MXTPU_RETRY_MAX", 3) if max_retries is None \
        else max_retries
    base = _env_float("MXTPU_RETRY_BACKOFF_S", 0.5) if base_backoff_s is None \
        else base_backoff_s
    cap = _env_float("MXTPU_RETRY_BACKOFF_MAX_S", 30.0) if max_backoff_s is None \
        else max_backoff_s
    judge = classify or classify_error

    from ..observability import metrics, tracer
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn(*args, **kwargs)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:
            last = exc
            if not judge(exc):
                metrics.record_resilience("escalations")
                tracer.instant("resilience/escalate", cat="resilience",
                               args={"label": label,
                                     "error": type(exc).__name__})
                raise
            if attempt >= retries:
                break
            metrics.record_resilience("retries")
            if on_retry is not None:
                on_retry(exc, attempt)
            with tracer.span("resilience/retry", cat="resilience",
                             args={"label": label, "attempt": attempt + 1,
                                   "error": f"{type(exc).__name__}: {exc}"[:200]}):
                time.sleep(_backoff_s(attempt, base, cap))
    metrics.record_resilience("retries_exhausted")
    tracer.instant("resilience/retries_exhausted", cat="resilience",
                   args={"label": label, "attempts": retries + 1})
    assert last is not None
    raise RetryError(label, retries + 1, last) from last
