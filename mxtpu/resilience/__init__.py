"""mxtpu.resilience — fault injection, watchdog/retry runtime, and the
elastic resume supervisor (ROADMAP item 4; see ``docs/resilience.md``).

The reference MXNet's dependency engine kept making progress under async
chaos inside one process; this package is the same discipline at the *job*
level: schedule failures deterministically (:mod:`.faults`), retry what is
transient (:mod:`.retry`), detect what hangs (:mod:`.watchdog`), restart
what dies — resuming from the last committed checkpoint at whatever dp size
is available (:mod:`.supervisor`) — and, when the mesh merely *shrinks or
grows*, resize in place without restarting at all (:mod:`.elastic`).
"""

from .elastic import ElasticRun, ResizeError, elastic_watchdog
from .faults import (FaultPlan, InjectedFault, fault_point, get_fault_plan,
                     reset_fault_plan)
from .retry import RetryError, classify_error, is_transient, retry_transient
from .supervisor import (GiveUpError, RestartContext, SuperviseResult,
                         supervise)
from .watchdog import (WATCHDOG_EXIT_CODE, StallReport, Watchdog, heartbeat)

__all__ = [
    "FaultPlan", "InjectedFault", "fault_point", "get_fault_plan",
    "reset_fault_plan",
    "RetryError", "classify_error", "is_transient", "retry_transient",
    "Watchdog", "StallReport", "heartbeat", "WATCHDOG_EXIT_CODE",
    "supervise", "RestartContext", "SuperviseResult", "GiveUpError",
    "ElasticRun", "ResizeError", "elastic_watchdog",
]
