"""Deterministic fault injection — the reproducibility half of the resilience
story (ROADMAP item 4).

Every failure mode the watchdog/retry/supervisor stack claims to survive must
be demonstrable in tier-1 on CPU, which means faults have to be *scheduled*,
not sampled: a ``FaultPlan`` parsed from ``MXTPU_FAULT_PLAN`` names a seam
(``site``), a pass count at that seam (``step``/``at``), a failure ``kind``,
and fires exactly when the plan says — same plan, same run, same fault.

Seams (``fault_point(site)`` calls) live in:

* ``step``            — top of ``StepExecutor.step`` (before RNG advance)
* ``ckpt.write``      — checkpoint writer thread, per save job
* ``ckpt.commit``     — rank-0 commit (tmp→final rename) boundary
* ``feed.produce``    — DeviceFeed producer thread, per prefetched batch
* ``collective``      — array-level collectives entry (``allreduce_array``)
* ``exchange``        — cross-process host-value exchange
* ``dist.initialize`` — multi-process runtime bring-up
* ``elastic.resize``  — top of a live in-place mesh resize (``ElasticRun``)
* ``serving.drain``   — serving drain/handoff, after admission stops

Grammar (entries split on ``,`` or ``;``; fields split on ``:``)::

    MXTPU_FAULT_PLAN="site=ckpt.write:step=2:kind=io_error"
    MXTPU_FAULT_PLAN="step=12:kind=io_error"            # site defaults to "step"
    MXTPU_FAULT_PLAN="site=feed.produce:at=3:kind=crash:attempt=1"

Fields: ``site`` (seam name, default ``step``), ``at``/``step`` (1-based pass
index at that seam, default 1), ``kind`` (below, default ``io_error``),
``count`` (how many consecutive passes fire, ``-1`` = forever, default 1),
``attempt`` (only fire on this restart attempt — ``MXTPU_RESTART_ATTEMPT``,
set by the supervisor — so a fault hits attempt 1 and *not* the resumed run).

Kinds:

* ``io_error``     — raise transient :class:`InjectedFault` (fs/backend error)
* ``unavailable``  — raise transient :class:`InjectedFault` with an
  ``UNAVAILABLE`` message (backend/transport flake)
* ``crash``        — raise non-transient :class:`InjectedFault` (logic error;
  retry must escalate, supervisor-level restart is the only recovery)
* ``preempt``      — ``SIGTERM`` to self (preemption notice; the checkpoint
  preemption handler takes the final-save path)
* ``kill``         — ``SIGKILL`` to self (hard loss, no cleanup — the
  crash-matrix hammer)
* ``exit``         — ``os._exit(13)`` (abrupt exit, skipping atexit)
* ``hang``         — block the calling thread (watchdog fodder); duration
  ``MXTPU_FAULT_HANG_S`` (default: forever from the step's point of view)
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

ENV_PLAN = "MXTPU_FAULT_PLAN"
ENV_ATTEMPT = "MXTPU_RESTART_ATTEMPT"
ENV_HANG_S = "MXTPU_FAULT_HANG_S"

#: kinds that raise; everything else is a process-level action
_RAISING_KINDS = ("io_error", "unavailable", "crash")
_ACTION_KINDS = ("preempt", "kill", "exit", "hang")
KINDS = _RAISING_KINDS + _ACTION_KINDS

#: raising kinds retry_transient() is allowed to absorb
TRANSIENT_KINDS = ("io_error", "unavailable")


class InjectedFault(RuntimeError):
    """A scheduled failure raised at a ``fault_point`` seam.

    ``transient`` drives :func:`mxtpu.resilience.retry.classify_error` —
    injected ``io_error``/``unavailable`` faults are retryable, injected
    ``crash`` faults must escalate."""

    def __init__(self, site: str, kind: str, hit: int):
        self.site = site
        self.kind = kind
        self.hit = hit
        self.transient = kind in TRANSIENT_KINDS
        tag = "UNAVAILABLE: " if kind == "unavailable" else ""
        super().__init__(
            f"{tag}injected {kind} at site={site} (pass #{hit}) "
            f"[{ENV_PLAN} fault]")


@dataclass
class FaultRule:
    """One parsed plan entry."""
    site: str = "step"
    at: int = 1            # 1-based pass index at the site
    kind: str = "io_error"
    count: int = 1         # consecutive passes that fire; -1 = forever
    attempt: Optional[int] = None  # restart attempt gate (None = any)
    fired: int = 0

    def matches(self, site: str, npass: int, attempt: int) -> bool:
        if self.site != site:
            return False
        if self.attempt is not None and self.attempt != attempt:
            return False
        if npass < self.at:
            return False
        if self.count >= 0 and self.fired >= self.count:
            return False
        return self.count < 0 or npass < self.at + self.count


def _parse_entry(entry: str) -> FaultRule:
    rule = FaultRule()
    for fld in entry.split(":"):
        fld = fld.strip()
        if not fld:
            continue
        if "=" not in fld:
            raise ValueError(
                f"{ENV_PLAN}: field {fld!r} is not key=value (entry {entry!r})")
        key, _, val = fld.partition("=")
        key, val = key.strip().lower(), val.strip()
        if key == "site":
            rule.site = val
        elif key in ("at", "step"):
            rule.at = int(val)
        elif key == "kind":
            if val not in KINDS:
                raise ValueError(
                    f"{ENV_PLAN}: unknown kind {val!r} (choose from {KINDS})")
            rule.kind = val
        elif key == "count":
            rule.count = int(val)
        elif key == "attempt":
            rule.attempt = int(val)
        else:
            raise ValueError(
                f"{ENV_PLAN}: unknown field {key!r} (entry {entry!r})")
    if rule.at < 1:
        raise ValueError(f"{ENV_PLAN}: at/step must be >= 1 (entry {entry!r})")
    return rule


@dataclass
class FaultPlan:
    """A parsed ``MXTPU_FAULT_PLAN``: rules plus per-site pass counters.

    Counters are per-plan (fresh plan → fresh counters), guarded by one lock
    because seams fire from the trainer thread, the feed producer, and the
    checkpoint writer concurrently."""
    rules: List[FaultRule] = field(default_factory=list)
    spec: str = ""

    def __post_init__(self):
        self._lock = threading.Lock()
        self._passes: Dict[str, int] = {}

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries = [e for chunk in spec.split(";")
                   for e in chunk.split(",") if e.strip()]
        return cls(rules=[_parse_entry(e) for e in entries], spec=spec)

    def passes(self, site: str) -> int:
        with self._lock:
            return self._passes.get(site, 0)

    def check(self, site: str) -> None:
        """Count one pass through ``site``; fire the first armed matching
        rule (raise or act per its kind)."""
        attempt = _current_attempt()
        with self._lock:
            npass = self._passes.get(site, 0) + 1
            self._passes[site] = npass
            hit: Optional[FaultRule] = None
            for rule in self.rules:
                if rule.matches(site, npass, attempt):
                    rule.fired += 1
                    hit = rule
                    break
        if hit is not None:
            _fire(site, hit.kind, npass)


def _current_attempt() -> int:
    try:
        return int(os.environ.get(ENV_ATTEMPT, "1"))
    except ValueError:
        return 1


def _record(site: str, kind: str) -> None:
    # Lazy import: observability must stay importable without resilience and
    # vice versa; seams are cheap until a fault actually fires.
    from ..observability import metrics, tracer
    metrics.record_resilience("faults_injected")
    tracer.instant("resilience/fault", cat="resilience",
                   args={"site": site, "kind": kind})


def _fire(site: str, kind: str, npass: int) -> None:
    _record(site, kind)
    if kind in _RAISING_KINDS:
        raise InjectedFault(site, kind, npass)
    if kind == "preempt":
        os.kill(os.getpid(), signal.SIGTERM)
        # Give the signal handler (final blocking save + SIG_DFL re-delivery)
        # time to run before this seam returns and races the teardown.
        time.sleep(float(os.environ.get("MXTPU_FAULT_PREEMPT_GRACE_S", "30")))
        return
    if kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(30)  # SIGKILL delivery is async; never proceed past it
        return
    if kind == "exit":
        os._exit(13)
    if kind == "hang":
        deadline = time.monotonic() + float(os.environ.get(ENV_HANG_S, "3600"))
        while time.monotonic() < deadline:
            time.sleep(0.05)
        return
    raise AssertionError(f"unhandled fault kind {kind!r}")


# -- module-level plan cache ------------------------------------------------
# One plan per env spec string: counters persist across fault_point calls but
# reset when the spec changes (or via reset_fault_plan, for tests that reuse
# a spec in-process).

_plan_lock = threading.Lock()
_cached_spec: Optional[str] = None
_cached_plan: Optional[FaultPlan] = None


def get_fault_plan() -> Optional[FaultPlan]:
    """The active plan parsed from ``MXTPU_FAULT_PLAN`` (None when unset)."""
    spec = os.environ.get(ENV_PLAN, "")
    if not spec.strip():
        return None
    global _cached_spec, _cached_plan
    with _plan_lock:
        if spec != _cached_spec:
            _cached_plan = FaultPlan.parse(spec)
            _cached_spec = spec
        return _cached_plan


def reset_fault_plan() -> None:
    """Drop the cached plan so the next seam re-parses (fresh counters)."""
    global _cached_spec, _cached_plan
    with _plan_lock:
        _cached_spec = None
        _cached_plan = None


def fault_point(site: str) -> None:
    """Injection seam: a no-op unless ``MXTPU_FAULT_PLAN`` schedules a fault
    here. Called from hot paths — the unset-env fast path is one getenv."""
    if not os.environ.get(ENV_PLAN):
        return
    plan = get_fault_plan()
    if plan is not None:
        plan.check(site)
