"""Per-step deadline watchdog — turns a silent hang into a structured abort.

A hung collective (or a wedged host callback, or a dead feed producer with a
blocked consumer) does not raise; it just stops the world. The reference's
dependency engine had the same failure shape — an op whose callback never
fires wedges every dependent op — and the operational answer is the same:
watch for "no step finished within the deadline", and when it trips, dump
*what the process was doing* (per-thread beats, the tracer's most recent
spans per timeline row, live Python stacks), attempt one emergency blocking
checkpoint, and exit with a recognizable code so a supervisor can restart
instead of waiting forever.

Heartbeats are cheap module-level calls (``watchdog.heartbeat("step")``)
wired into ``step_cache.StepExecutor.step`` (the deadline source), the
DeviceFeed producer (``feed``), and the checkpoint writer (``ckpt``) — the
last two don't gate the deadline but land in the :class:`StallReport` so a
stall distinguishes "step wedged while feed kept producing" from "everything
stopped".

Heartbeats also drive the *progress beacon*: when a supervisor set
``MXTPU_PROGRESS_BEACON`` the step count (and committed-step watermark, via
the checkpoint commit hook) is mirrored to a small JSON file the parent can
read after SIGKILL — the "steps lost since last commit" accounting in
``get_resilience_stats()`` (approximate by one async-snapshot lag; see
``docs/resilience.md``).

Knobs: ``MXTPU_STEP_DEADLINE_S`` (arms the deadline; unset = watchdog must
be constructed explicitly), ``MXTPU_WATCHDOG_GRACE_S`` (emergency-save
budget before the abort, default 20).

Exit code :data:`WATCHDOG_EXIT_CODE` (87) marks a watchdog abort to
``supervisor.supervise`` (restart-worthy, like a crash, but reported
separately).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

__all__ = ["Watchdog", "StallReport", "heartbeat", "active", "armed",
           "set_emergency_save", "set_progress_beacon", "beat_counts",
           "WATCHDOG_EXIT_CODE", "ENV_DEADLINE", "ENV_BEACON"]

WATCHDOG_EXIT_CODE = 87
ENV_DEADLINE = "MXTPU_STEP_DEADLINE_S"
ENV_BEACON = "MXTPU_PROGRESS_BEACON"

_log = logging.getLogger("mxtpu.resilience")

# -- module heartbeat plumbing ----------------------------------------------
# heartbeat() must stay callable (and cheap) with no watchdog armed: the step
# loop, feed producer, and ckpt writer call it unconditionally. Counts are
# module state guarded by one lock (R004 contract); the active watchdog and
# beacon path are scalar rebinds.

_hb_lock = threading.Lock()
_beat_counts: Dict[str, int] = {}
_beacon = {"path": None, "committed": 0, "last_write": 0.0}
_active: Optional["Watchdog"] = None


def heartbeat(source: str = "step") -> None:
    """Record one unit of progress from ``source`` (thread-safe, hot-path
    cheap: one lock bump; beacon writes are throttled)."""
    with _hb_lock:
        _beat_counts[source] = _beat_counts.get(source, 0) + 1
        n = _beat_counts[source]
        path = _beacon["path"]
    wd = _active
    if wd is not None:
        wd.beat(source)
    if path is not None and source == "step":
        _maybe_write_beacon(n)


def beat_counts() -> Dict[str, int]:
    with _hb_lock:
        return dict(_beat_counts)


def reset_heartbeats() -> None:
    with _hb_lock:
        _beat_counts.clear()


def active() -> Optional["Watchdog"]:
    return _active


def armed() -> bool:
    return _active is not None


def set_emergency_save(fn: Optional[Callable[[], None]]) -> None:
    """Register the blocking-save callable the default stall policy runs
    before aborting (``Module.fit`` wires this when a CheckpointManager is
    in play). No-op storage when no watchdog ever arms."""
    wd = _active
    if wd is not None:
        wd.set_emergency(fn)
    global _pending_emergency
    _pending_emergency = fn


_pending_emergency: Optional[Callable[[], None]] = None


# -- progress beacon ---------------------------------------------------------

def set_progress_beacon(path: Optional[str]) -> None:
    """Point the beacon at ``path`` (or disarm with None). The supervisor
    sets this in the child via ``MXTPU_PROGRESS_BEACON``."""
    with _hb_lock:
        _beacon["path"] = path
        _beacon["committed"] = 0
        _beacon["last_write"] = 0.0


def _write_beacon_locked_snapshot(path: str, steps: int, committed: int) -> None:
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"steps": steps, "committed_steps": committed,
                       "pid": os.getpid(), "ts": time.time()}, f)
        os.replace(tmp, path)
    except OSError:
        _log.debug("progress beacon write failed", exc_info=True)


def _maybe_write_beacon(steps: int, force: bool = False) -> None:
    now = time.monotonic()
    with _hb_lock:
        path = _beacon["path"]
        if path is None:
            return
        if not force and now - _beacon["last_write"] < 0.05:
            return
        _beacon["last_write"] = now
        committed = _beacon["committed"]
    _write_beacon_locked_snapshot(path, steps, committed)


def _on_checkpoint_commit() -> None:
    """Checkpoint commit hook (registered with ``observability.metrics``):
    advance the committed-step watermark to the current step count. Off by
    one async-snapshot lag — documented as approximate."""
    with _hb_lock:
        _beacon["committed"] = _beat_counts.get("step", 0)
        steps = _beat_counts.get("step", 0)
        path = _beacon["path"]
    if path is not None:
        _maybe_write_beacon(steps, force=True)


def ensure_commit_hook() -> None:
    """Register the committed-step watermark hook with the metrics store
    (idempotent — ``add_commit_hook`` dedups)."""
    from ..observability import metrics
    metrics.add_commit_hook(_on_checkpoint_commit)


def progress_snapshot() -> dict:
    """``{"steps": N, "committed_steps": M}`` for the current process —
    the inline-supervisor side of steps-lost accounting."""
    with _hb_lock:
        return {"steps": _beat_counts.get("step", 0),
                "committed_steps": _beacon["committed"]}


def read_beacon(path: str) -> Optional[dict]:
    """Parse a beacon file (parent side, after child death)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _install_beacon_from_env() -> None:
    path = os.environ.get(ENV_BEACON)
    if path:
        set_progress_beacon(path)
        ensure_commit_hook()


# -- stall report ------------------------------------------------------------

class StallReport:
    """Everything known at the moment the deadline tripped: per-source beat
    ages/counts, the tracer's most recent spans per thread row (the "blocked
    span"), and live Python stacks for every thread."""

    def __init__(self, deadline_s: float, waited_s: float,
                 beats: Dict[str, dict], spans: List[dict],
                 stacks: Dict[str, str]):
        self.deadline_s = deadline_s
        self.waited_s = waited_s
        self.beats = beats
        self.spans = spans
        self.stacks = stacks

    def to_dict(self) -> dict:
        return {"deadline_s": self.deadline_s, "waited_s": self.waited_s,
                "beats": self.beats, "recent_spans": self.spans,
                "stacks": self.stacks}

    def render(self) -> str:
        lines = [f"WATCHDOG: no step heartbeat for {self.waited_s:.1f}s "
                 f"(deadline {self.deadline_s:.1f}s)"]
        for src, info in sorted(self.beats.items()):
            lines.append(f"  beat[{src}]: count={info['count']} "
                         f"age={info['age_s']:.1f}s")
        for row in self.spans:
            tail = ", ".join(e.get("name", "?") for e in row["events"])
            lines.append(f"  spans[{row['thread']}]: ... {tail}")
        for name, stack in self.stacks.items():
            lines.append(f"  stack[{name}]:")
            for ln in stack.rstrip().splitlines():
                lines.append(f"    {ln}")
        return "\n".join(lines)

    def __str__(self):
        return self.render()


def _thread_stacks() -> Dict[str, str]:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')}({tid})"
        out[label] = "".join(traceback.format_stack(frame))
    return out


def _span_tails(per_thread: int = 4) -> List[dict]:
    from ..observability import tracer
    rows = []
    for tid, name, events, _dropped in tracer.snapshot_buffers():
        if events:
            rows.append({"thread": f"{name}({tid})",
                         "events": events[-per_thread:]})
    return rows


# -- watchdog ----------------------------------------------------------------

class Watchdog:
    """Deadline monitor over the ``step`` heartbeat.

    Default stall policy: render + log the :class:`StallReport`, run the
    registered emergency save (in a side thread, bounded by ``grace_s``),
    then ``os._exit(87)`` so the supervisor restarts from the last commit.
    Pass ``on_stall`` to fully replace that policy (tests; embedders).

    ``source`` picks which heartbeat gates the deadline (default ``"step"``
    for training loops; the serving engine arms one on ``"serving"`` so a
    wedged decode dispatch aborts the same way a wedged train step does).
    Non-gating sources still land in the report either way."""

    def __init__(self, deadline_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 on_stall: Optional[Callable[[StallReport], None]] = None,
                 grace_s: Optional[float] = None,
                 source: str = "step"):
        if deadline_s is None:
            raw = os.environ.get(ENV_DEADLINE, "")
            deadline_s = float(raw) if raw else None
        if deadline_s is None or deadline_s <= 0:
            raise ValueError(
                f"Watchdog needs a positive deadline (arg or {ENV_DEADLINE})")
        self.deadline_s = float(deadline_s)
        self.source = source
        self.poll_s = poll_s if poll_s is not None \
            else max(0.05, min(self.deadline_s / 4.0, 1.0))
        self.on_stall = on_stall
        if grace_s is None:
            grace_s = float(os.environ.get("MXTPU_WATCHDOG_GRACE_S", "20"))
        self.grace_s = grace_s
        self.stalled: Optional[StallReport] = None
        self._emergency: Optional[Callable[[], None]] = _pending_emergency
        self._lock = threading.Lock()
        self._beats: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t_start = 0.0
        self._prev_active: Optional["Watchdog"] = None

    # -- lifecycle --
    def start(self) -> "Watchdog":
        global _active
        if self._thread is not None:
            return self
        self._t_start = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._monitor,
                                        name="mxtpu-watchdog", daemon=True)
        # nested arming (a temporary "elastic" watchdog over a resize/drain
        # window while the per-step watchdog stays armed): remember who was
        # active so stop() restores them instead of leaving no watchdog
        self._prev_active = _active if _active is not self else None
        _active = self
        self._thread.start()
        return self

    def stop(self) -> None:
        global _active
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        if _active is self:
            _active = self._prev_active
        self._prev_active = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- inputs --
    def beat(self, source: str = "step") -> None:
        now = time.monotonic()
        with self._lock:
            self._beats[source] = now
            self._counts[source] = self._counts.get(source, 0) + 1

    def set_emergency(self, fn: Optional[Callable[[], None]]) -> None:
        self._emergency = fn

    # -- monitor --
    def _step_age(self) -> float:
        now = time.monotonic()
        with self._lock:
            last = self._beats.get(self.source, self._t_start)
        return now - last

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._step_age() > self.deadline_s:
                self._handle_stall()
                return  # one-shot: a stall ends this monitor

    def _build_report(self) -> StallReport:
        now = time.monotonic()
        with self._lock:
            beats = {src: {"count": self._counts.get(src, 0),
                           "age_s": now - t}
                     for src, t in self._beats.items()}
            if self.source not in beats:
                beats[self.source] = {"count": 0,
                                      "age_s": now - self._t_start}
        return StallReport(self.deadline_s, beats[self.source]["age_s"],
                           beats, _span_tails(), _thread_stacks())

    def _handle_stall(self) -> None:
        report = self._build_report()
        self.stalled = report
        from ..observability import flight, metrics, tracer
        metrics.record_resilience("watchdog_stalls")
        tracer.instant("resilience/stall", cat="resilience",
                       args={"waited_s": round(report.waited_s, 3),
                             "deadline_s": self.deadline_s})
        _log.error("%s", report.render())
        # postmortem bundle BEFORE any policy runs — on_stall may restart
        # the world and the default policy os._exit()s (dump() is a no-op
        # unless MXTPU_FLIGHT_DIR is set, and never raises)
        flight.record("stall", source=self.source,
                      waited_s=round(report.waited_s, 3))
        flight.dump("stall", extra=report.to_dict())
        if self.on_stall is not None:
            self.on_stall(report)
            return
        self._emergency_save()
        _log.error("watchdog: aborting with exit code %d", WATCHDOG_EXIT_CODE)
        logging.shutdown()
        os._exit(WATCHDOG_EXIT_CODE)

    def _emergency_save(self) -> None:
        fn = self._emergency
        if fn is None:
            return
        from ..observability import metrics
        done = threading.Event()

        def _run():
            try:
                fn()
                metrics.record_resilience("emergency_saves")
            except BaseException:  # mxtpu: ignore[R005] — the process is
                # about to os._exit(87); nothing may escape this thread
                _log.exception("watchdog: emergency save failed")
            finally:
                done.set()

        # the stalled thread might hold arbitrary locks — bound the save
        t = threading.Thread(target=_run, name="mxtpu-emergency-save",
                             daemon=True)
        t.start()
        if not done.wait(self.grace_s):
            _log.error("watchdog: emergency save did not finish in %.1fs",
                       self.grace_s)


_install_beacon_from_env()
