"""mxtpu — a TPU-native deep-learning framework with the capabilities of Apache MXNet.

Built from scratch on JAX/XLA/Pallas/pjit (SURVEY.md is the blueprint): the reference's
dependency engine, graph passes, and CUDA kernels collapse into XLA; the NCCL/ps-lite
KVStore becomes a collectives layer over ICI/DCN; the user-facing capability surface
(NDArray eager ops, autograd, Gluon-style modules, Module.fit, KVStore, data pipelines,
model zoo) is preserved.

Top-level layout mirrors the ``mx.*`` namespaces:

* ``mxtpu.nd`` — imperative NDArray ops (mx.nd)
* ``mxtpu.autograd`` — record/backward (mx.autograd)
* ``mxtpu.gluon`` — Block/HybridBlock/Trainer/data/model_zoo (mx.gluon)
* ``mxtpu.mod`` — Module API (mx.mod)
* ``mxtpu.io`` — data iterators (mx.io)
* ``mxtpu.kv`` — KVStore (mx.kvstore)
* ``mxtpu.parallel`` — device meshes, collectives, sharded training (TPU-first, new)
"""

import os as _os

# pod bring-up MUST precede any backend-initializing import (see mxtpu/dist.py);
# reference parity: ps-lite InitPSEnv runs at library load (kvstore.h:257)
if _os.environ.get("DMLC_NUM_WORKER", "1") not in ("", "0", "1"):
    from . import dist as _dist
    _dist.auto_initialize()

from .base import __version__
from . import base
from . import context
from .context import Context, cpu, cpu_pinned, current_context, device_mesh, gpu, num_devices, num_gpus, num_tpus, tpu
from . import rng
from . import ops
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray import NDArray

# subsystem imports (populated as the build proceeds; see SURVEY.md §7 build order)
import importlib as _importlib

_SUBSYSTEMS = ["initializer", "optimizer", "lr_scheduler", "metric", "callback",
               "io", "recordio", "kvstore", "symbol", "gluon", "module", "parallel",
               "profiler", "test_utils", "model", "image", "visualization",
               "contrib", "operator", "monitor", "rtc", "capi", "rnn",
               "attribute", "engine", "serving", "step_cache", "checkpoint",
               "device_feed", "analysis", "observability", "resilience",
               "quant"]
for _name in _SUBSYSTEMS:
    try:
        globals()[_name] = _importlib.import_module(f".{_name}", __name__)
    except ModuleNotFoundError as _e:
        if f"mxtpu.{_name}" not in str(_e):
            raise

if "kvstore" in globals():
    kv = globals()["kvstore"]
if "symbol" in globals():
    sym = globals()["symbol"]
    Symbol = sym.Symbol
if "module" in globals():
    mod = globals()["module"]
    Module = mod.Module
if "model" in globals():
    save_checkpoint = model.save_checkpoint
    load_checkpoint = model.load_checkpoint
if "attribute" in globals():
    AttrScope = attribute.AttrScope
