"""Framework PRNG state — the TPU-native take on the reference's random resources.

The reference gives ops per-device PRNG resources (``ResourceManager`` kRandom /
kParallelRandom, include/mxnet/resource.h:38-46) seeded by ``mx.random.seed``. JAX PRNG
is explicit-key, counter-based (threefry) — already the "parallel random" design — so the
framework keeps ONE global key per process and splits from it for every stochastic op.

Two modes:

* **Eager**: ``next_key()`` splits the global key — each imperative random op draws a
  fresh, reproducible stream.
* **Traced** (inside ``CachedOp``/hybridize tracing): a *key provider* is installed so
  ``next_key()`` yields keys split from a traced key argument. The trace counts how many
  keys it consumed; every subsequent call of the compiled function feeds a fresh key, so
  dropout/sampling differ per step exactly like the reference's random resource — without
  impure ops inside jit.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax

_state = threading.local()


def _global():
    if not hasattr(_state, "key"):
        _state.key = jax.random.key(0)
    return _state


def seed(seed_state: int):
    """Parity with ``mx.random.seed`` (python/mxnet/random.py)."""
    st = _global()
    st.key = jax.random.key(int(seed_state))
    st.trace_counter = 0     # seeded runs replay the foreign-jit stream too


def get_state_blob() -> dict:
    """Host-serializable PRNG state (checkpoint subsystem): the raw key data
    plus the foreign-jit fold counter. Restoring via ``set_state_blob``
    resumes the exact random stream — dropout/sampling after a restore match
    an uninterrupted run bit-for-bit."""
    import numpy as np
    st = _global()
    return {"key_data": np.asarray(jax.random.key_data(st.key)),
            "trace_counter": int(getattr(st, "trace_counter", 0))}


def set_state_blob(blob: dict):
    import jax.numpy as jnp
    st = _global()
    st.key = jax.random.wrap_key_data(jnp.asarray(blob["key_data"]))
    st.trace_counter = int(blob.get("trace_counter", 0))


class _TraceProvider:
    """Splits keys deterministically from one traced base key."""

    def __init__(self, base_key):
        self.base = base_key
        self.count = 0

    def next(self):
        k = jax.random.fold_in(self.base, self.count)
        self.count += 1
        return k


def push_trace_provider(base_key) -> "_TraceProvider":
    st = _global()
    if not hasattr(st, "providers"):
        st.providers = []
    p = _TraceProvider(base_key)
    st.providers.append(p)
    return p


def pop_trace_provider():
    _global().providers.pop()


def in_trace() -> bool:
    st = _global()
    return bool(getattr(st, "providers", None))


def next_key():
    st = _global()
    providers: List[_TraceProvider] = getattr(st, "providers", [])
    if providers:
        return providers[-1].next()
    new_key, sub = jax.random.split(st.key)
    if isinstance(sub, jax.core.Tracer):
        # an eager stochastic op is being traced by a FOREIGN jit (user code
        # wrapped framework calls in jax.jit without a trace provider).
        # Storing the traced split would poison the global key for every
        # later eager call — keep the global concrete and derive this trace's
        # keys by folding a counter instead (each such call gets a distinct,
        # deterministic stream; the compiled fn replays it, like the
        # reference replaying a seeded resource).
        st.trace_counter = getattr(st, "trace_counter", 0) + 1
        return jax.random.fold_in(st.key, st.trace_counter)
    st.key = new_key
    return sub
