"""Dispatch-amortized serving — the public form of the chained-forward trick.

Per-call inference pays one jit dispatch per forward; through a remote/tunnel
transport that dispatch has a fixed RPC floor (30-100 ms here) that can gate
small-batch serving far below the chip's real rate (measured: ResNet-50 b1
87 img/s per-call vs 589 chained, BENCH_r04). The reference has no equivalent
layer — its GPU sits on PCIe where per-call launch cost is microseconds; on a
disaggregated accelerator the amortization belongs IN the framework.

``ChainedPredictor`` compiles ONE program that scans over a stack of n
batches, so a chain of n forwards costs one dispatch + n compute steps.
``Module.predict(..., chain=n)`` uses it transparently.

Use the PLAIN (non-hybridized) block: a hybridized CachedOp draws rng keys at
its own trace time, which leaks tracers when traced inside the outer jit
(bench.py inference docstring records the same constraint).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..ndarray.ndarray import NDArray
from ..step_cache import ProgramCache

__all__ = ["ChainedPredictor"]


class ChainedPredictor:
    """Throughput serving over a single-input block.

    ``chain`` batches are stacked to ``(chain, B, ...)`` and one compiled
    ``lax.scan`` produces all outputs; programs are cached per
    (chain, batch shape, dtype) — a short tail chain compiles once more.
    The cache is a bounded LRU (``MXTPU_SERVING_PROGRAM_CACHE``) counted
    under ``serving_chained`` in ``profiler.get_compile_stats()``, so
    serving-side shape churn neither grows without limit nor hides from the
    retrace forensics.
    """

    def __init__(self, block, chain: int = 8):
        if chain < 1:
            raise ValueError("chain must be >= 1")
        if getattr(block, "_active", False):
            raise ValueError(
                "ChainedPredictor needs the PLAIN block: a hybridized "
                "CachedOp draws rng keys at its own trace time and leaks "
                "tracers inside the chain's jit — call "
                "block.hybridize(False) first")
        self._block = block
        self.chain = int(chain)
        self._fns = ProgramCache("serving_chained")

    def _fn(self, n: int, shape: Tuple[int, ...], dtype):
        key = (n,) + tuple(shape) + (str(dtype),)
        block = self._block

        def build():
            def run(stack):
                def step(carry, xb):
                    with autograd.predict_mode():
                        out = block(NDArray(xb))
                    outs = (tuple(o.data for o in out)
                            if isinstance(out, (tuple, list))
                            else (out.data,))
                    return carry, outs
                _, outs = lax.scan(step, jnp.zeros((), jnp.float32), stack)
                return outs
            return jax.jit(run)

        return self._fns.get_or_build(key, build)

    def predict_stack(self, stack) -> List[NDArray]:
        """(n, B, ...) stacked batches → list over outputs of (n, B, ...)."""
        raw = stack.data if isinstance(stack, NDArray) else jnp.asarray(stack)
        outs = self._fn(raw.shape[0], raw.shape[1:], raw.dtype)(raw)
        return [NDArray(o) for o in outs]

    def predict_batches(self, batches: Iterable) -> List[List[NDArray]]:
        """Consume an iterable of same-shape ``(B, ...)`` arrays; returns one
        ``[outputs...]`` list per input batch, in order. Dispatches once per
        ``chain`` batches (plus once for a shorter tail)."""
        results: List[List[NDArray]] = []
        buf: List = []

        def flush():
            if not buf:
                return
            raws = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                    for b in buf]
            stacked = jnp.stack(raws)
            outs = self.predict_stack(NDArray(stacked))
            for i in range(len(buf)):
                results.append([NDArray(o.data[i]) for o in outs])
            buf.clear()

        for b in batches:
            shape = tuple(b.shape)
            if buf and tuple(buf[0].shape) != shape:
                flush()                 # odd-shaped batch starts a new chain
            buf.append(b)
            if len(buf) == self.chain:
                flush()
        flush()
        return results
