"""Request objects and error surface for the serving engine.

A :class:`ServingRequest` is the handle ``ServingEngine.submit()`` returns:
the caller blocks on :meth:`result`, iterates :meth:`stream` for tokens as
they decode, or calls :meth:`cancel`. All cross-thread state lives behind
the request's own condition variable — the scheduler thread delivers tokens
and terminal states through :meth:`_emit`/:meth:`_finish`, submitters only
ever read.

Backpressure is explicit: a full admission queue raises
:exc:`QueueFullError` from ``submit()`` (recorded as ``rejected`` in
``get_serving_stats()``) instead of growing without bound — the caller
decides whether to shed, retry, or block.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

__all__ = ["ServingRequest", "SamplingParams", "ServingConfig",
           "QueueFullError", "RequestCancelled", "DeadlineExceeded",
           "ShedError", "HandoffMismatch", "TIERS",
           "PENDING", "RUNNING", "DONE", "CANCELLED", "EXPIRED", "SHED"]

PENDING = "pending"        # admitted to the queue, not yet prefilled
RUNNING = "running"        # occupying a decode slot (or mid-prefill)
DONE = "done"              # every requested token delivered
CANCELLED = "cancelled"    # caller cancelled (or the engine shut down)
EXPIRED = "expired"        # deadline passed before completion
SHED = "shed"              # SLO scheduler shed it BEFORE the deadline passed

_TERMINAL = frozenset({DONE, CANCELLED, EXPIRED, SHED})

# priority tiers of the SLO scheduler (mxtpu.sched.policy), ordered most- to
# least-latency-sensitive; a request's tier is static for its lifetime
TIERS = ("interactive", "standard", "batch")


class QueueFullError(RuntimeError):
    """Admission queue at capacity — the submit was rejected, not queued."""


class ShedError(RuntimeError):
    """The SLO scheduler (``mxtpu.sched``) shed this request under overload:
    its deadline was predicted unmeetable from the measured service rates, so
    it was rejected EARLY — before occupying a prefill cursor or decode slot
    and before the deadline actually passed — instead of burning capacity on
    work that would expire anyway. Distinct from :exc:`QueueFullError`
    (queue capacity, load-independent of deadlines) and from
    :exc:`DeadlineExceeded` (the deadline really passed)."""


class HandoffMismatch(ValueError):
    """``adopt()`` on a :class:`ServingHandoff` whose KV geometry or mesh
    placement is incompatible with the adopting engine — raised UP FRONT,
    before any page merges, naming the mismatched dimension (model cache
    rows, KV bucket page shapes, or mesh axis geometry) instead of letting
    a later ``kv.merge_page`` die on a shape crash mid-adoption."""


class RequestCancelled(RuntimeError):
    """result() on a request that was cancelled before completing."""


class DeadlineExceeded(RuntimeError):
    """result() on a request whose deadline passed before completing."""


_ids = itertools.count()


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode sampling knobs, riding the decode program as
    per-slot TRACED arrays (``TransformerLM.serving_sample``) — a mix
    change between dispatches never retraces.

    ``temperature == 0`` (the default) is greedy argmax, bit-exact with
    solo ``generate``; ``temperature > 0`` samples from the scaled,
    top-k-masked logits. ``top_k <= 0`` disables top-k truncation. The
    stream is deterministic per (seed, position): resubmitting the same
    request with the same seed reproduces the same tokens no matter how
    the scheduler slotted or chunked it."""
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0 (0 = greedy)")
        if self.seed < 0:
            raise ValueError("seed must be >= 0")


@dataclass(frozen=True)
class ServingConfig:
    """Engine configuration as one value (``ServingEngine(config=...)``) —
    the programmatic face of the ``MXTPU_SERVING_*`` env knobs, so a router
    or test can declare a whole deployment without touching the process
    environment. Resolution order per knob: explicit constructor kwarg >
    this config > env var > default; ``None`` fields defer down the chain.

    ``kv_dtype`` is the paged-KV storage dtype (e.g. ``'bfloat16'``; the
    once-dead ``kv.empty_cache(dtype=...)`` parameter, now plumbed
    end-to-end). ``quant`` selects low-precision execution — a
    :class:`~mxtpu.quant.serve.QuantSpec` or a token string like
    ``'int8_kv,int8_w'`` (see ``docs/quantization.md``). ``decode_kernel``
    pins the fused dequant-attention read of a quantized KV cache
    (``'pallas'``/``'xla'``; the ``MXTPU_DECODE_KERNEL`` knob — None defers
    down the chain to backend auto).

    ``sched`` installs the multi-tenant SLO control plane (``mxtpu.sched``):
    ``True`` for the default :class:`~mxtpu.sched.policy.SLOPolicy`, or a
    policy/scheduler instance; None keeps the plain FIFO engine
    byte-identical to before. ``prefill_batch`` (> 1, sched mode only)
    packs up to that many pending prompts into ONE batched prefill chunk
    program per scheduler turn (``mxtpu.sched.admission``).

    ``spec`` enables speculative multi-token decode — a
    :class:`~mxtpu.serving.spec.SpecConfig` or an integer draft depth
    ``k`` (the ``MXTPU_SPEC_DECODE`` knob; see ``docs/serving.md``). None
    keeps the engine byte-identical to the non-speculative path.

    ``mesh`` shards the engine over a ``parallel.mesh`` Mesh carrying
    ``fsdp``/``tp`` axes (``mxtpu.serving.sharded``); None is the
    single-device engine. ``engine_id`` names this engine in the exporter's
    ``{engine=...}`` metric label and in ``load()``/router telemetry
    (auto-minted ``engineN`` when unset)."""
    slots: Optional[int] = None
    queue_depth: Optional[int] = None
    chunk: Optional[int] = None
    prefill_chunk: Optional[int] = None
    prefix_cache_mb: Optional[float] = None
    stall_deadline_s: Optional[float] = None
    kv_dtype: Optional[str] = None
    quant: object = None
    decode_kernel: Optional[str] = None
    sched: object = None
    prefill_batch: Optional[int] = None
    spec: object = None
    mesh: object = None
    engine_id: Optional[str] = None


class ServingRequest:
    """One in-flight generation request.

    ``prompt`` is the token-id list, ``max_new`` the number of tokens to
    generate, ``deadline_s`` an optional completion budget measured from
    submit time (the engine retires the request as :data:`EXPIRED` at the
    first step boundary past it; partial tokens are kept), ``sampling``
    optional :class:`SamplingParams` (default greedy), and
    ``prefix_cache=False`` opts this request out of shared-prefix KV reuse
    AND of inserting its own prefix (for privacy-sensitive prompts that
    must not seed a cache other requests can hit). ``tenant`` names the
    submitting tenant (fair-share + per-tenant telemetry key) and
    ``priority`` its latency tier (one of :data:`TIERS`) — both are inert
    on a plain FIFO engine and drive admission order, preemption, and
    shedding when the SLO scheduler (``mxtpu.sched``) is installed."""

    def __init__(self, prompt, max_new: int,
                 deadline_s: Optional[float] = None,
                 sampling: Optional[SamplingParams] = None,
                 prefix_cache: bool = True,
                 tenant: str = "default", priority: str = "standard"):
        self.id = next(_ids)
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("empty prompt (give a BOS token for "
                             "unconditional generation)")
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        self.max_new = int(max_new)
        if sampling is not None and not isinstance(sampling, SamplingParams):
            sampling = SamplingParams(**dict(sampling))
        self.sampling = sampling
        self.use_prefix_cache = bool(prefix_cache)
        self.tenant = str(tenant)
        if priority not in TIERS:
            raise ValueError(f"priority must be one of {TIERS}, "
                             f"got {priority!r}")
        self.priority = priority
        self.t_submit = time.monotonic()
        self.deadline = None if deadline_s is None \
            else self.t_submit + float(deadline_s)
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.state = PENDING
        self.error: Optional[BaseException] = None
        self._tokens: List[int] = []
        self._cancel = False
        self._cond = threading.Condition()

    # -- caller side --------------------------------------------------------
    @property
    def total(self) -> int:
        return len(self.prompt) + self.max_new

    def done(self) -> bool:
        with self._cond:
            return self.state in _TERMINAL

    def cancel(self) -> None:
        """Ask the engine to drop this request at the next step boundary
        (immediately if still queued). Idempotent; a no-op once terminal."""
        with self._cond:
            self._cancel = True
            self._cond.notify_all()

    def tokens(self) -> List[int]:
        """Generated tokens delivered so far (prompt excluded)."""
        with self._cond:
            return list(self._tokens)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; returns the full generated-token list.
        Raises :exc:`RequestCancelled` / :exc:`DeadlineExceeded` (carrying
        any partial tokens on ``.args[1]``) for the non-DONE terminals, and
        ``TimeoutError`` if ``timeout`` elapses first."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self.state not in _TERMINAL:
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"request {self.id} not finished in {timeout}s")
                self._cond.wait(timeout=left)
            if self.state == DONE:
                return list(self._tokens)
            if self.error is not None:
                raise self.error
            if self.state == CANCELLED:
                raise RequestCancelled(
                    f"request {self.id} cancelled", list(self._tokens))
            raise DeadlineExceeded(
                f"request {self.id} missed its deadline", list(self._tokens))

    def stream(self, timeout: Optional[float] = None):
        """Yield generated tokens as the engine delivers them; returns when
        the request goes terminal (raising like :meth:`result` for the
        non-DONE terminals). ``timeout`` bounds each wait for the NEXT
        token, not the whole stream."""
        seen = 0
        while True:
            with self._cond:
                if seen == len(self._tokens) and self.state not in _TERMINAL:
                    if not self._cond.wait(timeout=timeout):
                        raise TimeoutError(
                            f"request {self.id}: no token in {timeout}s")
                fresh = self._tokens[seen:]
                state = self.state
                err = self.error
            for t in fresh:
                seen += 1
                yield t
            if state in _TERMINAL and seen == len(self.tokens()):
                if state == CANCELLED:
                    raise RequestCancelled(
                        f"request {self.id} cancelled", self.tokens())
                if state == EXPIRED:
                    raise DeadlineExceeded(
                        f"request {self.id} missed its deadline",
                        self.tokens())
                if err is not None:
                    raise err
                return

    # -- engine (scheduler-thread) side -------------------------------------
    def _cancelled(self) -> bool:
        with self._cond:
            return self._cancel

    def _expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def _emit(self, toks, now: float) -> int:
        """Deliver generated tokens (capped at ``max_new``); returns how
        many the request still wants after this delivery."""
        with self._cond:
            room = self.max_new - len(self._tokens)
            fresh = [int(t) for t in toks[:room]]
            if fresh and self.t_first_token is None:
                self.t_first_token = now
            self._tokens.extend(fresh)
            remaining = self.max_new - len(self._tokens)
            self._cond.notify_all()
        return remaining

    def _finish(self, state: str, now: float,
                error: Optional[BaseException] = None) -> None:
        with self._cond:
            if self.state in _TERMINAL:
                return
            self.state = state
            self.error = error
            self.t_done = now
            n_tokens = len(self._tokens)
            self._cond.notify_all()
        # one-line summary into the flight recorder's last-N ring (outside
        # _cond — the recorder has its own lock) so a postmortem bundle
        # shows what the engine finished right before dying
        from ..observability import flight
        flight.note_request({
            "id": self.id, "state": state,
            "prompt": len(self.prompt), "max_new": self.max_new,
            "tokens": n_tokens,
            "ttft_ms": None if self.t_first_token is None
            else round((self.t_first_token - self.t_submit) * 1e3, 3),
            "total_ms": round((now - self.t_submit) * 1e3, 3),
            "error": repr(error) if error is not None else None})

    def _set_state(self, state: str) -> None:
        with self._cond:
            if self.state not in _TERMINAL:
                self.state = state
