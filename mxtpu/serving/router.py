"""Multi-replica serving router: least-loaded + prefix-affinity admission
over N :class:`~mxtpu.serving.engine.ServingEngine` replicas, with live
zero-drop rebalancing.

The router is a thin control plane OVER engines, never inside one: it
places whole requests, and every signal it reads (``engine.load()``, the
exporter counters) is a lock-free snapshot — a routing decision can never
block a replica's decode turn (the tpulint R010 contract). Replicas are
in-process engines here; a multi-process deployment keeps the same shape by
pointing each :class:`Replica`'s ``load_fn`` at the remote process's
metrics exporter (PR 15's ``/metrics`` JSON carries ``serving.engine`` +
the queue gauges) and rendezvousing the processes over the
``mxtpu.dist`` Transport seam — the router logic is identical, only the
two callables change.

Routing, in decision order:

1. **Prefix affinity** — requests whose prompt carries at least one full
   32-token block hash that first block (``zlib.crc32``) and rendezvous-hash
   it across replica ids, so all requests sharing a prompt prefix land on
   the replica whose radix prefix cache already holds those KV rows.
   Rendezvous (highest-random-weight) hashing keeps the map minimal-motion:
   removing a replica only remaps the keys that lived there.
2. **Headroom spill** — an affinity target already loaded past
   ``MXTPU_ROUTER_HEADROOM`` of its capacity forfeits the request to the
   least-loaded replica (cache warmth never justifies queueing behind a hot
   spot).
3. **Least-loaded** — everything else goes to the replica with the lowest
   ``in_flight / slots`` ratio.
4. **Backpressure** — a :class:`QueueFullError` from the chosen replica
   moves the request to the next candidate instead of failing the caller;
   only when EVERY replica is full does ``submit()`` re-raise.

Rebalancing rides the engines' drain/adopt handoff:

* :meth:`Router.rebalance` — drain a replica, build a fresh engine (same
  geometry), ``adopt()`` the handoff, swap it in. The in-flight
  :class:`ServingRequest` handles cross unchanged; callers blocked in
  ``result()`` never notice.
* :meth:`Router.remove_replica` — drain a replica and RE-ROUTE its live
  requests to survivors: each becomes a continuation (original prompt +
  tokens already emitted, remaining ``max_new``, remaining deadline, same
  tenant/priority/sampling) spliced behind the caller's
  :class:`RouterRequest` handle. Greedy decode is a pure function of the
  token prefix and sampling is deterministic per (seed, position), so the
  spliced stream is bit-exact with an uninterrupted run — zero drops
  (``get_router_stats()['requests_dropped'] == 0``), asserted by the
  chaos test in ``tests/test_router_guard.py``.

With the SLO scheduler installed on the replicas, the router periodically
merges the per-tenant fair-share passes across replicas (max per tenant),
so a tenant flooding replica A cannot start fresh at the pass floor on
replica B.

Knobs: ``MXTPU_ROUTER_AFFINITY`` (default 1), ``MXTPU_ROUTER_HEADROOM``
(default 0.75 of slots+queue), ``MXTPU_ROUTER_FAIRSYNC_N`` (default 16
submissions per sync). See ``docs/serving.md``.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

from .. import profiler
from ..observability import tracer
from .api import (CANCELLED, DONE, EXPIRED, QueueFullError, RequestCancelled,
                  ServingRequest)

__all__ = ["Router", "Replica", "RouterRequest"]


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class Replica:
    """One routing target: an engine plus its load signal. ``load_fn``
    defaults to the in-process ``engine.load()``; a remote replica swaps in
    a closure that scrapes the process's metrics exporter instead — the
    router treats both identically (it only reads the returned dict)."""

    __slots__ = ("rid", "engine", "load_fn", "draining")

    def __init__(self, engine, rid: Optional[str] = None,
                 load_fn: Optional[Callable[[], dict]] = None):
        self.rid = rid or engine.engine_id
        self.engine = engine
        self.load_fn = load_fn
        self.draining = False

    def load(self) -> dict:
        return self.load_fn() if self.load_fn is not None \
            else self.engine.load()

    def pressure(self) -> float:
        """in_flight normalized by decode capacity — the least-loaded key."""
        ld = self.load()
        return ld["in_flight"] / max(1, ld["slots"])

    def headroom_ok(self, frac: float) -> bool:
        """Whether this replica is below ``frac`` of its total admission
        capacity (slots + queue) — the affinity-spill gate."""
        ld = self.load()
        cap = ld["slots"] + ld.get("queue_depth", 0)
        return ld["in_flight"] < frac * max(1, cap)


class RouterRequest:
    """The caller-facing handle for a routed request: proxies the live
    :class:`ServingRequest` segment and splices continuations across
    replica removal, so ``result()``/``tokens()`` always present ONE
    uninterrupted stream. The caller never sees which replica (or how many,
    after a rebalance) served it."""

    def __init__(self, prompt, max_new: int, deadline_s, sampling,
                 prefix_cache: bool, tenant: str, priority: str):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.sampling = sampling
        self.use_prefix_cache = bool(prefix_cache)
        self.tenant = tenant
        self.priority = priority
        self.deadline = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        self._lock = threading.Lock()
        self._prefix_tokens: List[int] = []   # emitted by superseded segments
        self._seg: Optional[ServingRequest] = None
        self._gen = 0                         # bumped at every splice

    # -- router side --------------------------------------------------------
    def _attach(self, seg: ServingRequest) -> None:
        with self._lock:
            self._seg = seg
            self._gen += 1

    def _splice(self, emitted: List[int], seg: ServingRequest) -> None:
        """Swap in a continuation segment; ``emitted`` is what the drained
        segment had already delivered (frozen — its engine is stopped)."""
        with self._lock:
            self._prefix_tokens.extend(emitted)
            self._seg = seg
            self._gen += 1

    def _segment(self):
        with self._lock:
            return self._seg, self._gen

    # -- caller side --------------------------------------------------------
    @property
    def id(self) -> int:
        return self._seg.id

    def tokens(self) -> List[int]:
        with self._lock:
            seg, prefix = self._seg, list(self._prefix_tokens)
        return prefix + (seg.tokens() if seg is not None else [])

    def done(self) -> bool:
        seg, _ = self._segment()
        return seg is not None and seg.done()

    def cancel(self) -> None:
        seg, _ = self._segment()
        if seg is not None:
            seg.cancel()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal across any number of splices; returns the
        full generated-token list. Raises like ``ServingRequest.result``,
        with partial tokens spanning every segment on ``.args[1]``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seg, gen = self._segment()
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                raise TimeoutError(f"request not finished in {timeout}s")
            try:
                toks = seg.result(timeout=left)
            except RequestCancelled:
                if self._segment()[1] != gen:
                    continue          # superseded mid-wait: follow the splice
                raise RequestCancelled("request cancelled", self.tokens())
            except Exception as e:
                if self._segment()[1] != gen:
                    continue
                if len(e.args) > 1 and isinstance(e.args[1], list):
                    e.args = (e.args[0], self.tokens()) + e.args[2:]
                raise
            if self._segment()[1] != gen:
                continue              # spliced between result and here
            with self._lock:
                return list(self._prefix_tokens) + toks


class Router:
    """Admission router over N serving replicas (see module docstring)."""

    def __init__(self, engines, factory: Optional[Callable] = None,
                 affinity: Optional[bool] = None,
                 headroom: Optional[float] = None,
                 fair_sync_every: Optional[int] = None):
        reps = [e if isinstance(e, Replica) else Replica(e) for e in engines]
        if not reps:
            raise ValueError("Router needs at least one replica")
        if len({r.rid for r in reps}) != len(reps):
            raise ValueError("replica ids must be unique "
                             "(pass engine_id= at engine construction)")
        self._replicas: Dict[str, Replica] = {r.rid: r for r in reps}
        self._factory = factory
        self._affinity = (affinity if affinity is not None
                          else bool(_env_int("MXTPU_ROUTER_AFFINITY", 1)))
        self._headroom = (headroom if headroom is not None
                          else _env_float("MXTPU_ROUTER_HEADROOM", 0.75))
        self._fair_sync_every = (
            fair_sync_every if fair_sync_every is not None
            else _env_int("MXTPU_ROUTER_FAIRSYNC_N", 16))
        self._lock = threading.Lock()
        # rid -> {segment request id -> RouterRequest}: which handle to
        # re-route when a replica is removed mid-flight
        self._inflight: Dict[str, Dict[int, RouterRequest]] = \
            {r.rid: {} for r in reps}
        self._since_sync = 0
        profiler.record_router("replicas", len(self._replicas))

    # -- factory convenience -------------------------------------------------
    @classmethod
    def local(cls, factory: Callable, n: int, **kw) -> "Router":
        """Build an N-replica in-process router from an engine factory.
        ``factory(rid)`` must return a STOPPED engine constructed with
        ``engine_id=rid`` (so the exporter label and the router id agree)."""
        engines = [factory(f"replica{i}") for i in range(n)]
        return cls(engines, factory=factory, **kw)

    # -- introspection -------------------------------------------------------
    @property
    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def stats(self) -> dict:
        return profiler.get_router_stats()

    def loads(self) -> Dict[str, dict]:
        with self._lock:
            reps = list(self._replicas.values())
        return {r.rid: r.load() for r in reps}

    # -- routing -------------------------------------------------------------
    BLOCK = 32      # affinity hashes the first full radix block

    def _affinity_rid(self, prompt, prefix_cache: bool,
                      rids: List[str]) -> Optional[str]:
        if not self._affinity or not prefix_cache \
                or len(prompt) < self.BLOCK:
            return None
        block = bytes(b"".join(int(t).to_bytes(4, "little", signed=True)
                               for t in prompt[:self.BLOCK]))
        key = zlib.crc32(block)
        # rendezvous: every (key, rid) pair scores independently, so a
        # removed replica only remaps its own keys
        return max(rids, key=lambda r: zlib.crc32(
            f"{key}:{r}".encode("ascii")))

    def _route(self, prompt, prefix_cache: bool) -> List[str]:
        """Candidate replica ids, best first, with the routing decision
        recorded: affinity target (when warm and with headroom), then the
        rest by ascending load pressure."""
        with self._lock:
            reps = {rid: r for rid, r in self._replicas.items()
                    if not r.draining}
        if not reps:
            raise RuntimeError("no live replicas")
        by_load = sorted(reps, key=lambda rid: reps[rid].pressure())
        aff = self._affinity_rid(prompt, prefix_cache, sorted(reps))
        if aff is None:
            profiler.record_router("routed_least_loaded")
            return by_load
        if not reps[aff].headroom_ok(self._headroom) and len(reps) > 1:
            profiler.record_router("routed_spill")
            return [r for r in by_load if r != aff] + [aff]
        profiler.record_router("routed_affinity")
        return [aff] + [r for r in by_load if r != aff]

    def submit(self, prompt, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               sampling=None, prefix_cache: bool = True,
               tenant: str = "default",
               priority: str = "standard") -> RouterRequest:
        """Route one generation request; returns its :class:`RouterRequest`
        handle. Raises :exc:`QueueFullError` only when EVERY replica's
        admission queue is full."""
        rr = RouterRequest(prompt, max_new_tokens, deadline_s, sampling,
                           prefix_cache, tenant, priority)
        profiler.record_router("submitted")
        self._maybe_sync_fair_share()
        err: Optional[BaseException] = None
        for rid in self._route(prompt, prefix_cache):
            try:
                self._submit_to(rr, rid, prompt, max_new_tokens, deadline_s)
                return rr
            except QueueFullError as e:
                profiler.record_router("overflow")
                err = e
            except RuntimeError as e:
                # replica started draining between _route and submit —
                # the rebalance window; fall through to the next candidate
                err = e
        profiler.record_router("rejected")
        raise err if isinstance(err, QueueFullError) else QueueFullError(
            f"all {len(self.replica_ids)} replicas unavailable: {err}")

    def _submit_to(self, rr: RouterRequest, rid: str, prompt,
                   max_new: int, deadline_s) -> None:
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.draining:
                raise RuntimeError(f"replica {rid} is gone")
        seg = rep.engine.submit(prompt, max_new, deadline_s=deadline_s,
                                sampling=rr.sampling,
                                prefix_cache=rr.use_prefix_cache,
                                tenant=rr.tenant, priority=rr.priority)
        rr._attach(seg)
        with self._lock:
            book = self._inflight.setdefault(rid, {})
            book[seg.id] = rr
            if len(book) > 4 * rep.engine.slots:
                for sid in [s for s, h in book.items() if h.done()]:
                    del book[sid]
        tracer.instant("router/route", cat="serving",
                       args={"id": seg.id, "replica": rid})

    # -- cross-replica fair share -------------------------------------------
    def _maybe_sync_fair_share(self) -> None:
        with self._lock:
            self._since_sync += 1
            if self._since_sync < self._fair_sync_every:
                return
            self._since_sync = 0
        self.sync_fair_share()

    def sync_fair_share(self) -> None:
        """Merge per-tenant fair-share passes across replica schedulers
        (max per tenant -> loaded into every replica), so a tenant's
        consumption on one replica counts against it everywhere. No-op
        unless at least two replicas run the SLO scheduler."""
        with self._lock:
            scheds = [r.engine._sched for r in self._replicas.values()
                      if getattr(r.engine, "_sched", None) is not None]
        if len(scheds) < 2:
            return
        merged: Dict[str, float] = {}
        for s in scheds:
            for t, p in s.export_state()["pass"].items():
                merged[t] = max(merged.get(t, p), p)
        for s in scheds:
            s.load_state({"pass": merged})
        profiler.record_router("fair_share_syncs")

    # -- live rebalancing ----------------------------------------------------
    def rebalance(self, rid: str,
                  factory: Optional[Callable] = None) -> None:
        """Swap replica ``rid``'s engine for a fresh one via drain/adopt
        (e.g. after an elastic mesh change): the in-flight handles cross
        unchanged, callers blocked in ``result()`` never notice, zero
        drops."""
        factory = factory or self._factory
        if factory is None:
            raise ValueError("rebalance needs an engine factory "
                             "(Router(..., factory=...) or pass one here)")
        with self._lock:
            rep = self._replicas[rid]
            rep.draining = True
        try:
            with tracer.span("router/rebalance", cat="serving",
                             args={"replica": rid}):
                handoff = rep.engine.drain()
                fresh = factory(rid)
                fresh.adopt(handoff)
                with self._lock:
                    rep.engine = fresh
        finally:
            rep.draining = False
        profiler.record_router("rebalanced")

    def add_replica(self, engine, rid: Optional[str] = None,
                    load_fn: Optional[Callable[[], dict]] = None) -> str:
        rep = Replica(engine, rid=rid, load_fn=load_fn)
        with self._lock:
            if rep.rid in self._replicas:
                raise ValueError(f"replica id {rep.rid!r} already routed")
            self._replicas[rep.rid] = rep
            self._inflight.setdefault(rep.rid, {})
            profiler.record_router("replicas", len(self._replicas))
        return rep.rid

    def remove_replica(self, rid: str) -> int:
        """Drain replica ``rid`` and re-route every live request to a
        survivor as a bit-exact continuation (see module docstring);
        returns how many requests were re-routed. The zero-drop contract:
        ``requests_dropped`` stays 0 — a request is only lost if every
        survivor rejects its continuation, which the counter would expose."""
        with self._lock:
            if len(self._replicas) < 2:
                raise ValueError("cannot remove the last replica")
            rep = self._replicas.pop(rid)
            book = self._inflight.pop(rid, {})
            profiler.record_router("replicas", len(self._replicas))
        with tracer.span("router/remove_replica", cat="serving",
                         args={"replica": rid}):
            handoff = rep.engine.drain()
            moved = 0
            frozen = ([e["req"] for e in handoff.entries]
                      + [e["req"] for e in handoff.partial]
                      + [e["req"] for e in handoff.parked]
                      + list(handoff.pending))
            for old in frozen:
                rr = book.get(old.id)
                if rr is None:
                    # submitted straight to the engine, not via this
                    # router: nothing to splice onto — the caller holds
                    # the raw handle and the drain already froze it
                    profiler.record_router("requests_dropped")
                    old._finish(CANCELLED, time.monotonic())
                    continue
                self._reroute(rr, old)
                moved += 1
        profiler.record_router("replicas_removed")
        return moved

    def _reroute(self, rr: RouterRequest, old: ServingRequest) -> None:
        """Re-submit one drained request to a survivor as a continuation:
        prompt + emitted tokens, remaining budget, remaining deadline, same
        tenant/priority/sampling. Splice-then-finish ordering matters — the
        splice bumps the handle's generation BEFORE the old segment is
        finished, so a caller woken by the finish follows the splice."""
        now = time.monotonic()
        emitted = old.tokens()       # old's contribution (engine stopped)
        all_tokens = rr.tokens()     # earlier splices + old's contribution
        remaining = rr.max_new - len(all_tokens)
        if remaining <= 0:           # drained at the finish line
            rr._splice([], old)
            old._finish(DONE, now)
            return
        if rr.deadline is not None and now >= rr.deadline:
            rr._splice([], old)      # expired while draining: not a drop
            old._finish(EXPIRED, now)
            return
        deadline_s = None if rr.deadline is None else rr.deadline - now
        cont_prompt = rr.prompt + all_tokens
        err: Optional[BaseException] = None
        for rid in self._route(cont_prompt, rr.use_prefix_cache):
            try:
                with self._lock:
                    rep = self._replicas[rid]
                    if rep.draining:
                        continue
                seg = rep.engine.submit(
                    cont_prompt, remaining, deadline_s=deadline_s,
                    sampling=rr.sampling, prefix_cache=rr.use_prefix_cache,
                    tenant=rr.tenant, priority=rr.priority)
            except (QueueFullError, RuntimeError) as e:
                err = e
                continue
            rr._splice(emitted, seg)
            old._finish(CANCELLED, now)      # unblock pre-splice waiters
            with self._lock:
                self._inflight.setdefault(rid, {})[seg.id] = rr
            profiler.record_router("requests_rebalanced")
            tracer.instant("router/reroute", cat="serving",
                           args={"from": old.id, "to": seg.id,
                                 "replica": rid,
                                 "emitted": len(emitted)})
            return
        profiler.record_router("requests_dropped")
        old._finish(CANCELLED, now,
                    error=QueueFullError(
                        f"no survivor could adopt request {old.id}: {err}"))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Router":
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            r.engine.start()
        return self

    def stop(self) -> None:
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            r.engine.stop()

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
