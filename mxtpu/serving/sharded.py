"""Model-parallel serving: fsdp×tp placement for :class:`ServingEngine`.

``ServingEngine(mesh=...)`` runs the SAME compiled prefill-chunk / decode /
verify programs sharded over a composed mesh (SNIPPETS [2]'s pitch: one
NamedSharding program from 8 chips to a supercluster). This module is the
placement table and the placement helpers; the engine calls them at
parameter materialization, cache creation/promotion, and page merge, and
opens ``fsdp.layout_scope`` around every dispatch so the step functions'
activation constraints fire while the program traces.

The layout — :class:`ServingLayout` — is the serving-specialized row of the
:class:`~mxtpu.parallel.fsdp.SpecLayout` table:

* **Column-parallel stays sharded**: q/k/v and ffn-up weights on ``tp``
  (dim 0, the gluon ``(out, in)`` convention), the embedding table on
  ``fsdp×tp`` over vocab rows, and the paged KV cache on ``tp`` over heads
  + ``fsdp`` over slots. Attention (per-head einsums), the qkv/ffn-up
  projections, and the tied-head logits all contract over UNSHARDED dims —
  every device computes full local dot products over its output columns.
* **Row-parallel goes replicated**: the base table's Megatron pair
  (``attn_out``/``ffn_down`` sharded on dim 1) would make XLA compute
  ``ctx @ ow.T`` as per-device partial sums + psum, changing the
  floating-point reduction order (the exact hazard
  ``fsdp.compose_spec``'s docstring documents for training). Serving's
  contract is stronger than training's: greedy decode must be BIT-EXACT vs
  the single-device engine. So ``ow``/``f2w`` replicate, and the step
  functions constrain the compact ``(S, U)`` activations back to the
  data-axes spec before each row matmul — an all-gather moves identical
  bytes, a psum re-rounds them.

With that layout every floating-point reduction in the forward runs over
an unsharded dim on one device, so sharded greedy decode is bit-exact by
construction, not by luck — the property ``tests/test_sharded_guard.py``
asserts against the single-device engine.

What composes: int8 KV (the :class:`~mxtpu.quant.kv_quant.QuantKV` data and
scale leaves shard congruently — same head/slot axes), the radix prefix
cache (host block round-trips gather/scatter through the placed pages),
speculative decode (the verify step carries the same constraints), and the
SLO scheduler (parked pages re-place on merge). What refuses: the Pallas
fused dequant-attention read (``decode_kernel='pallas'``) — a
``pallas_call`` body is opaque to GSPMD partitioning, so a sharded engine
pins the ``xla`` read and an explicit pallas request raises
:class:`ShardingUnsupported` instead of silently tracing a gather.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax

from ..parallel.fsdp import SpecLayout, filter_spec, scale_spec
from ..parallel.mesh import Mesh, NamedSharding, P

__all__ = ["ServingLayout", "ShardingUnsupported", "serving_param_specs",
           "place_params", "place_cache", "mesh_fingerprint",
           "validate_mesh", "pin_decode_kernel"]


class ShardingUnsupported(ValueError):
    """A serving feature that cannot compose with a sharded engine (named
    refusal, never a mid-dispatch shape crash)."""


@dataclass(frozen=True)
class ServingLayout(SpecLayout):
    """Bit-exact serving specialization of the SpecLayout table: the
    row-parallel Megatron pair replicates (see module docstring — replicated
    row matmuls + all-gathered activations keep every float reduction
    local), everything column-parallel inherits the base table."""

    def attn_out(self) -> P:
        return P()                       # replicated: no psum in ctx @ ow.T

    def ffn_down(self) -> P:
        return P()                       # replicated: no psum in g @ f2w.T

    def kv_cache(self) -> P:
        """(L, 2, S, H, TOT, D) paged KV (and its rank-5 QuantKV scale):
        slots over fsdp, heads over tp — each (slot, head) shard attends
        its own rows with no cross-device reduction."""
        return P(None, None, self.fsdp_axis, self.tp_axis)


# -- per-leaf spec table ------------------------------------------------------

def _entry(name: str, layout: SpecLayout) -> P:
    """SpecLayout entry for one ``_gen_params`` / ``quantize_lm`` leaf by
    name: ``<w>_q`` inherits the fp32 weight's spec, ``<w>_s`` its
    output-channel :func:`~mxtpu.parallel.fsdp.scale_spec`."""
    if name.endswith("_q"):
        return _entry(name[:-2], layout)
    if name.endswith("_s"):
        return scale_spec(_entry(name[:-2], layout))
    if name in ("embed", "head_w"):
        return layout.embeddings()
    if name in ("qw", "kw", "vw"):
        return layout.qkv_projection()
    if name == "ow":
        return layout.attn_out()
    if name == "f1w":
        return layout.ffn_up()
    if name == "f2w":
        return layout.ffn_down()
    return layout.vector()               # biases, norms, pos table


def serving_param_specs(params: dict, layout: Optional[SpecLayout] = None):
    """The spec pytree matching a serving params pytree (fp32 or
    ``quantize_lm``'d), same nesting, one :class:`PartitionSpec` per leaf."""
    layout = layout or ServingLayout()
    out = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = [{n: _entry(n, layout) for n in lp} for lp in v]
        else:
            out[k] = _entry(k, layout)
    return out


def _place(leaf, spec: P, mesh: Mesh):
    return jax.device_put(
        leaf, NamedSharding(mesh, filter_spec(spec, leaf.shape, mesh)))


def place_params(params: dict, mesh: Mesh,
                 layout: Optional[SpecLayout] = None) -> dict:
    """Device-put every params leaf onto its mesh-filtered table spec —
    non-divisible dims degrade to replicated (``fsdp.filter_spec``), so the
    tiny presets and the flagship share one placement path."""
    layout = layout or ServingLayout()
    out = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = [{n: _place(w, _entry(n, layout), mesh)
                       for n, w in lp.items()} for lp in v]
        else:
            out[k] = _place(v, _entry(k, layout), mesh)
    return out


def place_cache(caches, mesh: Mesh, layout: Optional[SpecLayout] = None):
    """Pin a KV cache (raw array or :class:`QuantKV`) onto the canonical
    ``kv_cache`` sharding. The engine re-pins after every host-side cache
    mutation (create / promote / page merge) so the dispatch-input sharding
    never drifts from what the first trace keyed on — the trace-once
    contract extended to shardings."""
    layout = layout or ServingLayout()
    spec = layout.kv_cache()
    from ..quant.kv_quant import QuantKV
    if isinstance(caches, QuantKV):
        return QuantKV(_place(caches.data, spec, mesh),
                       _place(caches.scale, spec, mesh), caches.mode)
    return _place(caches, spec, mesh)


# -- mesh validation / identity ----------------------------------------------

def mesh_fingerprint(mesh: Optional[Mesh]):
    """Hashable mesh identity for handoff compatibility checks: the sorted
    (axis, size) pairs, or None for a single-device engine. Two engines can
    exchange a :class:`ServingHandoff` only when fingerprints match —
    pages drained from a sharded cache re-place onto the SAME axis
    geometry or not at all (see ``ServingEngine.adopt``)."""
    if mesh is None:
        return None
    return tuple(sorted((str(a), int(mesh.shape[a]))
                        for a in mesh.axis_names))


def validate_mesh(mesh: Mesh, layout: Optional[SpecLayout] = None) -> None:
    """Up-front refusal for a mesh the serving layout can't use at all: a
    mesh carrying neither the tp nor the fsdp axis would replicate every
    leaf — a silent single-device engine that LOOKS sharded. Raise
    :class:`ShardingUnsupported` instead."""
    layout = layout or ServingLayout()
    names = set(mesh.axis_names)
    if layout.tp_axis not in names and layout.fsdp_axis not in names:
        raise ShardingUnsupported(
            f"mesh axes {tuple(mesh.axis_names)} carry neither "
            f"'{layout.tp_axis}' nor '{layout.fsdp_axis}' — the serving "
            "layout would replicate every tensor; build the mesh with "
            "make_mesh((fsdp, tp), ('fsdp', 'tp'))")


def audit_layout_invariants(layout: Optional[SpecLayout] = None):
    """The PR 19 bit-exactness precondition as data, for the program auditor
    (rule A104): the Megatron row-parallel pair MUST replicate under a
    serving layout — sharding either contraction dim turns ``ctx @ ow.T`` /
    ``g @ f2w.T`` into per-device partial sums + psum, which reorders the
    float reduction and silently breaks token parity with solo ``generate``
    while every shape check stays green.  Returns the violating
    ``(entry, actual spec)`` pairs (empty == invariant holds)."""
    layout = layout or ServingLayout()
    bad = []
    for entry in ("attn_out", "ffn_down"):
        spec = getattr(layout, entry)()
        if tuple(spec) != ():
            bad.append((entry, spec))
    return bad


def pin_decode_kernel(mode: Optional[str]) -> str:
    """Resolve the quantized attention-read kernel for a sharded engine:
    the Pallas fused read is refused (its kernel body is opaque to GSPMD —
    sharding it would force a full cache gather per dispatch), auto pins
    ``xla`` so a TPU backend never auto-selects pallas under a mesh."""
    if mode == "pallas":
        raise ShardingUnsupported(
            "decode_kernel='pallas' cannot run sharded: the fused "
            "dequant-attention pallas_call is opaque to GSPMD partitioning. "
            "Use decode_kernel='xla' (or leave unset — sharded engines pin "
            "it) for mesh serving")
    return "xla"
