"""mxtpu.serving — the online serving subsystem.

Two serving modes, one package:

* **Offline / throughput** — :class:`ChainedPredictor` (the original
  ``mxtpu/serving.py`` surface, unchanged): one compiled scan over a stack
  of pre-collected batches, amortizing the per-call dispatch floor.
* **Online / latency** — :class:`ServingEngine`: continuous batching over a
  fixed slot batch with bucketed KV admission, decode-overlapped chunked
  prefill, shared-prefix radix KV reuse, per-request
  :class:`SamplingParams`, deadlines, cancellation, and explicit
  backpressure. ``submit()`` from any thread; greedy output is bit-exact
  with per-request ``TransformerLM.generate``.

See ``docs/serving.md`` for architecture, knobs, and the latency/goodput
methodology behind ``bench.py serving``.
"""

from .api import (CANCELLED, DONE, EXPIRED, PENDING, RUNNING, SHED, TIERS,
                  DeadlineExceeded, HandoffMismatch, QueueFullError,
                  RequestCancelled, SamplingParams, ServingConfig,
                  ServingRequest, ShedError)
from .chained import ChainedPredictor
from .engine import ServingEngine, ServingHandoff
from .router import Replica, Router, RouterRequest
from .spec import Drafter, ModelDrafter, NgramDrafter, SpecConfig
from . import kv

__all__ = ["ChainedPredictor", "ServingEngine", "ServingHandoff",
           "ServingRequest", "SamplingParams", "ServingConfig",
           "Router", "Replica", "RouterRequest",
           "SpecConfig", "Drafter", "NgramDrafter", "ModelDrafter",
           "QueueFullError", "RequestCancelled", "DeadlineExceeded",
           "ShedError", "HandoffMismatch", "TIERS",
           "PENDING", "RUNNING", "DONE", "CANCELLED", "EXPIRED", "SHED",
           "kv"]
