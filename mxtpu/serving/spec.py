"""Speculative multi-token decode — the draft side of draft-and-verify.

The serving engine's speculative path splits one decode turn into two
halves with an exact greedy contract between them:

* **draft** (this module, host-side) — a :class:`Drafter` proposes up to
  ``k`` continuation tokens per slot from cheap n-gram statistics; a miss
  proposes nothing and the slot runs a plain decode step inside the same
  compiled verify program (``dlen == 0``), so drafting can never stall or
  retrace the engine.
* **verify** (``kv.build_verify``, on-device) — ONE batched target forward
  scores all ``k + 1`` positions per slot; the accepted prefix is exactly
  the run of drafts the target model itself would have produced, plus one
  bonus token, so greedy output is bit-identical to plain decode no matter
  what the drafter proposes.

:class:`NgramDrafter` is the default proposer and needs no second model:
it combines a *self-context* suffix lookup (the request's own
prompt + generated stream — prompt-lookup decoding, exact on the loops
and copy-spans real decodes are full of) with the
:meth:`~mxtpu.serving.kv.PrefixCache.ngram_lookup` side index over the
radix tree's token-id paths (cross-request prompt statistics, LRU with
the tree). The :class:`Drafter` base is the pluggable seam for a small
draft LM from the model zoo later — anything returning token ids fits;
proposals are advisory by construction.

Enable per engine with ``ServingEngine(spec=SpecConfig(k=...))``, the
``ServingConfig.spec`` field, or ``MXTPU_SPEC_DECODE=<k>``; default off
and byte-identical without it. See ``docs/serving.md`` for the turn state
machine and the accept-length diagnosis table.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

__all__ = ["SpecConfig", "parse_spec", "Drafter", "NgramDrafter",
           "ModelDrafter"]


@dataclass(frozen=True)
class SpecConfig:
    """Resolved speculative-decode configuration for one serving engine.

    ``k`` is the draft depth — the verify program scores ``k + 1``
    positions per slot and is keyed on (slots, KV bucket, k), so an engine
    holds ONE ``k`` for its lifetime (no retrace churn). ``ngram`` /
    ``min_ngram`` bound the suffix match the default drafter tries
    (longest first); ``scan`` caps how far back the self-context search
    walks. ``drafter`` swaps in a custom :class:`Drafter` (a draft LM
    seam); None builds an :class:`NgramDrafter` wired to the engine's
    prefix cache."""
    k: int = 4
    ngram: int = 3
    min_ngram: int = 2
    scan: int = 1024
    drafter: Optional["Drafter"] = None

    def __post_init__(self):
        if not 1 <= self.k <= 16:
            raise ValueError(f"spec draft depth k must be in 1..16, "
                             f"got {self.k}")
        if not 1 <= self.min_ngram <= self.ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= ngram, got "
                f"min_ngram={self.min_ngram} ngram={self.ngram}")


def parse_spec(value) -> Optional[SpecConfig]:
    """Parse ``MXTPU_SPEC_DECODE`` / ``ServingEngine(spec=...)``: a
    :class:`SpecConfig` passes through; an int (or int string) is the
    draft depth ``k``; None / '' / 0 disables (the byte-identical
    default). Anything else raises — speculation is never silently off
    when asked for."""
    if value is None or value == "":
        return None
    if isinstance(value, SpecConfig):
        return value
    try:
        k = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"spec must be a SpecConfig or an integer draft depth, "
            f"got {value!r}") from None
    return SpecConfig(k=k) if k > 0 else None


def spec_from_env() -> Optional[SpecConfig]:
    """The environment fallback of the engine's knob resolution chain
    (constructor kwarg > ``ServingConfig.spec`` > ``MXTPU_SPEC_DECODE``)."""
    return parse_spec(os.environ.get("MXTPU_SPEC_DECODE"))


class Drafter:
    """The pluggable proposer seam. ``propose(context, k)`` returns up to
    ``k`` token ids predicted to continue ``context`` (the request's full
    prompt + generated stream, oldest first) — an empty list on a miss.
    Called on the engine's scheduler thread between dispatches, for greedy
    slots only; implementations must be cheap and must not touch jax
    state (a draft *model* belongs behind its own compiled program and
    feeds its tokens back through this same interface)."""

    def propose(self, context: List[int], k: int) -> List[int]:
        raise NotImplementedError

    def stats(self) -> dict:
        """Optional counters merged into the engine's serving stats."""
        return {}


class NgramDrafter(Drafter):
    """Model-free n-gram proposer: self-context suffix lookup first, then
    the :class:`~mxtpu.serving.kv.PrefixCache` radix-tree side index.

    The self-context pass finds the most recent earlier occurrence of the
    stream's final ``n``-gram (``n`` from ``ngram`` down to ``min_ngram``,
    longest match wins, searching at most ``scan`` positions back) and
    proposes the tokens that followed it — exact whenever decode revisits
    a span it has produced or read before. On a miss, the tree's
    ``ngram_lookup`` answers from every cached prompt path, so a slot can
    draft from OTHER requests' prompts before its own stream has any
    statistics. Either source may be absent; both missing is a clean
    ``[]`` (the slot decodes plain this turn)."""

    def __init__(self, prefix_cache=None, ngram: int = 3, min_ngram: int = 2,
                 scan: int = 1024):
        self._prefix = prefix_cache
        self.ngram = int(ngram)
        self.min_ngram = int(min_ngram)
        self.scan = int(scan)

    @classmethod
    def from_config(cls, cfg: SpecConfig, prefix_cache=None):
        return cls(prefix_cache=prefix_cache, ngram=cfg.ngram,
                   min_ngram=cfg.min_ngram, scan=cfg.scan)

    def propose(self, context: List[int], k: int) -> List[int]:
        if k <= 0 or not context:
            return []
        got = self._self_lookup(context, k)
        if got:
            return got
        if self._prefix is not None:
            return self._prefix.ngram_lookup(context[-self.ngram:], k)
        return []

    def _self_lookup(self, context: List[int], k: int) -> List[int]:
        L = len(context)
        for n in range(min(self.ngram, L - 1), self.min_ngram - 1, -1):
            pat = context[L - n:]
            lo = max(0, L - n - self.scan)
            for s in range(L - n - 1, lo - 1, -1):
                if context[s:s + n] == pat:
                    cont = context[s + n:s + n + k]
                    if cont:
                        return list(cont)
        return []


class ModelDrafter(Drafter):
    """Draft-LM proposer behind the :class:`Drafter` seam: a small
    ``transformer_lm`` greedily continues the slot's context and its
    tokens ride the SAME advisory verify contract as the n-gram drafter —
    a weak draft model can slow decode down, never corrupt it.

    The draft model runs its OWN cached decode program (the model zoo's
    ``generate`` path), fully separate from the target engine's program
    caches. To keep that cache bounded, the context is left-truncated to
    the largest fitting bucket of ``buckets`` — at most ``len(buckets)``
    compiled draft programs per draft depth, regardless of how long served
    requests grow. Truncation only costs proposal quality (the verify
    step re-scores everything with the full-context target); a context
    shorter than the smallest bucket proposes nothing and the slot decodes
    plain that turn.

    Pair it with the engine via ``SpecConfig(k=..., drafter=
    ModelDrafter(draft_net))``; ``bench.py serving`` A/Bs it against the
    default :class:`NgramDrafter` on the spec leg."""

    BUCKETS = (8, 32, 64)

    def __init__(self, model, buckets=BUCKETS):
        self._model = model
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"bad draft buckets {buckets!r}")
        self.calls = 0
        self.proposed = 0

    def propose(self, context: List[int], k: int) -> List[int]:
        if k <= 0:
            return []
        b = 0
        for cand in self.buckets:
            if cand <= len(context):
                b = cand
        if b == 0:
            return []
        if b + k > self._model._max_len:
            return []
        import numpy as np
        from .. import nd
        tail = np.asarray(context[-b:], np.int32)[None, :]
        out = self._model.generate(nd.array(tail), k)
        toks = [int(t) for t in np.asarray(out.data)[0, b:]]
        self.calls += 1
        self.proposed += len(toks)
        return toks

    def stats(self) -> dict:
        return {"draft_lm_calls": self.calls,
                "draft_lm_tokens": self.proposed}
