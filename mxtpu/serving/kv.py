"""Bucketed KV-cache admission — the paged-memory half of the serving engine.

The continuous-batching decode loop (engine.py) runs over ONE static
``(L, 2, slots, H, TOT, D)`` KV cache; this module owns every decision about
that array's shape and contents:

* **32-token buckets** — ``TOT`` is always ``bucket32(n)`` of the longest
  admitted request's total length, the same rounding ``TransformerLM
  .generate`` keys its programs on, so the engine and solo decode share
  bucket geometry (and a mixed-length request stream shares a handful of
  compiled programs instead of one per length).
* **Per-slot pages** — each request owns one slot row of the cache
  (``[:, :, s]``); :meth:`TransformerLM.serving_step` scatters strictly
  per-slot, so admission is just "overwrite row s with the prefilled page".
* **Promotion** — when an incoming request's total length outgrows ``TOT``,
  :func:`promote` zero-pads the cache into the next bucket; decode re-keys
  on the new ``TOT`` and compiles at most once per bucket ever seen.
* **Chunked prefill** — long prompts prefill through a separate B=1
  program over their OWN prompt bucket, split into fixed-budget position
  chunks (:func:`build_prefill_chunk`) dispatched BETWEEN decode chunks, so
  admission never stalls the slot batch for more than one chunk's work; the
  finished page is merged into the slot row by :func:`merge_page`. The
  chunk scan body is exactly ``_build_generate``'s body (greedy by default,
  per-request sampling via ``serving_sample``), and the cross-chunk carry is
  just ``(page, prev-token)`` — splitting the scan cannot change a single
  emitted token, which is what keeps engine output bit-exact with solo
  ``generate`` by construction rather than by test luck.
* **Shared-prefix radix reuse** — :class:`PrefixCache` is a
  reference-counted radix/LRU tree over 32-token token-id prefix blocks of
  already-prefilled pages (SGLang-RadixAttention-style). A request whose
  prompt extends a cached prefix copies the cached K/V rows into its page
  and prefills only the suffix: a system prompt shared by N requests costs
  ONE prefill. K/V at position ``p`` depends only on tokens ``0..p``, so an
  exact token match at block granularity guarantees bit-identical rows.

Decode-step semantics (shared with ``generate`` via ``serving_step``):
feeding position ``p`` consumes the token AT ``p``, writes its K/V at ``p``,
and emits the token FOR ``p + 1``. A request with prompt length ``t0`` and
``max_new`` generated tokens spans positions ``0 .. total-1``
(``total = t0 + max_new``); the last position worth feeding is
``total - 2``, so a slot is *live* while ``p < limit`` with
``limit = total - 1``.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..quant import kv_quant as qkv

__all__ = ["bucket32", "cache_dims", "empty_cache", "empty_page", "promote",
           "merge_page", "slot_page", "host_page", "device_page",
           "install_rows", "cache_nbytes", "block_nbytes",
           "build_prefill_chunk", "build_decode", "build_verify",
           "PrefixCache"]


def _kv_mode(quant) -> Optional[str]:
    """KV storage mode of a quant selector: None, a bare mode string, or a
    ``QuantSpec`` (whose ``.kv`` field may itself be None — weight-only
    quantization keeps the cache at the working dtype)."""
    if quant is None or isinstance(quant, str):
        return quant
    return getattr(quant, "kv", None)


def _step_fn(model, S: int, TOT: int, quant, decode_kernel=None):
    """The decode-step builder both compiled programs share: the model's
    own ``serving_step`` on the fp32 path, its quantized twin
    (``mxtpu.quant.serve.build_step``) when a spec is active. Selected at
    BUILD time — the engine holds one spec (and one resolved
    ``decode_kernel``) for life, so program-cache keys stay (slots, bucket,
    chunk) exactly as before and ``MXTPU_DECODE_KERNEL`` flips between
    dispatches can never retrace a live program."""
    if quant is not None and not isinstance(quant, str) \
            and getattr(quant, "enabled", False):
        from ..quant.serve import build_step
        return build_step(model, S, TOT, quant, decode_kernel=decode_kernel)
    return model.serving_step(S, TOT)


def bucket32(n: int, max_len: int) -> int:
    """32-token length bucket, capped at the model's position table."""
    return min(max_len, -(-n // 32) * 32)


def cache_dims(model) -> Tuple[int, int, int]:
    """``(L, H, D)`` of the model's KV cache (layers, heads, head dim)."""
    H = model.blocks[0].attn._heads
    return len(model.blocks), H, model._units // H


def empty_cache(model, slots: int, TOT: int, dtype=jnp.float32, quant=None):
    """The engine cache: a ``dtype`` array, or a quantized
    :class:`~mxtpu.quant.kv_quant.QuantKV` when ``quant`` selects a KV mode
    (``dtype`` then only describes the working precision around it)."""
    L, H, D = cache_dims(model)
    return qkv.empty((L, 2, slots, H, TOT, D), dtype, _kv_mode(quant))


def empty_page(model, PB: int, dtype=jnp.float32, quant=None):
    """A fresh B=1 prefill page ``(L, 2, 1, H, PB, D)`` matching the engine
    cache's storage (same dtype/quant mode, so merge is a pure install)."""
    L, H, D = cache_dims(model)
    return qkv.empty_page(L, H, D, PB, dtype, _kv_mode(quant))


def promote(caches, TOT_new: int):
    """Zero-pad the cache into a bigger TOT bucket (request outgrew its
    page). Positions past the old TOT are unwritten by definition, so the
    pad is content-preserving; per-slot state (p/limit/tok) is untouched."""
    return qkv.promote(caches, TOT_new)


def merge_page(caches, page, slot: int):
    """Install a prefilled ``(L, 2, 1, H, PB, D)`` page as slot row ``slot``
    of the engine cache (zeroing the row's tail past PB — stale K/V from
    the slot's previous tenant must not survive admission)."""
    return qkv.merge_page(caches, page, slot)


def slot_page(caches, slot: int):
    """One slot's ``(L, 2, 1, H, TOT, D)`` page view — the drain() unit."""
    return qkv.slot_page(caches, slot)


def host_page(page):
    """Host-land a page (numpy leaves; quantized pages keep data + scale)
    for a mesh-independent ``ServingHandoff``."""
    return qkv.to_host(page)


def device_page(page):
    return qkv.to_device(page)


def install_rows(page, blocks, m: int):
    """Seed a page's first ``m`` token rows from cached prefix blocks
    (quantized blocks install bit-identical bytes — a shared prefix never
    pays a second quantization)."""
    return qkv.install_rows(page, blocks, m)


def cache_nbytes(caches) -> int:
    """Resident bytes of the cache (data + scales when quantized) — the
    ``kv_bytes_resident`` serving stat."""
    return qkv.cache_nbytes(caches)


def block_nbytes(model, dtype=jnp.float32, quant=None) -> int:
    """Bytes of one 32-token :class:`PrefixCache` block for this model at
    this cache storage (the prefix-cache byte-cap accounting)."""
    L, H, D = cache_dims(model)
    return qkv.page_nbytes(L, H, D, PrefixCache.BLOCK, dtype,
                           _kv_mode(quant))


def build_prefill_chunk(model, PB: int, csize: int, quant=None,
                        decode_kernel=None):
    """One compiled B=1 prefill CHUNK program for (prompt bucket ``PB``,
    chunk size ``csize``): scans :meth:`serving_step` over positions
    ``start .. start+csize-1``, forcing prompt tokens while ``t < t0`` and
    feeding back the sampled/argmax token beyond. The cross-chunk carry is
    exactly the in-scan carry — the partial page plus the previous token —
    so running ``PB/csize`` chunks back to back reproduces the monolithic
    prefill scan token for token. ``start`` rides as a TRACED scalar: every
    chunk of a bucket reuses ONE program, and the engine interleaves these
    dispatches with decode chunks so admission never stalls decode for more
    than one chunk's work (the decode-stall guard contract).

    Returns ``prefill(params, page, prompt (1, PB) int32, t0, start,
    prev (1,) int32, temp (1,) f32, topk (1,) int32, seed (1,) uint32) ->
    (page (L,2,1,H,PB,D), outs (csize,) int32)`` where ``outs[j]`` is the
    token for position ``start + j + 1``; the valid generated tokens of a
    chunk are those with ``start + j >= t0 - 1``. With a prefix-cache hit
    the engine seeds ``page`` with the cached rows and starts the cursor at
    the matched length — only the suffix is ever scanned. Greedy decoding
    is ``temp == 0`` (bit-exact argmax); sampling params are traced, so a
    sampled and a greedy request share this one program. ``quant`` (a
    :class:`~mxtpu.quant.serve.QuantSpec`) swaps in the quantized step —
    the page is then a :class:`QuantKV` and ``params`` come from
    ``quantize_lm``; the scan/carry structure is identical.
    ``decode_kernel`` pins the fused attention-read path (see
    :func:`_step_fn`)."""
    step = _step_fn(model, 1, PB, quant, decode_kernel)
    sample = model.serving_sample()

    def run(params, page, prompt, t0, start, prev, temp, topk, seed):
        def body(carry, j):
            page, prev = carry
            t = start + j
            tok = jnp.where(t < t0, prompt[:, jnp.minimum(t, PB - 1)], prev)
            pos = jnp.full((1,), t, jnp.int32)
            new_page, logits = step(params, page, tok, pos)
            nxt = sample(logits, temp, topk, seed, pos)
            return (new_page, nxt), nxt

        (page, _), outs = lax.scan(body, (page, prev),
                                   jnp.arange(csize, dtype=jnp.int32))
        return page, outs[:, 0]

    return jax.jit(run)


def build_decode(model, S: int, TOT: int, chunk: int, quant=None,
                 decode_kernel=None):
    """One compiled continuous-batching decode program for (slots ``S``,
    KV bucket ``TOT``): ``chunk`` decode steps over the slot batch with all
    per-slot state — token, position, active flag, live limit, and the
    sampling params (temperature/top-k/seed) — riding as TRACED arrays, so
    requests joining/retiring between dispatches AND sampling-mix changes
    never retrace (the compile-guard test pins exactly one trace per
    (S, TOT)).

    Returns ``decode(params, caches, tok, p, active, limit, temp, topk,
    seed) -> (caches, tok, p, toks (chunk, S), lives (chunk, S))``. Per
    inner step a slot is live while ``active & (p < limit)``; dead slots
    freeze (token and position held, their rewrites land only in their own
    already-retired row) and the host consumes ``toks[j, s]`` only where
    ``lives[j, s]``. A slot with ``temp == 0`` decodes greedy argmax —
    bit-exact with solo ``generate`` regardless of what its neighbors
    sample; ``temp > 0`` samples with a key derived from (seed, position),
    so a request's stream is deterministic per seed no matter how it was
    scheduled. ``quant`` swaps in the quantized step (``caches`` is then a
    :class:`QuantKV` pytree riding the same scan carry); ``decode_kernel``
    pins its fused attention-read path (see :func:`_step_fn`)."""
    step = _step_fn(model, S, TOT, quant, decode_kernel)
    sample = model.serving_sample()

    def run(params, caches, tok, p, active, limit, temp, topk, seed):
        def body(carry, _):
            caches, tok, p = carry
            live = active & (p < limit)
            new_caches, logits = step(params, caches, tok, p)
            nxt = sample(logits, temp, topk, seed, p)
            tok2 = jnp.where(live, nxt, tok)
            p2 = jnp.where(live, p + 1, p)
            return (new_caches, tok2, p2), (nxt, live)

        (caches, tok, p), (toks, lives) = lax.scan(
            body, (caches, tok, p), None, length=chunk)
        return caches, tok, p, toks, lives

    return jax.jit(run)


def _verify_step_fn(model, S: int, TOT: int, K1: int, quant,
                    decode_kernel=None):
    """The verify-step builder: ``serving_verify_step`` on the fp32 path,
    its quantized twin when a spec is active (same selection rule as
    :func:`_step_fn`)."""
    if quant is not None and not isinstance(quant, str) \
            and getattr(quant, "enabled", False):
        from ..quant.serve import build_verify_step
        return build_verify_step(model, S, TOT, K1, quant,
                                 decode_kernel=decode_kernel)
    return model.serving_verify_step(S, TOT, K1)


def build_verify(model, S: int, TOT: int, k: int, quant=None,
                 decode_kernel=None):
    """One compiled speculative-decode VERIFY program for (slots ``S``,
    KV bucket ``TOT``, draft depth ``k``): a single batched target forward
    scores all ``k + 1`` positions per slot, then greedy accept/reject
    runs entirely on-device so the host reads back one (tokens, lives)
    pair per dispatch — exactly the plain decode chunk's readback shape,
    transposed (tpulint R009's sanctioned readback).

    Per-slot draft length ``dlen`` rides as a TRACED array: drafter
    misses (``dlen == 0``), sampled slots, and every mixed accept-length
    pattern reuse this ONE program — the trace-once contract extends to
    (S, TOT, k). A ``dlen == 0`` slot degrades to a plain single-position
    decode step inside the same program (its position-0 output is sampled
    with the identical (seed, position) key the decode chunk would use),
    so greedy/sampled mixes never retrace.

    Returns ``verify(params, caches, tok, p, active, limit, temp, topk,
    seed, draft (S, k) int32, dlen (S,) int32) -> (caches, tok, p,
    outs (S, k+1), lives (S, k+1))``. ``outs[s, j]`` is the model's token
    for position ``p[s] + j + 1``; ``lives[s, j]`` marks the emitted
    prefix: position 0 always (the plain-decode token), position ``j``
    while every draft below it matched (``draft[s, i] == outs[s, i]`` for
    ``i < j``) — the emitted run is the accepted drafts plus the one
    bonus token the verifier computed past them, capped at the slot's
    live ``limit``. The accepted prefix's K/V rows were written by the
    forward itself (one append); rejected rows above the accept point are
    dead weight the next dispatch overwrites before anything attends them
    (see :meth:`~mxtpu.gluon.model_zoo.transformer.TransformerLM
    .serving_verify_step`), so rejection "rolls back" by pure host cursor
    arithmetic — int8 KV scales included."""
    K1 = k + 1
    step = _verify_step_fn(model, S, TOT, K1, quant, decode_kernel)
    sample = model.serving_sample()

    def run(params, caches, tok, p, active, limit, temp, topk, seed,
            draft, dlen):
        feeds = jnp.concatenate([tok[:, None], draft], axis=1)  # (S, K1)
        new_caches, logits = step(params, caches, feeds, p)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, K1)
        # position 0 goes through the decode chunk's sampler with the
        # SAME (seed, position) key — a sampled slot (forced dlen=0 by
        # the drafter) emits a bit-identical stream to plain decode
        nxt0 = sample(logits[:, 0], temp, topk, seed, p)
        outs = jnp.concatenate([nxt0[:, None], greedy[:, 1:]], axis=1)
        # greedy accept: draft j proposes the token for position p+j+1;
        # its ground truth is outs[:, j] (valid by induction while every
        # draft below it matched) — cumprod keeps the leading run only
        dl = jnp.where(temp > 0, 0, dlen)       # sampled slots: k = 0
        offs = jnp.arange(k)
        acc = (offs[None, :] < dl[:, None]) & (draft == outs[:, :k])
        chain = jnp.cumprod(acc.astype(jnp.int32), axis=1)
        a = chain.sum(axis=1)                   # accepted draft run
        offs1 = jnp.arange(K1)
        lives = (active[:, None]
                 & (p[:, None] + offs1[None, :] < limit[:, None])
                 & (offs1[None, :] <= a[:, None]))
        e = lives.sum(axis=1).astype(jnp.int32)  # emitted this dispatch
        last = jnp.take_along_axis(outs, jnp.maximum(e - 1, 0)[:, None],
                                   axis=1)[:, 0]
        tok2 = jnp.where(e > 0, last, tok)
        p2 = p + e
        return new_caches, tok2, p2, outs, lives

    return jax.jit(run)


def audit_programs(model, slots: int, TOT: int, chunk: int, k: int,
                   PB: int = 32, csize: int = 16, quant=None):
    """The canonical serving programs plus example arguments, built exactly
    as the engine's ProgramCache sites build them — the program auditor's
    (``python -m mxtpu.analysis --audit``) trace/compile entry points for
    the transfer (A202) and collective-budget (A201) invariants.  Returns
    ``[(name, fn, args), ...]`` where ``fn(*args)`` is dispatchable and
    ``jax.make_jaxpr(fn)(*args)`` is the audited trace; ``name`` matches
    the live ProgramCache name so audit findings read like compile-guard
    counters."""
    params = model._gen_params()
    caches = empty_cache(model, slots, TOT, quant=quant)
    tok = jnp.ones((slots,), jnp.int32)
    p = jnp.zeros((slots,), jnp.int32)
    active = jnp.ones((slots,), jnp.bool_)
    limit = jnp.full((slots,), TOT - 1, jnp.int32)
    temp = jnp.zeros((slots,), jnp.float32)
    topk = jnp.zeros((slots,), jnp.int32)
    seed = jnp.zeros((slots,), jnp.uint32)
    draft = jnp.ones((slots, k), jnp.int32)
    dlen = jnp.full((slots,), k, jnp.int32)
    page = empty_page(model, PB, quant=quant)
    prompt = jnp.ones((1, PB), jnp.int32)
    return [
        ("serving_decode",
         build_decode(model, slots, TOT, chunk, quant=quant),
         (params, caches, tok, p, active, limit, temp, topk, seed)),
        ("serving_verify",
         build_verify(model, slots, TOT, k, quant=quant),
         (params, caches, tok, p, active, limit, temp, topk, seed,
          draft, dlen)),
        ("serving_prefill",
         build_prefill_chunk(model, PB, csize, quant=quant),
         (params, page, prompt, jnp.int32(PB), jnp.int32(0),
          jnp.ones((1,), jnp.int32), jnp.zeros((1,), jnp.float32),
          jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.uint32))),
    ]


# ---------------------------------------------------------------------------
# shared-prefix radix KV reuse (SGLang RadixAttention over bucketed pages)
# ---------------------------------------------------------------------------


class PrefixCache:
    """Reference-counted radix/LRU tree over 32-token prompt-prefix blocks.

    Node identity is the FULL token-id path from the root (a tuple whose
    length is a multiple of :data:`BLOCK`), so a node at depth ``d`` holds
    the K/V rows for absolute positions ``[32(d-1), 32d)`` computed under
    exactly those first ``32d`` prompt tokens — the radix keying makes
    position alignment and content identity one and the same check, and a
    hit is therefore bit-exact by construction. Only FORCED prompt
    positions are ever cached (block end ``<= t0 - 1``): a generated or
    final-prompt position's token feeds the next step, which the suffix
    prefill must compute itself.

    Concurrency/ownership: the tree is engine-owned and scheduler-thread-
    only. :meth:`match` pins every matched node (refcount) so eviction
    can't race the page install; the engine releases the pins once the rows
    are copied into its page (pages are jnp arrays — installs copy, never
    alias, so cached rows are immutable by construction and eviction after
    release is always safe). Capacity is a byte cap (``MXTPU_PREFIX_CACHE_MB``);
    eviction walks LRU order and removes unpinned LEAF nodes only, keeping
    every cached path prefix-closed."""

    BLOCK = 32
    # n-gram side index over the tree's token-id paths (the speculative
    # drafter's read path): suffix n-grams up to NGRAM tokens map to the
    # next NGRAM_CONT tokens observed after them, recency-wins, capped at
    # NGRAM_CAP entries (plain LRU — stale predictions are harmless, the
    # verifier rejects them)
    NGRAM = 3
    NGRAM_CONT = 8
    NGRAM_CAP = 1 << 16

    def __init__(self, block_bytes: int, capacity_mb: float):
        self.block_bytes = int(block_bytes)
        self.capacity_bytes = int(float(capacity_mb) * (1 << 20))
        self.evictions = 0
        self.ngram_hits = 0
        self.ngram_misses = 0
        self._nodes: "OrderedDict[tuple, dict]" = OrderedDict()
        self._ngram: "OrderedDict[tuple, tuple]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def bytes(self) -> int:
        return len(self._nodes) * self.block_bytes

    def match(self, tokens, limit: int) -> Tuple[int, List, tuple]:
        """Longest cached prefix of ``tokens``, capped at position ``limit``
        (exclusive; the engine passes ``t0 - 1`` so the final prompt
        position is always recomputed — its output token seeds the feedback
        chain). Whole 32-token blocks match by radix lookup; past the last
        whole-block match, the children one block deeper are scanned for
        the longest common token run and its leading rows are reused at
        TOKEN granularity (K/V at position ``p`` depends only on tokens
        ``0..p``, so the rows before the first divergent token are
        bit-identical even though the blocks differ beyond it). Returns
        ``(matched_len, kv_blocks, path)`` with every contributing node
        PINNED — including a partially-matched child, whose key is the
        returned ``path`` tail; call :meth:`release(path)` once the rows
        are installed."""
        blocks: List = []
        path: tuple = ()
        m = 0
        while m + self.BLOCK <= limit:
            nxt = path + tuple(tokens[m:m + self.BLOCK])
            node = self._nodes.get(nxt)
            if node is None:
                break
            node["refs"] += 1
            self._nodes.move_to_end(nxt)
            blocks.append(node["kv"])
            path = nxt
            m += self.BLOCK
        # partial-block tail: best token-lcp among the children of `path`
        depth, cap = len(path) + self.BLOCK, min(self.BLOCK, limit - m)
        if cap > 0:
            want = tuple(tokens[m:m + cap])
            best_j, best_key = 0, None
            for key in self._nodes:
                if len(key) != depth or key[:len(path)] != path:
                    continue
                tail = key[len(path):]
                j = 0
                while j < cap and tail[j] == want[j]:
                    j += 1
                if j > best_j:
                    best_j, best_key = j, key
            if best_key is not None:
                node = self._nodes[best_key]
                node["refs"] += 1
                self._nodes.move_to_end(best_key)
                blocks.append(qkv.block_slice(node["kv"], 0, best_j))
                path = best_key
                m += best_j
        return m, blocks, path

    def release(self, path: tuple) -> None:
        """Unpin every node along ``path`` (inverse of :meth:`match`)."""
        for i in range(self.BLOCK, len(path) + 1, self.BLOCK):
            node = self._nodes.get(path[:i])
            if node is not None:
                node["refs"] -= 1

    def insert(self, tokens, page, limit: int) -> int:
        """Cache the prefix blocks of a finished (or partial) prefill:
        block ``b`` slices rows ``[32b, 32b+32)`` off ``page`` for every
        whole block below ``limit``. Existing nodes are kept (identical by
        the radix invariant), so N requests sharing a prefix insert it
        once. Returns the number of freshly created nodes; may evict."""
        created = 0
        path: tuple = ()
        m = 0
        while m + self.BLOCK <= limit:
            nxt = path + tuple(tokens[m:m + self.BLOCK])
            node = self._nodes.get(nxt)
            if node is None:
                node = {"kv": qkv.block_slice(page, m, self.BLOCK),
                        "refs": 0, "children": 0}
                self._nodes[nxt] = node
                if path:
                    self._nodes[path]["children"] += 1
                created += 1
            self._nodes.move_to_end(nxt)
            path = nxt
            m += self.BLOCK
        if created:
            self._evict()
        self._index_ngrams(tokens[:m])
        return created

    # -- n-gram side index (the speculative drafter's read path) ------------
    def _index_ngrams(self, seq) -> None:
        """Index every 1..NGRAM-token window of the freshly cached path
        against its following tokens. Recency wins on collision (the tree
        is LRU; so is its index) and the index is byte-bounded by
        NGRAM_CAP — entries are token-id tuples, never K/V rows."""
        seq = tuple(seq)
        for n in range(1, self.NGRAM + 1):
            for i in range(len(seq) - n):
                cont = seq[i + n:i + n + self.NGRAM_CONT]
                self._ngram[seq[i:i + n]] = cont
                self._ngram.move_to_end(seq[i:i + n])
        while len(self._ngram) > self.NGRAM_CAP:
            self._ngram.popitem(last=False)

    def ngram_lookup(self, suffix, k: int) -> List[int]:
        """Up to ``k`` continuation tokens proposed for ``suffix`` from the
        tree's token-id paths — longest indexed n-gram first (a 3-token
        suffix match beats a 1-token one). Returns ``[]`` on a miss; hits
        and misses are counted (``ngram_hits`` / ``ngram_misses``, surfaced
        through ``get_serving_stats()``). Proposals are advisory: the
        verify pass rejects anything the target model disagrees with, so a
        stale entry costs speculation efficiency, never correctness."""
        suffix = tuple(suffix)
        for n in range(min(self.NGRAM, len(suffix)), 0, -1):
            cont = self._ngram.get(suffix[len(suffix) - n:])
            if cont:
                self._ngram.move_to_end(suffix[len(suffix) - n:])
                self.ngram_hits += 1
                return list(cont[:k])
        self.ngram_misses += 1
        return []

    def _evict(self) -> None:
        while self.bytes > self.capacity_bytes:
            victim: Optional[tuple] = None
            for key, node in self._nodes.items():     # LRU order
                if node["children"] == 0 and node["refs"] == 0:
                    victim = key
                    break
            if victim is None:
                return            # everything pinned or interior: over-cap
            self._nodes.pop(victim)
            parent = victim[:-self.BLOCK]
            if parent in self._nodes:
                self._nodes[parent]["children"] -= 1
            self.evictions += 1
