"""Bucketed KV-cache admission — the paged-memory half of the serving engine.

The continuous-batching decode loop (engine.py) runs over ONE static
``(L, 2, slots, H, TOT, D)`` KV cache; this module owns every decision about
that array's shape and contents:

* **32-token buckets** — ``TOT`` is always ``bucket32(n)`` of the longest
  admitted request's total length, the same rounding ``TransformerLM
  .generate`` keys its programs on, so the engine and solo decode share
  bucket geometry (and a mixed-length request stream shares a handful of
  compiled programs instead of one per length).
* **Per-slot pages** — each request owns one slot row of the cache
  (``[:, :, s]``); :meth:`TransformerLM.serving_step` scatters strictly
  per-slot, so admission is just "overwrite row s with the prefilled page".
* **Promotion** — when an incoming request's total length outgrows ``TOT``,
  :func:`promote` zero-pads the cache into the next bucket; decode re-keys
  on the new ``TOT`` and compiles at most once per bucket ever seen.
* **Prefill/decode split** — long prompts prefill through a separate B=1
  program over their OWN prompt bucket (:func:`build_prefill`) instead of
  stalling the slot batch; the produced page is merged into the slot row by
  :func:`merge_page`. The prefill scan body is exactly ``_build_generate``'s
  greedy body, which is what makes engine output bit-exact with solo
  ``generate`` by construction rather than by test luck.

Decode-step semantics (shared with ``generate`` via ``serving_step``):
feeding position ``p`` consumes the token AT ``p``, writes its K/V at ``p``,
and emits the token FOR ``p + 1``. A request with prompt length ``t0`` and
``max_new`` generated tokens spans positions ``0 .. total-1``
(``total = t0 + max_new``); the last position worth feeding is
``total - 2``, so a slot is *live* while ``p < limit`` with
``limit = total - 1``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["bucket32", "cache_dims", "empty_cache", "promote", "merge_page",
           "build_prefill", "build_decode"]


def bucket32(n: int, max_len: int) -> int:
    """32-token length bucket, capped at the model's position table."""
    return min(max_len, -(-n // 32) * 32)


def cache_dims(model) -> Tuple[int, int, int]:
    """``(L, H, D)`` of the model's KV cache (layers, heads, head dim)."""
    H = model.blocks[0].attn._heads
    return len(model.blocks), H, model._units // H


def empty_cache(model, slots: int, TOT: int, dtype=jnp.float32):
    L, H, D = cache_dims(model)
    return jnp.zeros((L, 2, slots, H, TOT, D), dtype)


def promote(caches, TOT_new: int):
    """Zero-pad the cache into a bigger TOT bucket (request outgrew its
    page). Positions past the old TOT are unwritten by definition, so the
    pad is content-preserving; per-slot state (p/limit/tok) is untouched."""
    L, two, S, H, TOT_old, D = caches.shape
    if TOT_new <= TOT_old:
        return caches
    return jnp.zeros((L, two, S, H, TOT_new, D), caches.dtype) \
        .at[..., :TOT_old, :].set(caches)


def merge_page(caches, page, slot: int):
    """Install a prefilled ``(L, 2, 1, H, PB, D)`` page as slot row ``slot``
    of the engine cache (zeroing the row's tail past PB — stale K/V from
    the slot's previous tenant must not survive admission)."""
    PB = page.shape[4]
    row = jnp.zeros(caches.shape[:2] + caches.shape[3:], caches.dtype) \
        .at[..., :PB, :].set(page[:, :, 0])
    return caches.at[:, :, slot].set(row)


def build_prefill(model, PB: int):
    """One compiled B=1 prefill program for prompt bucket ``PB``: scans
    :meth:`serving_step` over positions ``0..PB-1``, forcing prompt tokens
    while ``t < t0`` and feeding back the greedy argmax beyond — byte-for-
    byte the greedy ``_build_generate`` body, so the page AND the emitted
    tokens match what solo ``generate`` would have produced.

    Returns ``prefill(params, prompt (1, PB) int32, t0) ->
    (page (L,2,1,H,PB,D), outs (PB,) int32)`` where ``outs[t]`` is the
    token for position ``t + 1``; the valid generated tokens are
    ``outs[t0-1 : PB]`` (positions ``t0..PB``), i.e. prefill always hands
    the request its first ``PB - t0 + 1`` tokens at admission — TTFT is
    prefill latency, and a short request may complete without ever
    occupying a decode slot."""
    L, H, D = cache_dims(model)
    step = model.serving_step(1, PB)

    def run(params, prompt, t0):
        page0 = jnp.zeros((L, 2, 1, H, PB, D), params["embed"].dtype)

        def body(carry, t):
            page, prev = carry
            tok = jnp.where(t < t0, prompt[:, jnp.minimum(t, PB - 1)], prev)
            pos = jnp.full((1,), t, jnp.int32)
            new_page, logits = step(params, page, tok, pos)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (new_page, nxt), nxt

        init = (page0, jnp.zeros((1,), jnp.int32))
        (page, _), outs = lax.scan(body, init,
                                   jnp.arange(PB, dtype=jnp.int32))
        return page, outs[:, 0]

    return jax.jit(run)


def build_decode(model, S: int, TOT: int, chunk: int):
    """One compiled continuous-batching decode program for (slots ``S``,
    KV bucket ``TOT``): ``chunk`` greedy steps over the slot batch with all
    per-slot state — token, position, active flag, live limit — riding as
    TRACED arrays, so requests joining/retiring between dispatches never
    retrace (the compile-guard test pins exactly one trace per (S, TOT)).

    Returns ``decode(params, caches, tok, p, active, limit) ->
    (caches, tok, p, toks (chunk, S), lives (chunk, S))``. Per inner step a
    slot is live while ``active & (p < limit)``; dead slots freeze (token
    and position held, their rewrites land only in their own already-
    retired row) and the host consumes ``toks[j, s]`` only where
    ``lives[j, s]``."""
    step = model.serving_step(S, TOT)

    def run(params, caches, tok, p, active, limit):
        def body(carry, _):
            caches, tok, p = carry
            live = active & (p < limit)
            new_caches, logits = step(params, caches, tok, p)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            tok2 = jnp.where(live, nxt, tok)
            p2 = jnp.where(live, p + 1, p)
            return (new_caches, tok2, p2), (nxt, live)

        (caches, tok, p), (toks, lives) = lax.scan(
            body, (caches, tok, p), None, length=chunk)
        return caches, tok, p, toks, lives

    return jax.jit(run)
